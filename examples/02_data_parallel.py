"""Data-parallel training over every visible device.

The replacement for the reference's chief/ps/worker cluster (SURVEY.md
§3.1): no roles, no ClusterSpec — one SPMD program over a named mesh,
gradients all-reduced in-graph over ICI.  Runs on any device count; with
fewer than 2 devices it self-arms an 8-device virtual CPU mesh
(laptop/CI mode — env vars alone are not enough when a site hook pinned
the platform at interpreter start):

    python examples/02_data_parallel.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import jax

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import ensure_virtual_cpu_devices

if __name__ == "__main__":
    if len(jax.devices()) < 2:
        ensure_virtual_cpu_devices(8)
    n = len(jax.devices())
    cfg = RunConfig(
        name=f"lenet_dp{n}", model="lenet5", dataset="mnist",
        batch_size=128 * n, epochs=5, lr=2e-3, dp=n,  # dp=0 also means "all"
    )
    if jax.default_backend() == "cpu":
        # Keep the virtual-mesh demo fast: the N virtual devices time-share
        # the host's cores, so run the MLP on a small split instead of
        # LeNet's convs (same DP machinery, laptop-friendly wall clock).
        import jax.numpy as jnp

        cfg = cfg.replace(
            model="mlp", model_kwargs={"dtype": jnp.float32},
            n_train=8192, n_test=2048, epochs=3,
        )
    summary = Trainer(cfg).fit()
    print(f"\n{n}-way DP: {summary['images_per_sec']:.0f} images/sec total, "
          f"{summary['images_per_sec_per_chip']:.0f} per chip")

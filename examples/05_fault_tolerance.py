"""Preemption-aware training with automatic restart-from-checkpoint,
driven by deterministic fault injection.

The reference's recovery story was K8s pod restart + the chief's
checkpoint (SURVEY.md §5 "Failure detection").  Here it is in-process AND
testable: a seeded FaultPlan (utils/chaos.py) injects a NaN train step and
a torn checkpoint write on a replayable schedule; run_with_recovery
detects the divergence, walks back past the torn step to the newest
INTACT checkpoint (integrity manifests), and replays the original data
schedule — the run finishes as if nothing had happened.  A
PreemptionHandler still turns SIGTERM into checkpoint-and-exit.

    python examples/05_fault_tolerance.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import tempfile

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
    FaultInjector,
    FaultPlan,
    FaultSpec,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
from distributed_tensorflow_ibm_mnist_tpu.utils.elastic import (
    PreemptionHandler,
    run_with_recovery,
)

if __name__ == "__main__":
    cfg = RunConfig(
        name="recoverable", model="lenet5", dataset="mnist",
        n_train=2048, n_test=512,  # CPU-friendly subset: the fault story,
        batch_size=256, epochs=2, lr=2e-3,  # not the accuracy, is the point
        eval_batch_size=512,
        checkpoint_dir=tempfile.mkdtemp(prefix="mnist_ft_"), checkpoint_every=1,
    )
    # A replayable fault schedule: epoch 1's dispatch poisons one param
    # (NaN loss -> TrainingDiverged) and the second save lands torn (the
    # intact-restore walk-back must skip it).  Same seed, same faults,
    # every run.
    chaos = FaultInjector(FaultPlan(seed=0, faults=(
        FaultSpec(site="train-step", kind="nan", at=(1,)),
        FaultSpec(site="checkpoint-write", kind="torn", at=(1,)),
    )))
    with PreemptionHandler() as h:  # SIGTERM/SIGINT -> checkpoint-and-exit
        summary = run_with_recovery(
            lambda: Trainer(cfg, chaos=chaos), max_restarts=3, preemption=h)
    if summary.get("preempted"):
        print("\npreempted at a safe point; resume with the same checkpoint_dir")
    else:
        print(
            f"\nfinished: best accuracy {summary['best_test_accuracy']:.4f} "
            f"after {summary['restarts']} restart(s), "
            f"{chaos.summary()['faults_injected']} fault(s) injected"
        )

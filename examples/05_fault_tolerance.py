"""Preemption-aware training with automatic restart-from-checkpoint.

The reference's recovery story was K8s pod restart + the chief's
checkpoint (SURVEY.md §5 "Failure detection").  Here it is in-process:
run_with_recovery reopens the checkpoint dir after a divergence or crash,
and a PreemptionHandler turns SIGTERM into checkpoint-and-exit.

    python examples/05_fault_tolerance.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import tempfile

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
from distributed_tensorflow_ibm_mnist_tpu.utils.elastic import (
    PreemptionHandler,
    run_with_recovery,
)

if __name__ == "__main__":
    cfg = RunConfig(
        name="recoverable", model="lenet5", dataset="mnist",
        batch_size=512, epochs=3, lr=2e-3,
        checkpoint_dir=tempfile.mkdtemp(prefix="mnist_ft_"), checkpoint_every=1,
    )
    with PreemptionHandler() as h:  # SIGTERM/SIGINT -> checkpoint-and-exit
        summary = run_with_recovery(lambda: Trainer(cfg), max_restarts=2, preemption=h)
    if summary.get("preempted"):
        print(f"\npreempted at a safe point; resume with the same checkpoint_dir")
    else:
        print(f"\nfinished: best accuracy {summary['best_test_accuracy']:.4f}")

"""Config-driven pipeline + expert parallelism — one RunConfig field each.

Round 2 of the rebuild made every parallelism strategy config-driven: this
example trains (a) a ViT whose block stack streams through a GPipe pipeline
(`pp=4`: stage-stacked params sharded over the 'pipe' mesh axis, microbatches
hopping stages via ppermute) and (b) a Mixture-of-Experts ViT whose experts
(and their adam moments) shard over 'data' with all_to_all token dispatch —
wired automatically the moment a MoE model trains at dp>1 (Switch top-1 by
default; `model_kwargs={"moe_top_k": 2}` switches to GShard top-2 routing
with choice-priority capacity filling). Needs 8 devices;
with fewer it self-arms the 8-device virtual CPU mesh:

    python examples/07_pipeline_and_experts.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import jax
import jax.numpy as jnp

if __name__ == "__main__":
    if len(jax.devices()) < 8:
        from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
            ensure_virtual_cpu_devices,
        )

        ensure_virtual_cpu_devices(8)

    from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    # (a) dp=2 x pp=4: eight microbatches per step keep the bubble small
    # (idle fraction = (pp-1)/(m+pp-1) = 3/11 per stage).
    cfg_pp = RunConfig(
        name="vit_pipeline", model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 4, "heads": 2,
                      "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=512, n_test=128,
        batch_size=64, epochs=2, lr=1e-3, dp=2, pp=4, pp_microbatches=8,
        eval_batch_size=128, quiet=True,
    )
    t = Trainer(cfg_pp)
    stacked = t.state.params["pipe_blocks"]["stacked"]
    leaf = jax.tree.leaves(stacked)[0]
    print(f"pipeline: stacked block params {leaf.shape}, sharded {leaf.sharding.spec}")
    s = t.fit()
    print(f"pipeline fit: acc {s['best_test_accuracy']:.3f} "
          f"({s['images_per_sec']:.0f} img/s across {t.n_chips} devices)\n")

    # (b) MoE + dp=8: expert parallelism is automatic — each device OWNS
    # n_experts/dp experts; tokens route via all_to_all over 'data'.
    cfg_moe = RunConfig(
        name="vit_moe_ep", model="vit",
        model_kwargs={"patch_size": 7, "dim": 32, "depth": 2, "heads": 2,
                      "moe_every": 1, "n_experts": 8, "dtype": jnp.float32},
        dataset="mnist", synthetic=True, n_train=512, n_test=128,
        batch_size=64, epochs=2, lr=1e-3, dp=8,
        eval_batch_size=128, quiet=True,
    )
    t = Trainer(cfg_moe)
    w1 = t.state.params["block_0"]["moe"]["w1"]
    print(f"experts: w1 {w1.shape} sharded {w1.sharding.spec} "
          f"({w1.shape[0] // 8} experts owned per device)")
    s = t.fit()
    print(f"moe fit: acc {s['best_test_accuracy']:.3f} "
          f"({s['images_per_sec']:.0f} img/s across {t.n_chips} devices)")

"""Best-of-n sampling with per-token logprobs and streaming (ISSUE 13).

examples/10 serves GREEDY traffic: every replay of a prompt is the same
argmax walk.  This example turns on per-request sampling — each request
carries its own :class:`~distributed_tensorflow_ibm_mnist_tpu.serving.
SamplingParams` ``(temperature, top_p, seed)`` — and shows the three
things the sampling engine guarantees:

* **best-of-n is "same prompt, n seeds"**: the engine decodes n
  stochastic candidates of one prompt concurrently (slot-multiplexed,
  ONE compiled program family — distinct configs are data, not
  recompiles) and returns per-token logprobs
  (``log_softmax(raw logits)[token]`` — the MODEL's distribution before
  temperature shaping, so candidates are scored on a common scale);
  ranking by mean logprob picks the candidate the model itself finds
  most plausible;
* **streaming**: a ``callback(request, token)`` fires once per
  generated token, in order, while the request is still decoding;
* **determinism**: a request's stream is a pure function of its seed —
  resubmitting the winning seed replays its tokens exactly, and an
  explicit ``temperature=0`` request is token-identical to greedy.

    python examples/11_sampling.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    FIFOScheduler,
    InferenceEngine,
    SamplingParams,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

N_CANDIDATES = 6
MAX_NEW = 24


def main():
    # A briefly-trained LM: enough fit that logprob ranking separates
    # plausible continuations from noise (on random weights every
    # candidate scores alike).
    cfg = RunConfig(
        name="lm_sampling", model="causal_lm",
        model_kwargs={"dim": 64, "depth": 1, "heads": 4},
        dataset="retrieval", dataset_kwargs={"vocab": 32, "seq_len": 64},
        n_train=2048, n_test=256, batch_size=128, epochs=2, lr=3e-3,
        eval_every=2, quiet=True,
    )
    with Trainer(cfg) as trainer:
        summary = trainer.fit()
        print(f"trained: test acc {summary['best_test_accuracy']:.3f}")

        engine = InferenceEngine.from_trainer(
            trainer, slots=4, max_len=64,
            scheduler=FIFOScheduler(max_len=64, buckets=(16,),
                                    max_queue=2 * N_CANDIDATES + 4))
        prompt = np.arange(1, 9, dtype=np.int32)

        # --- the greedy reference (no SamplingParams = the engine's
        # defaults, which are greedy here) ---
        greedy = engine.submit(prompt, max_new=MAX_NEW)
        engine.run()
        print(f"greedy   : {list(greedy.generated)}")

        # --- best-of-n: same prompt, n seeds, streamed ---
        streams: dict[int, list[int]] = {}

        def stream(req, token):
            # fires per token WHILE the request decodes; order is the
            # generation order (exactly-once, even across failover)
            streams.setdefault(req.id, []).append(int(token))

        candidates = [
            engine.submit(
                prompt, max_new=MAX_NEW, callback=stream,
                sampling=SamplingParams(temperature=0.9, top_p=0.9,
                                        seed=1000 + s))
            for s in range(N_CANDIDATES)
        ]
        engine.run()

        scored = sorted(
            candidates,
            key=lambda r: float(np.mean(r.logprobs)), reverse=True)
        print(f"\nbest-of-{N_CANDIDATES} over seeds "
              f"(temperature 0.9, top_p 0.9):")
        for rank, r in enumerate(scored):
            mark = " <- best" if rank == 0 else ""
            print(f"  seed {r.sampling.seed}: mean logprob "
                  f"{np.mean(r.logprobs):+.3f}  "
                  f"tokens {list(r.generated)[:10]}...{mark}")
        best = scored[0]
        # the callback saw exactly the retired stream, token for token
        assert streams[best.id] == list(best.generated)
        print(f"streamed == retired for every candidate: "
              f"{all(streams[r.id] == list(r.generated) for r in candidates)}")

        # --- determinism: the winning seed replays token-identically ---
        replay = engine.submit(prompt, max_new=MAX_NEW,
                               sampling=best.sampling)
        # and temperature=0 params are the greedy walk, exactly
        zero_t = engine.submit(prompt, max_new=MAX_NEW,
                               sampling=SamplingParams(temperature=0.0))
        engine.run()
        print(f"replay of seed {best.sampling.seed} identical: "
              f"{list(replay.generated) == list(best.generated)}")
        print(f"temperature=0 == greedy: "
              f"{list(zero_t.generated) == list(greedy.generated)}")

        s = engine.stats.summary()
        print(f"\nserved {s['n_done']} requests: "
              f"{s['n_sampled_requests']} sampled "
              f"(mean temperature {s['mean_temperature']}), "
              f"NLL p50 {s['nll_p50']:.2f} over "
              f"{s['logprob_tokens']} scored tokens")
        engine.close()


if __name__ == "__main__":
    main()

"""ZeRO-1 sharded weight update on the data-parallel path.

The reference all-reduced gradients and then ran the SAME optimizer update
on every worker (SURVEY.md §2.4) — per-worker update FLOPs and optimizer
memory did not shrink as workers were added.  ``sharded_update=True``
applies the cross-replica weight-update sharding recipe (PAPERS.md)
instead: gradients flatten into a few contiguous buckets, each bucket
REDUCE-SCATTERS (each chip keeps its 1/N block), the optimizer updates only
that block against dp-SHARDED adam moments, and the updated param buckets
all-gather.  Same loss trajectory as the replicated update; optimizer
FLOPs and mutable optimizer memory divided by dp.

    python examples/09_sharded_update.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import jax

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import ensure_virtual_cpu_devices

if __name__ == "__main__":
    if len(jax.devices()) < 2:
        ensure_virtual_cpu_devices(8)
    n = len(jax.devices())
    cfg = RunConfig(
        name=f"sharded_update_dp{n}", model="mlp", dataset="mnist",
        batch_size=64 * n, epochs=3, lr=2e-3, dp=n, sharded_update=True,
    )
    if jax.default_backend() == "cpu":
        import jax.numpy as jnp

        cfg = cfg.replace(
            model_kwargs={"hidden": (256,), "dtype": jnp.float32},
            n_train=8192, n_test=2048,
        )
    trainer = Trainer(cfg)
    summary = trainer.fit()

    # show the layout doing its job: adam moments live 1/N per chip
    layout = trainer._dp_sharded.layout
    bucket_leaves = [
        leaf for leaf in jax.tree.leaves(trainer.state.opt_state)
        if getattr(leaf, "ndim", 0) == 1 and leaf.size in set(layout.bucket_sizes)
    ]
    local = sum(next(iter(leaf.addressable_shards)).data.size for leaf in bucket_leaves)
    total = sum(leaf.size for leaf in bucket_leaves)
    print(
        f"\n{n}-way DP with sharded update: "
        f"{summary['images_per_sec']:.0f} images/sec, "
        f"best acc {summary['best_test_accuracy']:.4f}\n"
        f"buckets: {layout.bucket_sizes} ({len(layout.slots)} param leaves "
        f"packed into {layout.n_buckets} reduce-scatters/step)\n"
        f"optimizer moments per chip: {local:,} of {total:,} elements "
        f"(1/{n} — the ZeRO-1 memory split)"
    )

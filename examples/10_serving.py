"""Serve a trained causal LM through the continuous-batching engine.

examples/08 decodes OFFLINE: one ``Trainer.generate`` call per fixed-shape
batch, every row waiting for the slowest row.  This example is the ONLINE
form (ISSUE 2, serving/): requests of different prompt lengths and
generation budgets stream through a slot-multiplexed
:class:`~distributed_tensorflow_ibm_mnist_tpu.serving.InferenceEngine` —
one resident compiled decode program, per-request bucketed prefill, rows
retiring at their own budget (or EOS, or deadline) and freed slots
refilling immediately — with TTFT/latency percentiles, tokens/sec, and
slot occupancy emitted as one ``serving`` JSONL record.

ISSUE 5 knobs shown here: ``decode_ahead=4`` fuses 4 decode steps per
host sync (greedy output is k-invariant; the record's ``n_windows`` /
``window_waste_frac`` show the trade) and ``prefix_cache_bytes`` lets a
repeated prompt skip its prefill entirely (``prefix_hits``).

ISSUE 6: ``tracer=`` records every request as a span tree (queue →
admit/prefill → decode) on its own timeline track; ``export_trace``
writes a file ``chrome://tracing`` / Perfetto loads directly, and
``scripts/trace_report.py`` prints the per-phase latency split.  The
stats record also carries compile accounting (``n_compiled_programs``
by site — docs/OBSERVABILITY.md).

    python examples/10_serving.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
from distributed_tensorflow_ibm_mnist_tpu.serving import FIFOScheduler, InferenceEngine, QueueFull
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
from distributed_tensorflow_ibm_mnist_tpu.utils.metrics import MetricWriter
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer


def main():
    cfg = RunConfig(
        name="lm_serving", model="causal_lm",
        model_kwargs={"dim": 128, "depth": 2, "heads": 4},
        dataset="retrieval", dataset_kwargs={"vocab": 32, "seq_len": 128},
        n_train=4096, n_test=512, batch_size=128, epochs=4, lr=3e-3,
        eval_every=4, quiet=True,
    )
    # Trainer and MetricWriter are context managers (round 6): the metrics
    # file handle is released even if anything below raises.
    with Trainer(cfg) as trainer, MetricWriter(stdout=True) as writer:
        summary = trainer.fit()
        print(f"trained: test acc {summary['best_test_accuracy']:.3f}")

        # The engine serves the SAME clean decode model + device-resident
        # params Trainer.generate uses.  Buckets bound prefill compiles to
        # two shapes; the bounded queue is the backpressure surface.
        tracer = Tracer()  # one clock for the engine AND its scheduler
        engine = InferenceEngine.from_trainer(
            trainer, slots=4, max_len=128, writer=writer,
            decode_ahead=4, prefix_cache_bytes=64 << 20, tracer=tracer,
            scheduler=FIFOScheduler(max_len=128, buckets=(16, 32),
                                    max_queue=32))

        # A mixed request stream: ragged prompts, budgets from 8 to 64 —
        # under static batching every row would pay the 64.
        rng = np.random.default_rng(0)
        repeat = np.arange(1, 9, dtype=np.int32)  # the prefix-cache bait
        for i in range(12):
            prompt = (repeat if i % 4 == 3 else
                      rng.integers(0, 32, size=(int(rng.integers(4, 30)),)))
            engine.submit(prompt.astype(np.int32),
                          max_new=int(rng.choice([8, 16, 64])),
                          deadline_s=30.0)
        try:  # 40 tokens: fits the cache but no prefill bucket holds it
            engine.submit(np.zeros(40, np.int32), max_new=8)
        except ValueError as e:
            print(f"refused: {e}")
        try:
            while True:  # drive the queue into backpressure
                engine.submit(np.arange(1, 5, dtype=np.int32), max_new=8)
        except QueueFull as e:
            print(f"backpressure: {e}")

        done = engine.run()  # emits the 'serving' stats record on drain
        by_len = sorted(done, key=lambda r: len(r.generated))
        for r in (by_len[0], by_len[-1]):
            print(f"request {r.id}: prompt {r.tokens.size} tok -> "
                  f"{len(r.generated)} generated, status {r.status}, "
                  f"ttft {r.first_token_t - r.submit_t:.3f}s")
        s = engine.stats.summary()
        print(f"served {s['n_done']} requests, "
              f"{s['tokens_per_sec']:.0f} tok/s sustained, "
              f"occupancy {s['slot_occupancy']:.2f}")
        print(f"decode-ahead {s['decode_ahead']}: {s['n_windows']} windows "
              f"(waste {s['window_waste_frac']}), prefix cache "
              f"{s['prefix_hits']} hits / {s['prefix_misses']} misses")
        print(f"compiled {s['n_compiled_programs']} XLA programs "
              f"({s['compile_time_s']}s): {s['compile_by_site']}")

        # The timeline: every request above is a span tree on its own
        # track.  Load the file in Perfetto / chrome://tracing, or run
        #   python scripts/trace_report.py /tmp/serving.trace.json
        out = tracer.export_trace("/tmp/serving.trace.json")
        print(f"trace: {out['events']} events -> {out['path']} "
              f"(open spans: {tracer.open_spans})")


if __name__ == "__main__":
    main()

"""The internet-shaped front door: HTTP in, SSE out, elastic capacity (ISSUE 17).

Everything below examples/10 and /11 talked to the tier through Python
calls.  This example puts the :class:`~distributed_tensorflow_ibm_mnist_tpu.
serving.FrontDoor` in front of the daemonized tier — a stdlib-asyncio
HTTP server any ``curl`` can reach — and walks its whole surface:

* **unary** — ``POST /v1/generate`` with a JSON body, tokens back in one
  JSON response;
* **streaming** — the same endpoint with ``"stream": true`` answers
  ``text/event-stream``: one SSE event per token as the daemon's
  delivery thread hands it over (``loop.call_soon_threadsafe`` is the
  only bridge — no polling), a terminal ``event: end`` with the request
  id and status;
* **operations** — ``GET /healthz`` (replica census + the conservation
  invariant) and ``GET /metrics`` (Prometheus text; the front door's
  counters share the daemon's registry so one scrape sees the whole
  tier);
* **elasticity** — an :class:`~distributed_tensorflow_ibm_mnist_tpu.
  serving.Autoscaler` watching the same telemetry scales the tier up
  under backlog (warm respawn through the persistent compile cache) and
  retires — drain first, drop nothing — when traffic recedes.

The tiny untrained LM makes the TOKENS meaningless; what the example
demonstrates is protocol and lifecycle mechanics, which are exactly the
parts that transfer to a real checkpoint.

    JAX_PLATFORMS=cpu python examples/12_frontdoor.py
"""

import sys
import tempfile
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.serving import (
    Autoscaler,
    FIFOScheduler,
    FrontDoor,
    FrontDoorClient,
    InferenceEngine,
    Router,
    ServingDaemon,
)

VOCAB = 16
MAX_LEN = 16


def main():
    model = get_model("causal_lm", num_classes=VOCAB, dim=32, depth=1,
                      heads=2, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    # the persistent compile cache is what makes the autoscaler's
    # respawns warm: replica 0's prewarm populates it, every later
    # spawn reads it back instead of recompiling
    cache_dir = tempfile.mkdtemp(prefix="dtm_frontdoor_xc_")

    def make_engine(tid):
        return InferenceEngine(
            model, params, slots=2, max_len=MAX_LEN, kv_page_size=4,
            scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=(8,),
                                    max_queue=64),
            trace_tid=tid, compile_cache_dir=cache_dir)

    router = Router(make_engine, 1)
    router.prewarm()
    daemon = ServingDaemon(router, max_queue=64,
                           liveness_timeout_s=30.0).start()
    fd = FrontDoor(daemon).start_in_thread()
    print(f"front door listening on http://127.0.0.1:{fd.port}")
    print("the curl equivalents of everything below:")
    print(f"  curl -s http://127.0.0.1:{fd.port}/healthz")
    print(f"  curl -s http://127.0.0.1:{fd.port}/metrics")
    print(f"  curl -s -X POST http://127.0.0.1:{fd.port}/v1/generate "
          "-d '{\"prompt\": [1, 2, 3], \"max_new\": 4}'")
    print(f"  curl -sN -X POST http://127.0.0.1:{fd.port}/v1/generate "
          "-d '{\"prompt\": [1, 2, 3], \"max_new\": 4, \"stream\": true}'")

    cli = FrontDoorClient("127.0.0.1", fd.port)
    try:
        # -- unary ------------------------------------------------------
        body = cli.generate([1, 2, 3], 4)
        print(f"\nunary:     HTTP {cli.last_status} -> "
              f"tokens {body['tokens']} (request {body['id']})")

        # -- streaming: tokens arrive one SSE event at a time -----------
        got = []
        for tok in cli.stream([1, 2, 3], 4,
                              sampling={"temperature": 0.8, "seed": 7}):
            got.append(tok)
        term = cli.last_terminal
        print(f"streaming: {len(got)} SSE events {got}, "
              f"terminal status {term['status']!r}")

        # -- operations -------------------------------------------------
        hz = cli.healthz()
        print(f"healthz:   {hz['status']} — "
              f"{hz['healthy']}/{hz['n_replicas']} replicas healthy, "
              f"conservation "
              f"{'holds' if hz['conservation']['conserved'] else 'BROKEN'}")
        scrape = [ln for ln in cli.metrics().splitlines()
                  if "frontdoor_requests" in ln and not ln.startswith("#")]
        print(f"metrics:   {scrape[0]} (one scrape covers daemon + door)")

        # -- elasticity: backlog scales up, idleness retires ------------
        asc = Autoscaler(daemon, min_replicas=1, max_replicas=2,
                         up_backlog_per_slot=1.0, down_occupancy=0.5,
                         hysteresis_up=1, hysteresis_down=2)
        rng = np.random.default_rng(3)
        burst = [threading.Thread(
            target=cli_burst, args=(fd.port, rng.integers(1, VOCAB, 4)))
            for _ in range(10)]
        for th in burst:
            th.start()
        while not any(e["action"] == "up" for e in asc.events):
            asc.tick()
        up = asc.events[-1]
        print(f"\nburst of {len(burst)} streams -> scale-UP: replica "
              f"{up['replica']} spawned in {up['spawn_s']:.2f}s "
              f"({'warm restart' if up['warm'] else 'fresh spawn, compile-cache-warmed'}), "
              f"backlog/slot was "
              f"{up['signals']['backlog_per_slot']:.2f}")
        for th in burst:
            th.join()
        while not any(e["action"] == "down" for e in asc.events):
            asc.tick()
        print(f"traffic gone -> scale-DOWN: replica "
              f"{asc.events[-1]['replica']} drained and retired "
              f"(zero drops is the retire contract)")
        print(f"autoscaler: {asc.summary()}")
    finally:
        fd.stop()
        daemon.drain(timeout=30.0)
        daemon.close()
    print("\nfront door closed, tier drained clean")


def cli_burst(port, prompt):
    c = FrontDoorClient("127.0.0.1", port)
    list(c.stream(prompt, 5))


if __name__ == "__main__":
    main()

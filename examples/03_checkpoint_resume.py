"""Checkpoint, crash, resume — the MonitoredTrainingSession Saver story.

The reference's one real aux subsystem (SURVEY.md §5): the chief's Saver
hook wrote checkpoints and a restarted job resumed from the same dir.
Here that is explicit and layout-agnostic: the checkpoint round-trips
across device counts (save from a DP run, resume single-chip, or vice
versa).

    python examples/03_checkpoint_resume.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import tempfile

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

if __name__ == "__main__":
    ckpt_dir = tempfile.mkdtemp(prefix="mnist_ckpt_")
    cfg = RunConfig(
        name="resumable", model="lenet5", dataset="mnist",
        batch_size=512, epochs=2, lr=2e-3,
        checkpoint_dir=ckpt_dir, checkpoint_every=1,
    )

    print("--- first run (2 epochs, checkpointing) ---")
    Trainer(cfg).fit()

    print("--- resumed run (2 more epochs from the same dir) ---")
    t = Trainer(cfg.replace(resume=True))
    summary = t.fit()
    print(f"\nfinal step {int(t.state.step)} "
          f"(resumed past the first run's {2 * t.steps_per_epoch})")

"""Train a long-context retrieval transformer with causal flash attention.

The long-context path end to end: a decoder-style transformer stack
(`models/transformer.py::TransformerBlock` with the streaming Pallas
flash kernel as its ``attn_fn``, ``causal=True``, O(tile) VMEM — S=32k
fits one v5e chip) trained on a task that is IMPOSSIBLE without
long-range attention: token 0 is a random key, every other input token
is noise, and the label at position t is ``(key + t) mod V``.  A model
that cannot attend ~1000 positions back to token 0 is stuck at the
uniform -log(1/V) loss floor; the causal flash kernel drives it to ~0.
On a multi-device mesh, swap the attention for
``make_ring_attention(mesh, causal=True, inner="flash")`` or
``make_ulysses_attention(...)`` — the same drop-in ``attn_fn`` slot.

This walkthrough builds the net by hand to show the pieces; the same task
is one config away since round 2 (causal derives from the family since
round 3 — and RoPE positions and grouped-query attention are each one
model_kwargs entry; a sliding ``window`` also exists, but would defeat
THIS task: the key lives at position 0, which is the point)::

    RunConfig(model="causal_lm", dataset="retrieval",
              dataset_kwargs={"vocab": 64, "seq_len": 1024},
              model_kwargs={"attn": "flash", "heads_kv": 2})

    python examples/06_causal_lm_long_context.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import time
from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_ibm_mnist_tpu.models.transformer import TransformerBlock
from distributed_tensorflow_ibm_mnist_tpu.ops.flash_attention import flash_attention

VOCAB, SEQ, DIM, HEADS, DEPTH = 64, 1024, 128, 4, 2
BATCH, STEPS = 16, 1500  # the attend-to-key head emerges around step ~500


class RetrievalLM(nn.Module):
    @nn.compact
    def __call__(self, tokens, train: bool = False):
        x = nn.Embed(VOCAB, DIM, dtype=jnp.bfloat16)(tokens)
        pos = self.param("pos", nn.initializers.normal(0.02), (1, SEQ, DIM))
        x = x + pos.astype(jnp.bfloat16)
        attn = partial(flash_attention, causal=True)
        for i in range(DEPTH):
            x = TransformerBlock(
                dim=DIM, heads=HEADS, attn_fn=attn, name=f"block_{i}"
            )(x, train=train)
        x = nn.LayerNorm(dtype=jnp.bfloat16)(x)
        return nn.Dense(VOCAB, dtype=jnp.bfloat16, name="logits")(x).astype(jnp.float32)


def make_batch(rng: np.random.Generator):
    """tokens: [key, noise, noise, ...]; labels[t] = (key + t) mod V."""
    key = rng.integers(0, VOCAB, (BATCH, 1))
    noise = rng.integers(0, VOCAB, (BATCH, SEQ - 1))
    tokens = np.concatenate([key, noise], axis=1).astype(np.int32)
    labels = ((key + np.arange(SEQ)[None, :]) % VOCAB).astype(np.int32)
    return jnp.asarray(tokens), jnp.asarray(labels)


if __name__ == "__main__":
    model = RetrievalLM()
    rng = np.random.default_rng(0)
    tokens, labels = make_batch(rng)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    tx = optax.adam(optax.warmup_cosine_decay_schedule(0.0, 5e-3, 50, STEPS))
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, tokens, labels):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt2, loss

    print(f"retrieval LM: vocab {VOCAB}, seq {SEQ}, {DEPTH} blocks, causal flash attention")
    print(f"no-attention models are stuck at the {np.log(VOCAB):.3f} uniform loss floor")
    # warm the compile outside the timed region (repo convention, bench.py)
    params, opt, loss = step(params, opt, tokens, labels)
    jax.device_get(loss)
    t0 = time.perf_counter()
    for i in range(STEPS):
        params, opt, loss = step(params, opt, *make_batch(rng))
        if (i + 1) % 300 == 0:
            print(f"step {i+1}: loss {float(jax.device_get(loss)):.4f}")
    wall = time.perf_counter() - t0
    tok_s = STEPS * BATCH * SEQ / wall
    final = float(jax.device_get(loss))
    verdict = (
        "<< floor: every position retrieved the key from ~1000 tokens back"
        if final < 1.0 else "still descending"
    )
    print(f"\n{tok_s/1e3:.0f}k tokens/sec (excl compile); final loss {final:.3f} ({verdict})")

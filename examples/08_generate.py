"""Train the config-driven causal LM, then decode from it (KV cache).

The reference was a trainer only (SURVEY.md §2.1); this example shows the
round-3 inference surface: ``Trainer.fit`` -> ``Trainer.generate``, backed
by ``core/generate.py`` — prefill + a ``lax.scan`` of single-token steps
compiled into ONE program, with per-block K/V caches appended in place and
RoPE rotating each token at its absolute position.  Because positions are
rotary (the family default), the decode runs PAST the trained sequence
length — the same property that lets ring attention scale context across
chips at train time.

    python examples/08_generate.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig


def main():
    # The retrieval task: token 0 is a key, labels are (key + t) mod V —
    # learnable only by attending back to position 0.
    cfg = RunConfig(
        name="lm_generate", model="causal_lm",
        model_kwargs={"dim": 128, "depth": 2, "heads": 4},
        dataset="retrieval", dataset_kwargs={"vocab": 32, "seq_len": 128},
        n_train=4096, n_test=512, batch_size=128, epochs=6, lr=3e-3,
        eval_every=6,
    )
    trainer = Trainer(cfg)
    summary = trainer.fit()
    print(f"trained: loss floor {np.log(32):.2f} -> "
          f"{trainer.history[-1]['train_loss']:.2f}, "
          f"test acc {summary['best_test_accuracy']:.3f}")

    # Greedy decode from a fresh prompt — and PAST the trained length
    # (trained at S=128, decoded to 160: learned positions can't do this).
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, 32, size=(2, 8)), jnp.int32)
    out = trainer.generate(prompt, max_new=152)
    print(f"prompt {prompt.shape} -> generated {out.shape}")
    print("first generated row:", np.asarray(out[0, 8:24]))

    # Sampled decode: temperature + nucleus/top-k filters + rng
    import jax

    sampled = trainer.generate(prompt, max_new=16, temperature=0.8,
                               top_p=0.9, top_k=8,
                               rng=jax.random.PRNGKey(0))
    print("sampled row:       ", np.asarray(sampled[0, 8:24]))

    # Production decode semantics (round 4): a ragged right-padded batch
    # — each row decodes from ITS OWN length — with an EOS stop token
    # (per-row freeze, early loop exit) and pad filling afterwards.
    # Repeat calls with the same shapes hit the Trainer's generator cache
    # and the device-resident params: no re-jit, no host round-trip.
    ragged = jnp.zeros((2, 8), jnp.int32)
    ragged = ragged.at[0, :8].set(prompt[0]).at[1, :3].set(prompt[1, :3])
    out = trainer.generate(ragged, max_new=16, eos_id=2, pad_id=0,
                           prompt_lens=jnp.asarray([8, 3], jnp.int32))
    print("ragged row 0 (len 8):", np.asarray(out[0]))
    print("ragged row 1 (len 3):", np.asarray(out[1]))

    # with_lengths=True returns each row's REAL generated length (EOS
    # included) — the reliable recovery handle when pad_id can also be
    # sampled as an ordinary token (round 5).
    out, lens = trainer.generate(ragged, max_new=16, eos_id=2, pad_id=0,
                                 prompt_lens=jnp.asarray([8, 3], jnp.int32),
                                 with_lengths=True)
    print("generated lengths:", np.asarray(lens))

    # int8 KV cache (round 5): halve the decode cache's HBM stream with a
    # tested logit-drift bound — a RunConfig knob, everything else equal:
    #   RunConfig(..., model_kwargs={..., "kv_cache_dtype": "int8"})


if __name__ == "__main__":
    main()

"""Compose DP x TP x SP on a ViT — the scale-out machinery.

Everything the reference could not do: Megatron-style tensor parallelism
(GSPMD PartitionSpecs over the 'model' axis), ring attention over the
'seq' axis, batch over 'data' — one jitted train step, shardings only.
Needs 8 devices; with fewer it self-arms an 8-device virtual CPU mesh
(env vars alone are not enough when a site hook pinned the platform at
interpreter start):

    python examples/04_scale_out_vit.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_ibm_mnist_tpu.core.state import TrainState
from distributed_tensorflow_ibm_mnist_tpu.models import get_model
from distributed_tensorflow_ibm_mnist_tpu.parallel import make_mesh
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import make_ring_attention
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    make_param_specs, make_tp_train_step, megatron_dense_rule, shard_train_state,
)

if __name__ == "__main__":
    if len(jax.devices()) < 8:
        from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
            ensure_virtual_cpu_devices,
        )

        ensure_virtual_cpu_devices(8)
    mesh = make_mesh(dp=2, tp=2, sp=2)  # needs 8 devices
    vit = get_model(
        "vit", patch_size=7, dim=64, depth=4, heads=4,
        attn_fn=make_ring_attention(mesh),
    )
    tx = optax.adamw(1e-3)
    state = TrainState.create(vit, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8))
    specs = make_param_specs(state.params, megatron_dense_rule())
    step = make_tp_train_step(vit, tx, mesh, specs, state)
    state = shard_train_state(mesh, state, specs)

    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(rng.integers(0, 255, (16, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, (16,)).astype(np.int32)),
    }
    for i in range(5):
        state, metrics = step(state, batch)
        print(f"step {i}: loss {float(metrics['loss']):.4f}")
    print("\nDP x TP x SP ViT step ran on", mesh)

"""Train the reference's MNIST CNN on one TPU chip.

The one-chip analog of the reference's local single-process run
(SURVEY.md §3.3): build a config, train LeNet-5 to 99% test accuracy,
print the metrics of record.

    python examples/01_train_single_chip.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo-root import without install

from distributed_tensorflow_ibm_mnist_tpu.core import Trainer
from distributed_tensorflow_ibm_mnist_tpu.utils.config import get_preset

if __name__ == "__main__":
    cfg = get_preset("mnist_lenet_1chip").replace(
        batch_size=1024, lr=4e-3, schedule="cosine",
        epochs=15, target_accuracy=0.99,  # early-stops at 99%
    )
    summary = Trainer(cfg).fit()
    ttt = summary["time_to_target_s"]
    reached = f"reached 99% in {ttt}s" if ttt else (
        f"did not reach 99% in {summary['epochs_run']} epochs")
    print(f"\nbest test accuracy {summary['best_test_accuracy']:.4f}; {reached} "
          f"({summary['images_per_sec_per_chip']:.0f} images/sec/chip)")

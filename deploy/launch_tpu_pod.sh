#!/usr/bin/env bash
# TPU-VM pod-slice launcher — the reference's deploy layer, TPU-native.
#
# The reference launched training by building a Docker image, pushing it to
# IBM Cloud Container Registry, and kubectl-applying per-role (chief/ps/
# worker) Jobs + Services (SURVEY.md §2.1 rows "Dockerfile" / "K8s
# manifests" / "Submit scripts", §3.5 call stack).  SPMD on TPU needs none
# of that role choreography: every host of a pod slice runs the SAME
# command; jax.distributed.initialize() discovers peers from TPU metadata
# (launch/tpu_vm.py), and the mesh + collectives do the rest.
#
# Usage:
#   ./deploy/launch_tpu_pod.sh <tpu-name> <zone> [--preset mnist_cnn_dp8 ...]
#
# Everything after zone is passed through to the training CLI.

set -euo pipefail

TPU_NAME="${1:?usage: launch_tpu_pod.sh <tpu-name> <zone> [cli args...]}"
ZONE="${2:?usage: launch_tpu_pod.sh <tpu-name> <zone> [cli args...]}"
shift 2
CLI_ARGS=("$@")

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
PKG="distributed_tensorflow_ibm_mnist_tpu"

# 1. Ship the framework to every host of the slice (rsync over gcloud ssh).
gcloud compute tpus tpu-vm scp --recurse \
  "${REPO_ROOT}/${PKG}" "${REPO_ROOT}/native" "${REPO_ROOT}/pyproject.toml" \
  "${TPU_NAME}:~/app/" --zone="${ZONE}" --worker=all

# 2. Start the identical SPMD process on every host.  No role flags, no
#    ClusterSpec: TPU metadata gives each process its slice coordinates.
gcloud compute tpus tpu-vm ssh "${TPU_NAME}" --zone="${ZONE}" --worker=all \
  --command="cd ~/app && python -m ${PKG}.launch.cli ${CLI_ARGS[*]}"

"""Speculative decoding vs plain decode-ahead on a repetitive-suffix stream.

The ``speculative`` comparison block for bench.py (ISSUE 9): the SAME
stream of repetitive-suffix requests — prompts built from a repeated
motif, the workload prompt-lookup drafting exists for (retrieved context
quoted back, boilerplate, code idioms) — is served twice by engines
sharing one model:

* **plain** — the decode-ahead engine at ``k = draft_len + 1``: every
  window runs k SEQUENTIAL fused decode steps and emits k tokens (same
  window length, same host-sync cadence — the apples-to-apples baseline);
* **spec**  — ``speculative="ngram"``: the host drafts up to ``draft_len``
  tokens per slot from the request's own token stream, ONE
  (slots, k)-position verify forward accepts the longest greedy-matching
  prefix + one correction token, and the KV cursor rewinds to the
  acceptance point.

Why spec can win at identical emitted tokens: a k-position forward is ONE
pass over the weights (position-batched matmuls) where the decode-ahead
scan makes k sequential single-position passes — on memory-bound decode
that is ~k weight reads vs ~1.  Every accepted draft token converts that
cheaper forward into MORE than one emitted token; every rejected lane
wastes a verify position but never emits a wrong token.

The comparison is HONEST the same way the serving bench is: both legs
must produce token-for-token identical greedy output — any mismatch NULLS
the reported speedup and the script exits nonzero (status 4), so a
speedup bought with different tokens can never be reported.  A
``low_repetition`` control leg (i.i.d. random prompts) is measured
alongside: its accept rate collapses and its speedup hovers near (often
below) 1x, which is the documented floor, not a failure.

Designed to run in a SUBPROCESS (bench.py spawns it with
``JAX_PLATFORMS=cpu``; ``DTM_BENCH_SKIP_SPEC=1`` skips the phase) and
self-arms when run directly:

    python scripts/bench_speculative.py [--requests 16] [--slots 4]

Prints ONE JSON line (``"metric": "speculative"``).
``DTM_BENCH_QUICK=1`` shrinks the stream for CI smoke runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

# the regime speculative decoding targets: per-position decode COMPUTE
# dominates the host loop — one k-position verify forward makes ONE pass
# over the weights where the decode-ahead scan makes k, and on this host
# class the k=8 window-vs-verify cost ratio only clears ~2x from dim-320
# depth-6 up (measured: 1.2x at dim-96, 1.3x at dim-192, 2.0x at
# dim-320).  The dispatch-taxed dim-32 toy regime belongs to the
# decode-ahead leg of bench_serving.py, not here; QUICK trades headroom
# for runtime and may land under target (the record says so).
DIM, DEPTH, HEADS, VOCAB = (192, 4, 8, 32) if QUICK else (320, 6, 8, 32)
BUCKET = 32
# long enough that the steady periodic phase (where prompt-lookup locks
# onto the generated cycle and accepts whole drafts) amortizes the first
# windows' transient, where the model is still diverging from the prompt
# motif and drafts mostly miss
MAX_NEW = 48 if QUICK else 64
DRAFT_LEN = 7  # k = 8 verify positions per window


def make_stream(n_requests: int, seed: int, repetitive: bool):
    """``repetitive``: each prompt is a short random motif tiled to the
    bucket — the suffix n-gram always has a prior occurrence, so
    prompt-lookup drafts the motif's continuation (and, once generation
    falls into the model's greedy attractor, its own recent output).
    Otherwise: i.i.d. random prompts — the low-repetition control."""
    rng = np.random.default_rng(seed)
    stream = []
    for _ in range(n_requests):
        if repetitive:
            motif = rng.integers(1, VOCAB - 1,
                                 size=(int(rng.integers(4, 9)),))
            reps = int(np.ceil(28 / motif.size))
            prompt = np.tile(motif, reps)[:28].astype(np.int32)
        else:
            n = int(rng.integers(16, 29))
            prompt = rng.integers(1, VOCAB - 1, size=(n,)).astype(np.int32)
        stream.append((prompt, MAX_NEW))
    return stream


def serve(model, params, stream, slots: int, max_len: int, warm, **kw):
    """One engine, warmed outside the timed region, then the stream timed.
    Returns (elapsed_s, per-request outputs, per-request decode latency
    mean, stats summary)."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        ServingStats,
    )

    eng = InferenceEngine(
        model, params, slots=slots, max_len=max_len,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                max_queue=max(len(stream), len(warm))),
        **kw)
    for p, mn in warm:
        eng.submit(p, max_new=mn)
    eng.run()
    eng.completed.clear()
    eng.stats = ServingStats(slots, decode_ahead=eng.decode_ahead)
    t0 = time.perf_counter()
    reqs = [eng.submit(p, max_new=mn) for p, mn in stream]
    eng.run()
    elapsed = time.perf_counter() - t0
    outs = [np.asarray(r.generated) for r in reqs]
    # per-request decode latency: first token to retirement (prefill and
    # queue wait excluded — the window loop is what speculation changes)
    decode_lat = float(np.mean([r.finish_t - r.first_token_t for r in reqs]))
    summ = eng.stats.summary()
    eng.close()
    return elapsed, outs, decode_lat, summ


def run_pair(model, params, stream, warm, slots: int, max_len: int) -> dict:
    """plain decode-ahead (k = DRAFT_LEN+1) vs speculative on one stream;
    refuses to report a speedup over mismatched output."""
    k = DRAFT_LEN + 1
    pl_s, pl_out, pl_lat, pl_summ = serve(
        model, params, stream, slots, max_len, warm, decode_ahead=k)
    sp_s, sp_out, sp_lat, sp_summ = serve(
        model, params, stream, slots, max_len, warm,
        speculative="ngram", draft_len=DRAFT_LEN)
    mismatches = sum(not np.array_equal(a, b)
                     for a, b in zip(pl_out, sp_out))
    useful = sum(o.size for o in pl_out)
    speedup = (useful / sp_s) / (useful / pl_s)
    lat_ratio = pl_lat / sp_lat if sp_lat > 0 else None
    return {
        "n_requests": len(stream),
        "useful_tokens": useful,
        "output_mismatches": mismatches,  # MUST be 0 (greedy parity)
        "plain": {
            "decode_ahead": k,
            "elapsed_s": round(pl_s, 4),
            "tokens_per_sec": round(useful / pl_s, 2),
            "decode_latency_s_mean": round(pl_lat, 4),
            "n_windows": pl_summ["n_windows"],
            "useful_tokens_per_window": pl_summ["useful_tokens_per_window"],
        },
        "spec": {
            "draft_len": DRAFT_LEN,
            "elapsed_s": round(sp_s, 4),
            "tokens_per_sec": round(useful / sp_s, 2),
            "decode_latency_s_mean": round(sp_lat, 4),
            "n_windows": sp_summ["n_windows"],
            "useful_tokens_per_window": sp_summ["useful_tokens_per_window"],
            "drafted_tokens": sp_summ["drafted_tokens"],
            "accepted_tokens": sp_summ["accepted_tokens"],
            "accept_rate": sp_summ["accept_rate"],
        },
        # the headline: sustained useful tokens/sec, spec over plain, on
        # IDENTICAL output — nulled on any mismatch
        "speedup": None if mismatches else round(speedup, 3),
        "decode_latency_ratio": (
            None if mismatches or lat_ratio is None else round(lat_ratio, 3)),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12 if QUICK else 16)
    ap.add_argument("--slots", type=int, default=4)
    args = ap.parse_args()

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    max_len = BUCKET + MAX_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM, depth=DEPTH,
                      heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    warm = make_stream(max(args.slots, 4), seed=1, repetitive=True)

    rep = run_pair(model, params,
                   make_stream(args.requests, seed=2, repetitive=True),
                   warm, args.slots, max_len)
    # low-repetition control: accept rate collapses, speedup ~1x or below
    # — measured and reported, never averaged into the headline
    low = run_pair(model, params,
                   make_stream(max(args.requests // 2, 4), seed=3,
                               repetitive=False),
                   warm, args.slots, max_len)

    result = {
        "metric": "speculative",
        "model": {"dim": DIM, "depth": DEPTH, "heads": HEADS,
                  "vocab": VOCAB},
        "slots": args.slots,
        "max_new": MAX_NEW,
        "draft_len": DRAFT_LEN,
        "repetitive": rep,
        "low_repetition": low,
        "speedup": rep["speedup"],
        "target_speedup": 1.3,
        "meets_target": (rep["speedup"] is not None
                         and (rep["speedup"] >= 1.3
                              or (rep["decode_latency_ratio"] or 0) >= 1.3)),
        "quick": QUICK,
        "device": str(jax.devices()[0]),
        "note": (
            "speedup is spec-over-plain useful tokens/sec at identical "
            "greedy output (mismatches null it; exit 4); the "
            "low_repetition control documents the honest floor — without "
            "repeated suffixes prompt-lookup accepts little and spec "
            "pays its verify overhead for ~nothing"
        ),
    }
    print(json.dumps(result), flush=True)
    if rep["output_mismatches"] or low["output_mismatches"]:
        print(f"speculative parity BREACH: repetitive="
              f"{rep['output_mismatches']} low={low['output_mismatches']} "
              f"mismatched request(s) — speculative output must be "
              f"token-identical to plain greedy decode", file=sys.stderr)
        return 4
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Measure the BASELINE.md configs on the attached hardware.

Runs a named preset in its single-chip form (dp forced to 1 — multi-chip
hardware isn't attached in this environment; the dp>1 layouts are validated
on the virtual mesh and by the driver's dryrun) and prints ONE JSON line
with the BASELINE.json:2 metrics of record: steady-state images/sec/chip
(+ MFU) via ``Trainer.measure_throughput`` and wall-clock-to-target via
``Trainer.fit``.

Usage:
    python scripts/measure_baselines.py <preset> [throughput_epochs]
"""

from __future__ import annotations

import json
import os
import sys
import time

# runnable from anywhere: the package lives at the repo root, one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    preset = sys.argv[1]
    tput_epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    import jax

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import get_preset

    cfg = get_preset(preset)
    name = cfg.name + ("_1chip" if cfg.dp > 1 else "")
    cfg = cfg.replace(name=name, dp=1, quiet=True)
    trainer = Trainer(cfg)

    tput = trainer.measure_throughput(epochs=tput_epochs)
    trainer.evaluate()  # warm the eval compile outside the timed fit
    t0 = time.perf_counter()
    summary = trainer.fit()
    fit_wall = time.perf_counter() - t0

    print(json.dumps({
        "preset": preset,
        "name": name,
        "dataset": cfg.dataset,
        "synthetic_data": trainer.data_synthetic,  # as RESOLVED by the loader
        "batch_size": cfg.batch_size,
        "images_per_sec_per_chip": tput["images_per_sec_per_chip"],
        "mfu": tput["mfu"],
        "model_tflops_per_sec_per_chip": tput["model_tflops_per_sec_per_chip"],
        "compile_and_first_epoch_s": tput["compile_and_first_epoch_s"],
        "best_test_accuracy": summary["best_test_accuracy"],
        "target_accuracy": cfg.target_accuracy,
        "time_to_target_s": summary["time_to_target_s"],
        "fit_wall_s_excl_compile": round(fit_wall, 3),
        "epochs_run": summary["epochs_run"],
        "param_count": summary["param_count"],
        "device": tput["device"],
    }), flush=True)


if __name__ == "__main__":
    main()

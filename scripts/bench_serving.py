"""Continuous batching vs static one-shot batching on a mixed request stream.

The `serving` comparison block for bench.py's MULTICHIP-style section: the
SAME mixed-length synthetic request stream (short and long generation
budgets interleaved, ragged prompt lengths) is served twice —

* **static** — the pre-ISSUE-2 baseline: FIFO batches of `slots` requests
  through one compiled ``make_generator`` episode per batch; every row
  pays the batch's LONGEST ``max_new`` (head-of-line blocking), and only
  each request's own budget counts as useful output;
* **engine** — serving/engine.py continuous batching: one resident decode
  step, per-request bucket-padded prefill, rows retire at their OWN budget
  and freed slots refill immediately.

Both legs produce token-for-token identical useful output (greedy decode,
same model/params — the parity is pinned in tests/test_serving.py), so
sustained useful tokens/sec is the honest comparison.  Designed to run in
a SUBPROCESS (bench.py spawns it with ``JAX_PLATFORMS=cpu``) and self-arms
when run directly:

    python scripts/bench_serving.py [--requests 24] [--slots 4]

Prints ONE JSON line.  Honest caveat baked into the output: on this
1-core CPU host the engine's per-step host loop pays real Python overhead
that a TPU's faster decode step would amplify, while the static leg's
fused episode hides it — the measured speedup is therefore a LOWER bound
on what the same stream shows wherever decode steps dominate.

Two more legs (ISSUE 5):

* **decode_ahead** — the SAME engine, same stream, at ``decode_ahead=1``
  vs k ∈ {2,4,8}, on a deliberately SMALL model (dim-64 class): the
  dispatch-taxed regime where the per-step host sync dominates (the main
  comparison's dim-320 note measures this regime at ~0.3x vs static —
  exactly the tax decode-ahead exists to amortize).  The harness refuses
  to report a speedup unless every k's greedy output is token-identical
  to the k=1 leg.
* **prefix_cache** — a stream of repeated identical prompts served cold
  (cache off) vs warm (cache on): reports the prefill-skip count and the
  TTFT delta hits buy.

Two more legs (ISSUE 6, observability):

* **compile_census** — one engine, buckets (16, 32), four requests in
  sequence with a CompileTracker snapshot delta around each: repeated
  buckets compile ZERO new XLA programs, a first-seen bucket compiles
  exactly its prefill program — the ``n_compiled_programs`` moves when,
  and only when, a new bucket is introduced.
* **tracer_overhead** — the primary serving model windowed at the
  decode-ahead leg's top ``k``, served tracer-off vs tracer-on as PAIRED
  back-to-back reps (order alternating, GC swept first); reported
  ``overhead_frac`` is the median within-pair ratio, which cancels the
  host drift two independent blocks would absorb differently.  The
  <= 2% budget is measured there, not on the dim-32 toy regime where a
  whole decode step is ~200us of host Python and ANY per-window event
  model breaches 2% by arithmetic (see docs/OBSERVABILITY.md §Overhead).

Two more legs (ISSUE 11, live telemetry):

* **telemetry_overhead** — the tracer_overhead pairing applied to the
  telemetry hooks: telemetry-off vs an engine wired to a live sampler
  (0.1 s interval, real JSONL + Prometheus writes).  Unlike the tracer
  figure this one is GATED: ``overhead_frac > 2%`` exits nonzero.
* **slo_goodput** — 4x-slots requests queued at once, half with an
  impossible TTFT SLO and half unmissable, plus an unloaded control leg:
  the met/miss/goodput counters must come out EXACTLY right (arithmetic
  gates, not timing thresholds) and ``ServingStats.merge`` must sum them
  — any gate failing exits nonzero.

Two more legs (ISSUE 7, paged KV):

* **compile_census** additionally serves a PAGED engine (``kv_page_size``
  set, radix on, a shared-prefix pair so the extend program compiles):
  ``paged_cold`` pins the exact program set the paged path adds (prefill,
  paged insert, paged reset, decode window, radix extend) and
  ``paged_repeat`` pins zero recompiles on reuse.  The census is now a
  REGRESSION GATE: every leg's program count is pinned in
  ``CENSUS_BUDGET`` and the bench exits nonzero (status 3) when any leg
  exceeds its budget — a new program sneaking into the serving path fails
  CI instead of silently inflating compile time.
* **compile_cache** — the opt-in persistent compilation cache
  (``compile_cache_dir=`` / ``train.py --compile-cache-dir``) measured
  honestly: SUBPROCESSES share a temp cache dir (an in-process rerun
  would hit jax's in-memory jit cache and prove nothing); the cold run
  populates the dir, the warm run must add no files, and cold-vs-warm
  compile seconds come from each process's own CompileTracker.  A third
  probe (ISSUE 9 satellite, ROADMAP 5a) calls ``engine.prewarm()``
  before its first submit and reports cold-vs-prewarmed first-request
  TTFT — the launch path absorbing the compile bill instead of the
  first request.

One more block (ISSUE 13, run via ``--sampling-only`` so bench.py can
skip it independently with ``DTM_BENCH_SKIP_SAMPLING``):

* **sampling** — per-request temperature/top_p/seed decode: the
  greedy-limit gate (``SamplingParams(temperature=0)`` token-identical
  to plain greedy on dense AND speculative engines), the seeded-replay
  gate (the sampled stream served twice is token-identical — the
  carried-PRNG contract), and the speculative rejection-sampling
  figures (acceptance rate + useful tokens/sec for sampled spec
  traffic beside the greedy-spec floor).  Gate breaches exit 3.  The
  main serving record's compile census additionally pins
  ``sample_cold``/``sample_repeat`` at ZERO new programs — sampling
  configs are data planes in one program family, never new programs.

One more block (ISSUE 14, run via ``--chunked-only`` so bench.py can
skip it independently with ``DTM_BENCH_SKIP_CHUNKED``):

* **chunked_prefill** — ``InferenceEngine(prefill_chunk=C)`` under a
  long-prompt stream, four gates: decode TPOT p99 stays flat (≤ 1.15x a
  no-long-prompt control on the SAME engine) while prompts past every
  bucket admit chunk-by-chunk; short-request TTFT p99 is held; the
  chunked stream is token-identical to the same stream through a
  whole-prompt engine with a big-enough bucket (parity — chunking is a
  latency schedule, never different math); and the chunk program family
  is census-pinned (``chunked_cold`` exact, ``chunked_repeat`` ZERO —
  one ``extend[b{C}]`` program serves every prompt length).  Gate
  breaches exit 3.

``DTM_BENCH_QUICK=1`` shrinks models/streams to a CI smoke of the same
code paths (exercised by a ``slow``-marked test so harness rot is caught
without paying the full sweep); the record carries ``"quick": true``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

# a model big enough that the decode step's compute dominates the host
# loop's per-step dispatch (~0.5-1 ms on this class of host; dim-320
# depth-6 steps at ~4-5 ms/step) — the regime real serving runs in, where
# the engine's head-of-line win is visible instead of being drowned in
# dispatch overhead on toy models (at dim-64 the same harness measures
# the engine at ~0.3x: dispatch-bound, the wrong regime to serve from)
DIM, DEPTH, HEADS, VOCAB = (96, 2, 4, 32) if QUICK else (320, 6, 8, 32)
BUCKET = 32
SHORT_NEW, LONG_NEW = 8, 56
# the decode-ahead leg PINS the dispatch-taxed regime instead: a small
# model whose per-step compute is cheap enough that the host sync/dispatch
# IS the bottleneck decode_ahead amortizes
DA_DIM, DA_DEPTH, DA_HEADS = 32, 1, 2
DA_KS = (2, 4) if QUICK else (2, 4, 8)


def make_stream(n_requests: int, seed: int = 0):
    """Mixed-length synthetic stream: ragged prompts (4..28 tokens), one
    long-budget request per `slots` short ones — the head-of-line shape
    real traffic has (a few long generations pinning many short ones)."""
    rng = np.random.default_rng(seed)
    stream = []
    for i in range(n_requests):
        n = int(rng.integers(4, 29))
        prompt = rng.integers(1, VOCAB - 1, size=(n,)).astype(np.int32)
        max_new = LONG_NEW if i % 4 == 0 else SHORT_NEW
        stream.append((prompt, max_new))
    return stream


def run_static(model, params, stream, slots: int, max_len: int, gens: dict):
    """FIFO batches of `slots` through the one-shot generator: prompts
    right-padded to the shared bucket, per-batch max_new = the batch max
    (every row decodes that far — the head-of-line cost being measured).
    ``gens`` caches one compiled episode per distinct (batch, max_new) —
    share it across the warmup and timed legs so the static baseline is
    timed with warm compiles, exactly like the engine leg.  Returns
    (elapsed_s, useful_tokens, outputs keyed by stream index)."""
    from distributed_tensorflow_ibm_mnist_tpu.core.generate import make_generator

    outputs = {}
    t0 = time.perf_counter()
    useful = 0
    for base in range(0, len(stream), slots):
        batch = stream[base: base + slots]
        b = len(batch)
        batch_new = max(mn for _, mn in batch)
        gen = gens.get((b, batch_new))
        if gen is None:
            gen = gens[(b, batch_new)] = make_generator(
                model, max_len=max_len, max_new=batch_new)
        padded = np.zeros((b, BUCKET), np.int32)
        lens = np.asarray([p.size for p, _ in batch], np.int32)
        for i, (p, _) in enumerate(batch):
            padded[i, : p.size] = p
        out = np.asarray(gen(params, jnp.asarray(padded),
                             prompt_lens=jnp.asarray(lens)))
        for i, (p, mn) in enumerate(batch):
            outputs[base + i] = out[i, p.size: p.size + mn]  # useful slice
            useful += mn
    return time.perf_counter() - t0, useful, outputs


def run_engine(model, params, stream, slots: int, max_len: int, engine=None):
    """The same stream through the continuous-batching engine.  Pass a
    warmed engine to reuse its compiled programs (fresh mutable state is
    re-created per call via a new engine when None)."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )

    eng = engine or InferenceEngine(
        model, params, slots=slots, max_len=max_len,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                max_queue=len(stream)))
    t0 = time.perf_counter()
    reqs = [eng.submit(p, max_new=mn) for p, mn in stream]
    eng.run()
    elapsed = time.perf_counter() - t0
    useful = sum(len(r.generated) for r in reqs)
    outputs = {i: np.asarray(r.generated) for i, r in enumerate(reqs)}
    return elapsed, useful, outputs, eng


def run_decode_ahead(slots: int, requests: int) -> dict:
    """Decode-ahead sweep in the PINNED dispatch-taxed regime: the same
    stream through the same small model at ``decode_ahead=1`` vs each
    k in ``DA_KS``.  Greedy parity across k is enforced — any mismatch
    nulls the reported speedup instead of reporting one bought with
    different output."""
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        ServingStats,
    )

    max_len = BUCKET + LONG_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DA_DIM,
                      depth=DA_DEPTH, heads=DA_HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    stream = make_stream(requests, seed=2)
    warm = make_stream(max(slots * 2, 8), seed=3)

    def serve(k):
        # ONE engine per k, warmed then re-timed: a fresh engine would
        # recompile its window/prefill programs inside the timed region
        # (each engine jits its own closures), burying the per-window
        # dispatch tax under a constant ~0.4 s of XLA compile time
        eng = InferenceEngine(
            model, params, slots=slots, max_len=max_len, decode_ahead=k,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                    max_queue=max(len(stream), len(warm))))
        for p, mn in warm:
            eng.submit(p, max_new=mn)
        eng.run()
        eng.completed.clear()
        eng.stats = ServingStats(slots, decode_ahead=k)
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new=mn) for p, mn in stream]
        eng.run()
        return time.perf_counter() - t0, reqs, eng

    legs = {}
    base_out = None
    mismatches = 0
    for k in (1,) + DA_KS:
        el, reqs, eng = serve(k)
        useful = sum(len(r.generated) for r in reqs)
        out = [np.asarray(r.generated) for r in reqs]
        summ = eng.stats.summary()
        if k == 1:
            base_out = out
        else:
            mismatches += sum(
                not np.array_equal(a, b) for a, b in zip(base_out, out))
        legs[str(k)] = {
            "tokens_per_sec": round(useful / el, 2),
            "elapsed_s": round(el, 4),
            "n_windows": summ["n_windows"],
            # blocking host syncs per USEFUL token — the ~1/k decode-ahead
            # is buying (admissions add their own first-token syncs)
            "syncs_per_token": round(summ["n_windows"] / useful, 4),
            "window_waste_frac": summ["window_waste_frac"],
            "window_dispatch_s": summ["window_dispatch_s"],
            "window_readback_s": summ["window_readback_s"],
        }
    best_k = max(DA_KS, key=lambda k: legs[str(k)]["tokens_per_sec"])
    speedup = (legs[str(best_k)]["tokens_per_sec"]
               / legs["1"]["tokens_per_sec"])
    return {
        "model": {"dim": DA_DIM, "depth": DA_DEPTH, "heads": DA_HEADS},
        "n_requests": len(stream),
        "output_mismatches": mismatches,  # MUST be 0 (greedy k-parity)
        "legs": legs,
        "best_k": best_k,
        # the headline: sustained useful tokens/sec at the best window vs
        # the SAME engine at decode_ahead=1 — refused on any mismatch
        "speedup_best_k": None if mismatches else round(speedup, 3),
    }


def run_prefix_cache(model, params, slots: int, repeats: int) -> dict:
    """Repeated-prefix economics: the same prompt served ``repeats``
    times, cold (cache off — every admission prefills) vs warm (prefix
    cache on — every admission after the first reuses the stored row).
    Requests are served SEQUENTIALLY (submit, drain, next) so TTFT is the
    admission cost itself, not queue wait behind other slots; the means
    exclude request 0 of each leg (it pays the guaranteed first miss in
    the warm world and nothing special in the cold one — symmetric
    exclusion keeps the comparison honest)."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )

    max_len = BUCKET + LONG_NEW + 8
    rng = np.random.default_rng(7)
    prompt = rng.integers(1, VOCAB - 1, size=(24,)).astype(np.int32)
    stream = [(prompt, SHORT_NEW)] * repeats

    def serve(cache_bytes):
        eng = InferenceEngine(
            model, params, slots=slots, max_len=max_len,
            prefix_cache_bytes=cache_bytes,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                    max_queue=len(stream)))
        # warm the prefill/window compiles outside the timed region with a
        # DIFFERENT prompt (its cache entry shares nothing with `prompt`)
        eng.submit(np.arange(1, 30, dtype=np.int32), max_new=2)
        eng.run()
        eng.completed.clear()
        t0 = time.perf_counter()
        reqs, ttfts = [], []
        for p, mn in stream:
            r = eng.submit(p, max_new=mn)
            eng.run()
            reqs.append(r)
            ttfts.append(r.first_token_t - r.submit_t)
        el = time.perf_counter() - t0
        return el, reqs, eng, float(np.mean(ttfts[1:]))

    cold_s, cold_reqs, _, cold_ttft = serve(0)
    warm_s, warm_reqs, eng, warm_ttft = serve(256 << 20)
    summ = eng.stats.summary()
    mismatches = sum(
        not np.array_equal(np.asarray(a.generated), np.asarray(b.generated))
        for a, b in zip(cold_reqs, warm_reqs))
    return {
        "repeats": repeats,
        "prompt_len": int(prompt.size),
        "output_mismatches": mismatches,  # MUST be 0 (hit-vs-miss parity)
        "prefills_skipped": summ["prefix_hits"],
        "prefix_hit_rate": summ["prefix_hit_rate"],
        "wall_cold_s": round(cold_s, 4),
        "wall_warm_s": round(warm_s, 4),
        "ttft_s_mean_cold": round(cold_ttft, 6),
        "ttft_s_mean_warm": round(warm_ttft, 6),
        # the economics line: what one cache hit saves per request
        "ttft_delta_s_mean": round(cold_ttft - warm_ttft, 6),
    }


def run_sampling(slots: int, requests: int) -> dict:
    """ISSUE 13 acceptance, bench-shaped (``--sampling-only`` block):

    * **greedy_limit** — the SAME stream served plain-greedy vs with an
      explicit ``SamplingParams(temperature=0)`` per request, on a dense
      AND a speculative engine: temperature -> 0 collapses the tempered
      softmax to argmax, so the outputs must be token-identical.  Any
      mismatch is a HARD gate (exit 3) — the sampling plumbing must be
      invisible when it is off.
    * **seeded_replay** — the sampled stream (temperature 0.8, top_p
      0.9, per-request seeds) served TWICE through the same engine:
      token-identical replay is the carried-PRNG contract (a request's
      stream is a pure function of its seed and generated position,
      never of slot placement or admission order).  Also a hard gate.
    * **speculative sampling** — the spec engine serves the sampled
      stream by rejection sampling inside the verify window: acceptance
      rate and useful tokens/sec are REPORTED beside the greedy-spec
      floor, not parity-gated against plain sampling — rejection
      sampling preserves the target DISTRIBUTION, not the sample path
      (the distribution itself is chi-squared-gated in
      tests/test_sampling.py; only the temperature->0 limit is
      token-identical, and greedy_limit covers that on this engine too).
    """
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        SamplingParams,
        ServingStats,
    )

    max_len = BUCKET + LONG_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DA_DIM,
                      depth=DA_DEPTH, heads=DA_HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(6),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    stream = make_stream(requests, seed=8)
    warm = make_stream(max(slots * 2, 8), seed=9)
    none_sp = [None] * len(stream)
    zero_t = [SamplingParams(temperature=0.0, seed=i * 11 + 3)
              for i in range(len(stream))]
    sampled = [SamplingParams(temperature=0.8, top_p=0.9, seed=i * 11 + 3)
               for i in range(len(stream))]

    def build(**kw):
        # warmed outside the timed region, like every other leg: the
        # comparison is sustained serving, not compile time
        eng = InferenceEngine(
            model, params, slots=slots, max_len=max_len,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                    max_queue=max(len(stream), len(warm))),
            **kw)
        for p, mn in warm:
            eng.submit(p, max_new=mn)
        eng.run()
        return eng

    def serve(eng, sampling):
        eng.completed.clear()
        eng.stats = ServingStats(slots, decode_ahead=eng.decode_ahead)
        t0 = time.perf_counter()
        reqs = [eng.submit(p, max_new=mn, sampling=sp)
                for (p, mn), sp in zip(stream, sampling)]
        eng.run()
        el = time.perf_counter() - t0
        useful = sum(len(r.generated) for r in reqs)
        out = [np.asarray(r.generated) for r in reqs]
        return el, useful, out, eng.stats.summary()

    eng = build()
    _, _, greedy_out, _ = serve(eng, none_sp)
    _, _, zerot_out, _ = serve(eng, zero_t)
    s_el, s_useful, s1_out, s_summ = serve(eng, sampled)
    _, _, s2_out, _ = serve(eng, sampled)
    eng.close()
    mism_greedy = sum(not np.array_equal(a, b)
                      for a, b in zip(greedy_out, zerot_out))
    mism_replay = sum(not np.array_equal(a, b)
                      for a, b in zip(s1_out, s2_out))

    seng = build(speculative="ngram", draft_len=3)
    sg_el, sg_useful, sg_out, sg_summ = serve(seng, none_sp)
    _, _, sz_out, _ = serve(seng, zero_t)
    ss_el, ss_useful, ss1_out, ss_summ = serve(seng, sampled)
    _, _, ss2_out, _ = serve(seng, sampled)
    seng.close()
    mism_greedy += sum(not np.array_equal(a, b)
                       for a, b in zip(sg_out, sz_out))
    mism_replay += sum(not np.array_equal(a, b)
                       for a, b in zip(ss1_out, ss2_out))

    return {
        "model": {"dim": DA_DIM, "depth": DA_DEPTH, "heads": DA_HEADS},
        "n_requests": len(stream),
        "params": {"temperature": 0.8, "top_p": 0.9},
        # the HARD gates (exit 3 on breach), dense + spec engines both:
        "greedy_limit_mismatches": mism_greedy,  # MUST be 0
        "replay_mismatches": mism_replay,        # MUST be 0
        "gates_ok": not (mism_greedy or mism_replay),
        # sampled-traffic accounting from the dense engine's stats
        "sampled_tokens_per_sec": round(s_useful / s_el, 2),
        "n_sampled_requests": s_summ["n_sampled_requests"],
        "mean_temperature": s_summ["mean_temperature"],
        "nll_p50": s_summ["nll_p50"],
        "nll_p95": s_summ["nll_p95"],
        # rejection sampling vs greedy verify on the SAME spec engine:
        # the greedy row is the comparison floor — sampled acceptance
        # is expected at-or-below it (accepting a draft now costs a
        # Bernoulli trial, not an argmax match), and the figures say
        # what that costs in useful tokens per dispatch
        "spec": {
            "greedy": {
                "accept_rate": sg_summ["accept_rate"],
                "useful_tokens_per_window":
                    sg_summ["useful_tokens_per_window"],
                "tokens_per_sec": round(sg_useful / sg_el, 2),
            },
            "sampled": {
                "accept_rate": ss_summ["accept_rate"],
                "useful_tokens_per_window":
                    ss_summ["useful_tokens_per_window"],
                "tokens_per_sec": round(ss_useful / ss_el, 2),
            },
        },
    }


def run_chunked(slots: int, requests: int) -> dict:
    """ISSUE 14 acceptance, bench-shaped (``--chunked-only`` block).

    The regime: a stream where every 4th prompt is LONGER than every
    prefill bucket (48..64 tokens vs bucket 32) served by a chunked
    engine (``prefill_chunk=8``), beside a no-long-prompt control on the
    SAME engine.  Chunking's contract is that admitting a long prompt
    costs the decoding slots one bounded chunk per engine iteration —
    never a whole-prompt prefill stall — so the four HARD gates (any
    breach exits 3) are:

    * **tpot_flat** — decode TPOT p99 of the mixed stream's SHORT
      requests ≤ 1.15x the control's TPOT p99.  The chunk rides the
      prefill-overlap seam (dispatched between the window dispatch and
      its blocking readback), so its cost must mostly hide under the
      in-flight window (chunk FLOPs here are ~1/8 of a window's).
    * **ttft_held** — the mixed stream's short-request TTFT p99 stays
      within ``TTFT_HELD_X`` of control: long admissions must not
      starve short ones out of their first token.
    * **parity** — the mixed stream through a whole-prompt engine
      (bucket 64 so the long prompts fit densely) is token-identical to
      the chunked serve.  Chunking is a latency SCHEDULE over the same
      suffix-extend math, never a different computation.
    * **census** — a fresh chunked engine's cold program set is pinned
      (``chunked_cold``) and a second long-prompt stream compiles ZERO
      new programs (``chunked_repeat``): ONE ``extend[b{C}]`` program
      serves every prompt length, so prompt length can never trigger a
      compile storm — the point of chunking over a bucket ladder.
    """
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import CompileTracker

    CHUNK, AHEAD, PAGE = 8, 48, 8
    LONG_LO, LONG_HI = 48, 64
    max_len = LONG_HI + SHORT_NEW + 8

    def make_streams(n, seed):
        """(control, mixed): identical SHORT prompts; mixed swaps every
        4th for a past-every-bucket long one.  max_new is uniformly
        SHORT_NEW — the leg measures prefill admission cost, so decode
        budgets are held equal across legs."""
        rng = np.random.default_rng(seed)
        control, mixed = [], []
        for i in range(n):
            short = rng.integers(
                1, VOCAB - 1, size=(int(rng.integers(4, 29)),)
            ).astype(np.int32)
            control.append((short, SHORT_NEW))
            if i % 4 == 0:
                long_p = rng.integers(
                    1, VOCAB - 1,
                    size=(int(rng.integers(LONG_LO, LONG_HI + 1)),)
                ).astype(np.int32)
                mixed.append((long_p, SHORT_NEW))
            else:
                mixed.append((short, SHORT_NEW))
        return control, mixed

    # --- census sub-leg FIRST (small model, fresh process): the chunked
    # engine's cold set — including the module-level pick/helper jits
    # this standalone process hasn't warmed yet — then a SECOND
    # long-prompt stream that must compile NOTHING (one extend[b8]
    # program, whatever the prompt length)
    tracker = CompileTracker.install()
    cmodel = get_model("causal_lm", num_classes=VOCAB, dim=DA_DIM,
                       depth=DA_DEPTH, heads=DA_HEADS, dtype=jnp.float32)
    cparams = cmodel.init(jax.random.PRNGKey(14),
                          jnp.zeros((1, 8), jnp.int32))["params"]

    def chunked_engine(model, params, n_queue, radix=False):
        return InferenceEngine(
            model, params, slots=slots, max_len=max_len,
            kv_page_size=PAGE, prefill_chunk=CHUNK, decode_ahead=AHEAD,
            radix_cache=radix,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                    max_queue=n_queue))

    def census_serve(engine, streams):
        before = tracker.snapshot()
        reqs = [engine.submit(p, max_new=mn) for p, mn in streams]
        engine.run()
        d = CompileTracker.delta(tracker.snapshot(), before)
        assert all(len(r.generated) == mn for r, (_, mn) in
                   zip(reqs, streams))
        return {"n_new_programs": d["n_compiled_programs"],
                "by_site": {k: v["n"] for k, v in d["by_site"].items()}}

    ceng = chunked_engine(cmodel, cparams, 16)
    _, cmix1 = make_streams(8, seed=20)
    _, cmix2 = make_streams(8, seed=21)
    census = {"chunked_cold": census_serve(ceng, cmix1),
              "chunked_repeat": census_serve(ceng, cmix2)}
    ceng.close()
    census_over = {
        name: leg["n_new_programs"] - CENSUS_BUDGET[name]
        for name, leg in census.items()
        if leg["n_new_programs"] > CENSUS_BUDGET[name]}

    # --- timed legs: the compute-dominant model (same regime argument
    # as the headline serving leg — a dispatch-bound toy model would
    # measure the host loop, not the chunk schedule)
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM, depth=DEPTH,
                      heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(15),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    control, mixed = make_streams(requests, seed=22)
    short_idx = [i for i in range(requests) if i % 4 != 0]

    def serve(eng, stream):
        from distributed_tensorflow_ibm_mnist_tpu.serving.stats import (
            ServingStats,
        )

        eng.completed.clear()
        eng.stats = ServingStats(slots, decode_ahead=eng.decode_ahead)
        reqs = [eng.submit(p, max_new=mn) for p, mn in stream]
        eng.run()
        ttft = [r.first_token_t - r.submit_t for r in reqs]
        tpot = {i: (r.finish_t - r.first_token_t) / (len(r.generated) - 1)
                for i, r in enumerate(reqs) if len(r.generated) >= 2}
        outs = [np.asarray(r.generated) for r in reqs]
        return ttft, tpot, outs, eng.stats.summary()

    # radix off in the timed/parity legs: prefix sharing would skip
    # chunks for whichever leg ran second — the comparison is the chunk
    # SCHEDULE, so both engines prefill every admitted token
    eng = chunked_engine(model, params, 2 * requests + 8)
    warm, warm_mixed = make_streams(max(slots * 2, 8), seed=23)
    for p, mn in warm + warm_mixed:  # warm both prompt shapes' programs
        eng.submit(p, max_new=mn)
    eng.run()

    c_ttft, c_tpot, _, _ = serve(eng, control)
    m_ttft, m_tpot, m_out, m_summ = serve(eng, mixed)
    eng.close()

    # parity: whole-prompt engine, bucket 64 so long prompts fit densely
    weng = InferenceEngine(
        model, params, slots=slots, max_len=max_len,
        kv_page_size=PAGE, decode_ahead=AHEAD, radix_cache=False,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET, LONG_HI),
                                max_queue=2 * requests + 8))
    for p, mn in warm + warm_mixed:
        weng.submit(p, max_new=mn)
    weng.run()
    _, _, w_out, _ = serve(weng, mixed)
    weng.close()
    mismatches = sum(not np.array_equal(a, b)
                     for a, b in zip(m_out, w_out))

    def p99(xs):
        return float(np.percentile(np.asarray(xs, np.float64), 99))

    control_tpot_p99 = p99(list(c_tpot.values()))
    mixed_short_tpot_p99 = p99([m_tpot[i] for i in short_idx
                                if i in m_tpot])
    control_ttft_p99 = p99(c_ttft)
    mixed_short_ttft_p99 = p99([m_ttft[i] for i in short_idx])
    tpot_x = mixed_short_tpot_p99 / control_tpot_p99
    ttft_x = mixed_short_ttft_p99 / control_ttft_p99
    gates = {
        "tpot_flat": tpot_x <= TPOT_FLAT_X,
        "ttft_held": ttft_x <= TTFT_HELD_X,
        "parity": mismatches == 0,
        "census": not census_over,
    }
    return {
        "model": {"dim": DIM, "depth": DEPTH, "heads": HEADS},
        "n_requests": requests,
        "slots": slots,
        "prefill_chunk": CHUNK,
        "decode_ahead": AHEAD,
        "kv_page_size": PAGE,
        "prefill_bucket": BUCKET,
        "long_prompt_tokens": [LONG_LO, LONG_HI],
        # the new ServingStats schema, from the mixed serve
        "n_prefill_chunks": m_summ["n_prefill_chunks"],
        "chunk_stall_s": m_summ["chunk_stall_s"],
        "chunk_stall_frac": m_summ["chunk_stall_frac"],
        "longest_prompt_admitted": m_summ["longest_prompt_admitted"],
        # gate figures: decode-latency flatness under long admissions
        "control_tpot_s_p99": round(control_tpot_p99, 6),
        "mixed_short_tpot_s_p99": round(mixed_short_tpot_p99, 6),
        "tpot_p99_x": round(tpot_x, 3),
        "tpot_target_x": TPOT_FLAT_X,
        "control_ttft_s_p99": round(control_ttft_p99, 6),
        "mixed_short_ttft_s_p99": round(mixed_short_ttft_p99, 6),
        "ttft_p99_x": round(ttft_x, 3),
        "ttft_target_x": TTFT_HELD_X,
        "output_mismatches": mismatches,  # MUST be 0 (chunked parity)
        "census": {"legs": census, "mode": tracker.mode,
                   "budget": {k: CENSUS_BUDGET[k] for k in census},
                   "over_budget": census_over},
        "gates": gates,
        "gates_ok": all(gates.values()),
    }


# Gate thresholds for the chunked_prefill leg (ISSUE 14): TPOT p99 of
# the short requests sharing the engine with chunking long admissions
# must stay within 15% of the no-long-prompt control — the headline
# "decode latency stays flat" claim — and their TTFT p99 within 2x (a
# short request may queue behind at most one in-flight chunked
# admission's bounded chunks, never a whole-prompt prefill).
TPOT_FLAT_X = 1.15
TTFT_HELD_X = 2.0


# Pinned per-leg budgets for the compile census (ISSUE 7 satellite: the
# census is a regression GATE, not just a report — a leg exceeding its
# budget means a program-family leak, and the bench exits nonzero).  The
# numbers are the MEASURED cold sets of the current engine, pinned exact:
# one extra program in any leg is the regression the gate exists to catch.
CENSUS_BUDGET = {
    "bucket16_first": 10,   # 2 under prefill[b16] + first_pick (the ISSUE
    #                         13 split: prefill emits raw logits, the
    #                         SHARED sample-aware pick program picks at
    #                         landing) + window + insert + reset + 4
    #                         unattributed helper jits
    "bucket16_repeat": 0,   # repeats compile NOTHING
    "bucket32_new": 1,      # the new bucket's prefill only
    "bucket32_repeat": 0,
    "paged_cold": 5,        # paged prefill/insert/window/reset + extend
    #                         (first_pick is MODULE-level and already
    #                         warm from the dense engine)
    "paged_repeat": 0,      # paging adds programs once, not per request
    "spec_cold": 4,         # prefill[b16] + verify_window[k4] + insert +
    #                         reset; first_pick and the helper jits are
    #                         shared module-level programs the dense legs
    #                         already warmed
    "spec_repeat": 0,       # speculation adds its programs once too
    "tp_cold": 8,           # the dense serve family under GSPMD — prefill,
    #                         first_pick (recompiles: sharded inputs),
    #                         window, insert, reset + 3 unattributed helper
    #                         jits; the sharded cache-alloc/param-upload
    #                         programs compile at engine CONSTRUCTION,
    #                         before this leg's delta
    "tp_repeat": 0,         # tp changes program CONTENTS, never counts
    "quant_cold": 4,        # prefill + insert + window + reset with int8
    #                         kernels inside — the dense cold set minus
    #                         the pick/helper jits the earlier dense legs
    #                         already warmed; quant must NOT fork the
    #                         program family past these four sites
    "quant_repeat": 0,      # the int8 tree must not flap jit cache keys
    "sample_cold": 0,       # sampling is DATA, not program shape (ISSUE
    #                         13): temperature/top_p/key ride the decode
    #                         carry as per-slot planes through the SAME
    #                         window/prefill programs, so a sampled
    #                         request on the warmed dense engine compiles
    #                         NOTHING — even its first one
    "sample_repeat": 0,     # and a DIFFERENT (temp, top_p, seed) config
    #                         compiles nothing either: one program family
    #                         across every sampling config
    # the chunked-prefill family (ISSUE 14; gated by the --chunked-only
    # block, which runs in its OWN process so the module-level pick and
    # helper jits land in this cold set too):
    "chunked_cold": 8,      # extend[b8] + decode window + slot_reset +
    #                         first_pick + 4 helper jits — and NO bucket
    #                         prefill: a chunked engine admits every
    #                         prompt through the one extend program
    "chunked_repeat": 0,    # a SECOND long-prompt stream (new lengths,
    #                         new chunk counts) compiles NOTHING: prompt
    #                         length is data, never a program shape
}

# Per-site pins for the speculative leg (ISSUE 9): the verify window is
# ONE program for its k, and the host-side draft upload (`slot_draft`)
# compiles NOTHING — drafting is numpy + a device transfer; a program
# appearing under slot_draft means drafting grew a jit, which is the
# regression this pin catches.
SPEC_SITE_BUDGET = {"verify_window[k4]": 1, "slot_draft": 0}


def run_compile_census(slots: int) -> dict:
    """ISSUE 6 acceptance, hardened into a gate (ISSUE 7 satellite):
    ``n_compiled_programs`` changes when — and only when — a new program
    family member is introduced, and every leg stays within its pinned
    ``CENSUS_BUDGET``.  ONE dense engine (jit caches are per-engine
    closures) with buckets (16, 32) serves four requests in sequence, then
    one PAGED engine (its own window/insert/reset/extend family) serves a
    shared-prefix pair twice:

    1. first bucket-16 request: the engine's cold set compiles;
    2. second bucket-16 request: ZERO new programs (all cache hits);
    3. first bucket-32 request: EXACTLY the new bucket's prefill program;
    4. second bucket-32 request: zero again;
    5. paged_cold: the paged family (+ the radix suffix-extend program);
    6. paged_repeat: zero — paging adds programs once, not per request;
    7. spec_cold: the speculative family (verify window replaces the
       decode window; ``slot_draft`` must compile NOTHING — per-site pins
       in ``SPEC_SITE_BUDGET``);
    8. spec_repeat: zero.
    4b. sample_cold / sample_repeat (ISSUE 13): sampled requests on the
       SAME warmed dense engine — distinct (temperature, top_p, seed)
       configs are per-slot data planes in the decode carry, so BOTH
       legs pin ZERO new programs (the one-program-family acceptance
       criterion, census-shaped);
    9. quant_cold (ISSUE 12): a fresh int8 weight-quant engine compiles
       the SAME program set as the dense cold engine — the family is
       quant-BLIND (int8 kernels/scales change what programs contain,
       never how many there are);
    10. quant_repeat: zero — the int8 tree must not flap jit cache keys.
    11. tp_cold (ISSUE 10, >= 2 devices): the same dense family under a
        2-chip tp mesh — ONE program per (site, shape-key); GSPMD changes
        program contents, never counts, and a site compiling twice means
        the jit cache key is flapping on input shardings;
    12. tp_repeat: zero again.
    """
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        SamplingParams,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import CompileTracker

    tracker = CompileTracker.install()
    max_len = 32 + SHORT_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DA_DIM,
                      depth=DA_DEPTH, heads=DA_HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(4),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    eng = InferenceEngine(
        model, params, slots=slots, max_len=max_len,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(16, 32),
                                max_queue=8))
    rng = np.random.default_rng(5)

    def serve_one(engine, prompts, sampling=None):
        before = tracker.snapshot()
        for p in prompts:
            engine.submit(p, max_new=SHORT_NEW, sampling=sampling)
        engine.run()
        d = CompileTracker.delta(tracker.snapshot(), before)
        return {"n_new_programs": d["n_compiled_programs"],
                "by_site": {k: v["n"] for k, v in d["by_site"].items()}}

    def rand_prompt(n):
        return rng.integers(1, VOCAB - 1, size=(n,)).astype(np.int32)

    legs = {
        "bucket16_first": serve_one(eng, [rand_prompt(8)]),
        "bucket16_repeat": serve_one(eng, [rand_prompt(10)]),  # same bucket
        "bucket32_new": serve_one(eng, [rand_prompt(24)]),
        "bucket32_repeat": serve_one(eng, [rand_prompt(28)]),
        # sampling is data, not program shape (ISSUE 13): the warmed
        # dense engine serves its FIRST sampled request — and then a
        # different (temperature, top_p, seed) config — compiling nothing
        "sample_cold": serve_one(
            eng, [rand_prompt(8)],
            sampling=SamplingParams(temperature=0.8, top_p=0.9, seed=11)),
        "sample_repeat": serve_one(
            eng, [rand_prompt(10)],
            sampling=SamplingParams(temperature=1.1, top_p=0.5, seed=12)),
    }
    # the paged program family: a fresh paged engine (page pool + radix)
    # serving a shared-prefix pair — the second request radix-matches the
    # first's donated page, compiling the suffix-extend program once
    peng = InferenceEngine(
        model, params, slots=slots, max_len=48, kv_page_size=8,
        scheduler=FIFOScheduler(max_len=48, buckets=(16, 32), max_queue=8))
    shared = rand_prompt(8)
    pair = [np.concatenate([shared, rand_prompt(4)]) for _ in range(2)]
    legs["paged_cold"] = serve_one(peng, pair)
    legs["paged_repeat"] = serve_one(
        peng, [np.concatenate([shared, rand_prompt(4)]) for _ in range(2)])
    # the speculative program family (ISSUE 9): a fresh spec engine —
    # verify window instead of decode window, host drafting under the
    # slot_draft site (which must compile NOTHING; see SPEC_SITE_BUDGET)
    seng = InferenceEngine(
        model, params, slots=slots, max_len=max_len,
        speculative="ngram", draft_len=3,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(16, 32),
                                max_queue=8))
    legs["spec_cold"] = serve_one(seng, [rand_prompt(8)])
    legs["spec_repeat"] = serve_one(seng, [rand_prompt(10)])
    # the quantized program family (ISSUE 12): a fresh int8 weight-quant
    # engine must compile the SAME program set as the dense cold engine —
    # quant lives in the model fields and the param tree (int8 kernels +
    # scale leaves), so the family is quant-BLIND: same sites, same
    # shape-keys, different dtypes inside.  A quant_cold count above the
    # dense cold set means quantization forked a program family; any
    # quant_repeat compile means the int8 tree flaps the jit cache key.
    qeng = InferenceEngine(
        model, params, slots=slots, max_len=max_len, quant="int8",
        scheduler=FIFOScheduler(max_len=max_len, buckets=(16, 32),
                                max_queue=8))
    legs["quant_cold"] = serve_one(qeng, [rand_prompt(8)])
    legs["quant_repeat"] = serve_one(qeng, [rand_prompt(10)])
    # the tensor-parallel program family (ISSUE 10): the SAME engine
    # sharded over a 2-chip tp mesh must stay ONE program per (site,
    # shape-key) — GSPMD partitioning changes what each program contains,
    # never how many there are.  A tp_cold count above the dense cold set
    # (+ the sharded-upload helpers) or ANY tp_repeat compile means the
    # mesh path leaks programs per request (e.g. committed/uncommitted
    # input sharding flapping the jit cache key).
    teng = None
    if len(jax.devices()) >= 2:
        teng = InferenceEngine(
            model, params, slots=slots, max_len=max_len, tp=2,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(16, 32),
                                    max_queue=8))
        legs["tp_cold"] = serve_one(teng, [rand_prompt(8)])
        legs["tp_repeat"] = serve_one(teng, [rand_prompt(10)])
    over = {name: leg["n_new_programs"] - CENSUS_BUDGET[name]
            for name, leg in legs.items()
            if leg["n_new_programs"] > CENSUS_BUDGET[name]}
    if teng is not None:
        # one-program-per-site within the tp cold set: a site compiling
        # twice under tp (same shape-key) is exactly the sharding-flap
        # regression the leg exists to catch
        for site, n in legs["tp_cold"]["by_site"].items():
            if site != "unattributed" and n > 1:
                over[f"tp_cold:{site}"] = n - 1
    for site, budget in SPEC_SITE_BUDGET.items():
        n = legs["spec_cold"]["by_site"].get(site, 0)
        if n > budget:
            over[f"spec_cold:{site}"] = n - budget
    return {
        "legs": legs,
        "mode": tracker.mode,
        "budget": CENSUS_BUDGET,
        "spec_site_budget": SPEC_SITE_BUDGET,
        # the regression gate: any leg over its pinned budget fails the
        # bench run (main() exits 3) — program-family growth is a perf
        # regression even when every test still passes
        "over_budget": over,
        "census_ok": not over,
        # the acceptance booleans bench.py's record pins: repeats compile
        # NOTHING, and the new bucket compiles SOMETHING
        "repeat_compiles_zero": (
            legs["bucket16_repeat"]["n_new_programs"] == 0
            and legs["bucket32_repeat"]["n_new_programs"] == 0
            and legs["paged_repeat"]["n_new_programs"] == 0
            and legs["spec_repeat"]["n_new_programs"] == 0
            and legs["quant_repeat"]["n_new_programs"] == 0
            and legs["sample_repeat"]["n_new_programs"] == 0
            and legs.get("tp_repeat", {"n_new_programs": 0})[
                "n_new_programs"] == 0),
        "new_bucket_compiles": legs["bucket32_new"]["n_new_programs"] > 0,
    }


def _compile_cache_probe(cache_dir: str, prewarm: bool = False) -> None:
    """Subprocess mode (``--compile-cache-probe DIR``): build ONE engine
    with the persistent XLA compile cache at DIR, serve two requests, and
    print the engine's compile accounting as JSON.  Run three times
    against the same DIR by :func:`run_compile_cache`: the first call
    populates the cache, the second measures what a warm process actually
    saves — cross-PROCESS, which is the regression the cache exists to
    fix (an in-process rerun would hit jax's in-memory jit cache and
    prove nothing) — and the third (``--prewarm``) additionally calls
    :meth:`InferenceEngine.prewarm` before submitting, measuring the
    launch-path half of ROADMAP 5a: the first request's TTFT with every
    compile moved before traffic.  Uses the bench's PRIMARY model: the
    persistent cache only stores programs above
    ``jax_persistent_cache_min_compile_time_secs`` (0.1 s —
    core/trainer._enable_compile_cache), and the toy models' programs all
    compile under that floor, honestly measuring nothing."""
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )

    max_len = 16 + SHORT_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM,
                      depth=DEPTH, heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(9),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    t0 = time.perf_counter()
    eng = InferenceEngine(
        model, params, slots=2, max_len=max_len,
        compile_cache_dir=cache_dir,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(16,), max_queue=4))
    # the production threshold (0.1 s) is tuned for accelerator-scale
    # programs; this host's XLA:CPU backend-compiles each engine program
    # in less, which would honestly cache NOTHING — lower the floor so
    # the probe exercises the cache mechanism itself (programs compile
    # lazily at first dispatch, so this lands before any compile)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    prewarm_s = None
    if prewarm:
        prewarm_s = eng.prewarm()["wall_s"]
    rng = np.random.default_rng(11)
    reqs = []
    for _ in range(2):
        reqs.append(eng.submit(
            rng.integers(1, VOCAB - 1, size=(8,)).astype(np.int32),
            max_new=4))
    eng.run()
    s = eng.stats.summary()
    print(json.dumps({
        "wall_s": round(time.perf_counter() - t0, 4),
        "compile_s": s["compile_time_s"],
        "n_programs": s["n_compiled_programs"],
        "n_cache_files": len(os.listdir(cache_dir)),
        # first request's TTFT: with --prewarm every program was compiled
        # before the submit, so this is pure serving latency; without, it
        # eats the first-use compiles — the cold-vs-prewarmed delta the
        # compile_cache block reports
        "ttft_first_s": round(reqs[0].first_token_t - reqs[0].submit_t, 6),
        "prewarm_s": prewarm_s,
    }), flush=True)


def run_compile_cache(timeout_s: float = 600.0) -> dict:
    """ISSUE 7 satellite: cold-vs-warm compile seconds through the opt-in
    persistent compilation cache (``compile_cache_dir=`` on the engine /
    ``compile_cache_dir`` in RunConfig).  Two subprocess probes share one
    ephemeral cache dir; the report is honest about the delta it actually
    measured — ``cache_effective`` is a measurement, not an assertion
    (CPU-backend cacheability varies across jax versions)."""
    import subprocess
    import tempfile

    with tempfile.TemporaryDirectory(prefix="dtm-compile-cache-") as d:
        runs = []
        for extra in ((), (), ("--prewarm",)):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--compile-cache-probe", d, *extra],
                capture_output=True, text=True, timeout=timeout_s,
                env={**os.environ, "JAX_PLATFORMS": "cpu"})
            if proc.returncode != 0:
                return {"error": (proc.stderr or proc.stdout).strip()[-400:]}
            runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
    cold, warm, prewarmed = runs
    return {
        "cold_wall_s": cold["wall_s"],
        "warm_wall_s": warm["wall_s"],
        # CompileTracker seconds include trace+lower (host work a cache
        # hit still pays); the backend-compile share is what warms away
        "cold_compile_s": cold["compile_s"],
        "warm_compile_s": warm["compile_s"],
        "n_programs": cold["n_programs"],
        "n_cache_files": warm["n_cache_files"],
        # the wiring proof: the cold probe POPULATED the dir and the warm
        # probe added nothing (it read what the cold one wrote)
        "cache_effective": (
            cold["n_cache_files"] > 0
            and warm["n_cache_files"] == cold["n_cache_files"]),
        # ROADMAP 5a, the launch-path half: first-request TTFT with no
        # prewarm (eats the engine's first-use compiles) vs with
        # engine.prewarm() run before the first submit (every program
        # compiled — and, here, persistent-cache-hit — before traffic)
        "ttft_first_cold_s": cold["ttft_first_s"],
        "ttft_first_prewarmed_s": prewarmed["ttft_first_s"],
        "prewarm_s": prewarmed["prewarm_s"],
        "prewarm_ttft_delta_s": round(
            cold["ttft_first_s"] - prewarmed["ttft_first_s"], 6),
    }


def run_tracer_overhead(slots: int, requests: int) -> dict:
    """Tracer cost on the decode bench the budget is pinned against: the
    serving bench's PRIMARY model (``DIM``/``DEPTH``/``HEADS`` — the
    regime whose tokens/sec the bench headlines) at the decode-ahead
    leg's top window size, served by a tracer-off engine vs a tracer-on
    one, both warmed.  Target: <= 2% overhead.

    Not measured on the decode-ahead study's dim-32 toy model: there a
    whole decode step is ~200 us of host Python, so ANY per-request/
    per-window event model is >2% by arithmetic (each recorded event
    costs ~1-2 us; even no-op tracer calls breach the budget).  The toy
    regime exists to stress window amortization, not to represent
    serving; the budget is for tracing realistically-sized decode."""
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        ServingStats,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer

    max_len = BUCKET + LONG_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM,
                      depth=DEPTH, heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(6),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    stream = make_stream(requests, seed=8)
    warm = make_stream(max(slots * 2, 8), seed=9)

    k = DA_KS[-1]

    def build(tracer):
        eng = InferenceEngine(
            model, params, slots=slots, max_len=max_len, tracer=tracer,
            decode_ahead=k,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                    max_queue=max(len(stream), len(warm)),
                                    tracer=tracer))
        for p, mn in warm:
            eng.submit(p, max_new=mn)
        eng.run()
        return eng

    def timed(eng):
        eng.completed.clear()
        eng.stats = ServingStats(eng.slots, decode_ahead=eng.decode_ahead)
        t0 = time.perf_counter()
        for p, mn in stream:
            eng.submit(p, max_new=mn)
        eng.run()
        return time.perf_counter() - t0

    # a large-capacity tracer so the soak never wraps mid-measurement (ring
    # eviction is cheap, but keep the two legs structurally identical)
    tracer = Tracer(capacity=1 << 18)
    eng_off, eng_on = build(None), build(tracer)
    # The effect (~0.5 ms of tracer work) is far below this host's
    # run-to-run noise (tens of ms runs drifting ±20% over minutes), so
    # measure PAIRED: each rep times the two legs back-to-back (order
    # alternating, GC swept first) and yields one on/off ratio — drift
    # across a ~70 ms pair window cancels where two independent
    # min-of-reps blocks would each absorb a different machine state.
    # The reported overhead is the median pair ratio.
    import gc

    reps = 10
    off_ts: list[float] = []
    on_ts: list[float] = []
    for i in range(reps):
        pair = ((eng_off, eng_on) if i % 2 == 0 else (eng_on, eng_off))
        for eng in pair:
            gc.collect()
            t = timed(eng)
            (off_ts if eng is eng_off else on_ts).append(t)
    ratios = sorted(b / a for a, b in zip(off_ts, on_ts))
    mid = len(ratios) // 2
    median_ratio = (ratios[mid] if len(ratios) % 2
                    else (ratios[mid - 1] + ratios[mid]) / 2.0)
    off_s, on_s = min(off_ts), min(on_ts)
    return {
        "n_requests": len(stream),
        "decode_ahead": k,
        "off_s": round(off_s, 4),
        "on_s": round(on_s, 4),
        "overhead_frac": round(median_ratio - 1.0, 4),
        "target_frac": 0.02,
        "n_trace_events": len(tracer.events()) + tracer.open_spans,
        "dropped_events": tracer.dropped,
    }


def run_telemetry_overhead(slots: int, requests: int) -> dict:
    """Telemetry cost on the same primary regime, measured the same PAIRED
    way as ``run_tracer_overhead`` (back-to-back off/on reps, alternating
    order, GC swept, median within-pair ratio): a telemetry-off engine vs
    one wired to a live :class:`Telemetry` sampling every 0.1 s into real
    JSONL + Prometheus files.  The wired-on cost is per-request histogram
    observes, a per-step counter, a per-step clock compare, and the
    interval's sample writes — the nil-guard contract keeps wired-off at
    one attribute test.  Target: <= 2% (breach exits the bench nonzero —
    unlike the tracer this budget is a hard gate).  The dim-32 toy-regime
    caveat from ``run_tracer_overhead`` applies identically."""
    import gc
    import tempfile

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        ServingStats,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import Telemetry

    max_len = BUCKET + LONG_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM,
                      depth=DEPTH, heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(6),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    stream = make_stream(requests, seed=8)
    warm = make_stream(max(slots * 2, 8), seed=9)
    k = DA_KS[-1]

    def build(telemetry):
        eng = InferenceEngine(
            model, params, slots=slots, max_len=max_len,
            telemetry=telemetry, decode_ahead=k,
            scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                    max_queue=max(len(stream), len(warm))))
        for p, mn in warm:
            eng.submit(p, max_new=mn)
        eng.run()
        return eng

    def timed(eng):
        eng.completed.clear()
        eng.stats = ServingStats(eng.slots, decode_ahead=eng.decode_ahead)
        t0 = time.perf_counter()
        for p, mn in stream:
            eng.submit(p, max_new=mn)
        eng.run()
        return time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as td:
        telemetry = Telemetry(interval_s=0.1,
                              jsonl_path=f"{td}/telemetry.jsonl",
                              prom_path=f"{td}/telemetry.prom")
        eng_off, eng_on = build(None), build(telemetry)
        reps = 10
        off_ts: list[float] = []
        on_ts: list[float] = []
        for i in range(reps):
            pair = ((eng_off, eng_on) if i % 2 == 0 else (eng_on, eng_off))
            for eng in pair:
                gc.collect()
                t = timed(eng)
                (off_ts if eng is eng_off else on_ts).append(t)
        samples = telemetry.samples
        telemetry.close()
    ratios = sorted(b / a for a, b in zip(off_ts, on_ts))
    mid = len(ratios) // 2
    median_ratio = (ratios[mid] if len(ratios) % 2
                    else (ratios[mid - 1] + ratios[mid]) / 2.0)
    return {
        "n_requests": len(stream),
        "decode_ahead": k,
        "interval_s": 0.1,
        "off_s": round(min(off_ts), 4),
        "on_s": round(min(on_ts), 4),
        "overhead_frac": round(median_ratio - 1.0, 4),
        "target_frac": 0.02,
        "n_samples": samples,
    }


def run_slo_goodput(slots: int) -> dict:
    """SLO/goodput counters move CORRECTLY on an overloaded stream.

    One warmed primary-regime engine serves 4x-slots requests submitted
    at once (the queue is the overload), split between an impossible
    TTFT SLO (1e-6 s — below one jit dispatch, so every such request
    MUST miss at first token) and an unmissable one (1e4 s — met iff the
    request completes).  A second, unloaded leg (slots requests, all
    unmissable) must meet everything.  The gates are arithmetic, not
    timing-sensitive: met + miss == tracked on each leg, the tight half
    misses exactly, the generous half and the unloaded leg meet exactly,
    goodput is reported, and ``ServingStats.merge`` across the two legs
    sums the counters — the same rollup the router applies per replica.
    Any gate failing exits the bench nonzero."""
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        ServingStats,
    )

    max_len = BUCKET + LONG_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM,
                      depth=DEPTH, heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(7),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    n = 4 * slots
    n_tight = (n + 1) // 2
    stream = make_stream(n, seed=10)
    warm = make_stream(max(slots * 2, 8), seed=11)
    eng = InferenceEngine(
        model, params, slots=slots, max_len=max_len,
        decode_ahead=DA_KS[-1],
        scheduler=FIFOScheduler(max_len=max_len, buckets=(BUCKET,),
                                max_queue=n + len(warm)))
    for p, mn in warm:
        eng.submit(p, max_new=mn)
    eng.run()

    # overloaded leg: every request queued up front, alternating SLOs
    eng.completed.clear()
    eng.stats = ServingStats(slots, decode_ahead=eng.decode_ahead)
    t0 = time.perf_counter()
    for i, (p, mn) in enumerate(stream):
        eng.submit(p, max_new=mn,
                   ttft_slo_s=(1e-6 if i % 2 == 0 else 1e4),
                   tpot_slo_s=1e4)
    eng.run()
    over_s = time.perf_counter() - t0
    over_stats = eng.stats
    over = over_stats.summary()

    # unloaded leg: fits the slots, all SLOs unmissable
    eng.completed.clear()
    eng.stats = ServingStats(slots, decode_ahead=eng.decode_ahead)
    for p, mn in make_stream(slots, seed=12):
        eng.submit(p, max_new=mn, ttft_slo_s=1e4, tpot_slo_s=1e4)
    eng.run()
    un = eng.stats.summary()
    merged = ServingStats.merge([over_stats, eng.stats])

    gates = {
        "overloaded_conservation": (
            over["slo_met"] + over["slo_miss"] == over["slo_tracked"] == n),
        "tight_half_missed": (over["slo_miss"] == n_tight
                              and over["slo_ttft_miss"] == n_tight),
        "generous_half_met": over["slo_met"] == n - n_tight,
        "unloaded_all_met": (un["slo_met"] == un["slo_tracked"] == slots
                             and un["slo_miss"] == 0),
        "goodput_reported": (over["goodput_rps"] is not None
                             and un["goodput_rps"] is not None),
        "merge_sums_counters": (
            merged["slo_tracked"] == n + slots
            and merged["slo_met"] == over["slo_met"] + un["slo_met"]
            and merged["slo_miss"] == over["slo_miss"]),
    }
    return {
        "slots": slots,
        "overloaded_requests": n,
        "overloaded_s": round(over_s, 4),
        "slo_tracked": over["slo_tracked"],
        "slo_met": over["slo_met"],
        "slo_miss": over["slo_miss"],
        "slo_ttft_miss": over["slo_ttft_miss"],
        "slo_met_rate": over["slo_met_rate"],
        "goodput_rps": over["goodput_rps"],
        # queue-inflation visibility: under overload the p99 TTFT carries
        # the queue wait the p50 mostly dodges (reported, not gated —
        # wall-clock ratios on a shared host are noise)
        "ttft_s_p50": over["ttft_s_p50"],
        "ttft_s_p99": over["ttft_s_p99"],
        "unloaded_goodput_rps": un["goodput_rps"],
        "merged_slo_met_rate": merged["slo_met_rate"],
        "gates": gates,
        "gates_ok": all(gates.values()),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--compile-cache-probe", metavar="DIR", default=None,
                    help="internal: run one engine against the persistent "
                         "compile cache at DIR and print its compile "
                         "accounting (spawned by the compile_cache leg)")
    ap.add_argument("--prewarm", action="store_true",
                    help="internal: with --compile-cache-probe, call "
                         "engine.prewarm() before the first submit")
    ap.add_argument("--sampling-only", action="store_true",
                    help="run ONLY the ISSUE 13 sampling block (greedy-"
                         "limit + seeded-replay gates, speculative "
                         "rejection-sampling figures) and print its own "
                         "JSON record — bench.py's `sampling` block")
    ap.add_argument("--chunked-only", action="store_true",
                    help="run ONLY the ISSUE 14 chunked-prefill block "
                         "(TPOT-flat + TTFT-held + whole-prompt parity + "
                         "census gates under a long-prompt stream) and "
                         "print its own JSON record — bench.py's "
                         "`chunked_prefill` block")
    args = ap.parse_args()
    if args.compile_cache_probe is not None:
        _compile_cache_probe(args.compile_cache_probe, prewarm=args.prewarm)
        return
    if QUICK:
        args.requests = min(args.requests, 10)
    if args.sampling_only:
        rec = run_sampling(args.slots, 16 if QUICK else args.requests)
        rec = {"metric": "sampling", **rec, "quick": QUICK,
               "device": str(jax.devices()[0])}
        print(json.dumps(rec), flush=True)
        # the parity gates: temperature->0 that changes tokens, or a
        # seeded replay that drifts, is a correctness regression — fail
        # the block AFTER the record prints
        if not rec["gates_ok"]:
            print(f"sampling gates failed: greedy_limit_mismatches="
                  f"{rec['greedy_limit_mismatches']} replay_mismatches="
                  f"{rec['replay_mismatches']}", file=sys.stderr)
            sys.exit(3)
        return
    if args.chunked_only:
        rec = run_chunked(args.slots, 16 if QUICK else args.requests)
        rec = {"metric": "chunked_prefill", **rec, "quick": QUICK,
               "device": str(jax.devices()[0])}
        print(json.dumps(rec), flush=True)
        # the four chunked gates: decode latency that is NOT flat under
        # long admissions, a starved short request, a token that differs
        # from whole-prompt prefill, or a program-family leak is each a
        # regression — fail the block AFTER the record prints
        if not rec["gates_ok"]:
            print(f"chunked_prefill gates failed: {rec['gates']} "
                  f"(tpot_p99_x={rec['tpot_p99_x']} "
                  f"ttft_p99_x={rec['ttft_p99_x']} "
                  f"output_mismatches={rec['output_mismatches']} "
                  f"census_over={rec['census']['over_budget']})",
                  file=sys.stderr)
            sys.exit(3)
        return

    # tensor-parallel census legs (ISSUE 10) need a multi-chip platform;
    # arm it before ANY jax array exists — single-device legs are
    # unaffected (unsharded jits run on device 0 regardless)
    from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
        ensure_virtual_cpu_devices,
    )

    ensure_virtual_cpu_devices(8)

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    max_len = BUCKET + LONG_NEW + 8
    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM, depth=DEPTH,
                      heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    stream = make_stream(args.requests)

    # warmup leg: compile both paths' programs outside the timed region
    # (the comparison is sustained serving throughput, not compile time)
    warm = make_stream(max(args.slots * 2, 8), seed=1)
    gens: dict = {}
    run_static(model, params, warm, args.slots, max_len, gens)
    _, _, _, eng = run_engine(model, params, warm, args.slots, max_len)
    # reuse the warmed engine's compiled programs; its mutable state is
    # clean after the drain (every retired row was reset), so only the
    # bookkeeping needs a fresh start for the timed leg
    from distributed_tensorflow_ibm_mnist_tpu.serving.stats import ServingStats

    eng.completed.clear()
    eng.stats = ServingStats(args.slots, decode_ahead=eng.decode_ahead)
    eng.scheduler.max_queue = max(eng.scheduler.max_queue, args.requests)

    st_s, st_useful, st_out = run_static(model, params, stream, args.slots,
                                         max_len, gens)
    en_s, en_useful, en_out, eng = run_engine(model, params, stream,
                                              args.slots, max_len, engine=eng)

    # both legs must have produced the SAME useful tokens (greedy parity —
    # the bench refuses to report a speedup bought with different output)
    mismatches = sum(
        not np.array_equal(st_out[i], en_out[i]) for i in range(len(stream)))
    summary = eng.stats.summary()
    result = {
        "metric": "serving",
        "n_requests": len(stream),
        "slots": args.slots,
        "max_len": max_len,
        "prefill_bucket": BUCKET,
        "max_new_mix": {"short": SHORT_NEW, "long": LONG_NEW,
                        "long_every": 4},
        "useful_tokens": st_useful,
        "output_mismatches": mismatches,  # MUST be 0 (greedy parity)
        "static_s": round(st_s, 4),
        "engine_s": round(en_s, 4),
        "static_tokens_per_sec": round(st_useful / st_s, 2),
        "engine_tokens_per_sec": round(en_useful / en_s, 2),
        "engine_over_static": round((en_useful / en_s) / (st_useful / st_s), 3),
        "slot_occupancy": summary["slot_occupancy"],
        "ttft_s_p50": summary["ttft_s_p50"],
        "ttft_s_p95": summary["ttft_s_p95"],
        "ttft_s_p99": summary["ttft_s_p99"],
        "latency_s_p50": summary["latency_s_p50"],
        "latency_s_p99": summary["latency_s_p99"],
        "decode_ahead": run_decode_ahead(
            args.slots, 16 if QUICK else args.requests),
        "prefix_cache": run_prefix_cache(
            model, params, args.slots, 6 if QUICK else 12),
        "compile_census": run_compile_census(args.slots),
        "compile_cache": run_compile_cache(),
        "tracer_overhead": run_tracer_overhead(
            args.slots, 16 if QUICK else 24),
        "telemetry_overhead": run_telemetry_overhead(
            args.slots, 16 if QUICK else 24),
        "slo_goodput": run_slo_goodput(args.slots),
        "quick": QUICK,
        "device": str(jax.devices()[0]),
        "note": (
            "1-core CPU host: the engine pays per-step host-loop overhead a "
            "fused episode hides, so the speedup is a lower bound for "
            "decode-step-dominated hardware; both legs emit identical "
            "greedy tokens (output_mismatches must be 0)"
        ),
    }
    print(json.dumps(result), flush=True)
    # the census GATE: program-family growth past the pinned budgets is a
    # perf regression (compile storms at startup, cache-key churn) — fail
    # the bench run so CI catches it, AFTER the record is printed
    if not result["compile_census"]["census_ok"]:
        print(f"compile census over budget: "
              f"{result['compile_census']['over_budget']}", file=sys.stderr)
        sys.exit(3)
    # the telemetry GATE (ISSUE 11): wired-on sampling must stay within
    # its <=2% budget — unlike tracer_overhead (reported, not gated) this
    # is the acceptance bar for the zero-cost-off contract's ON side
    tel = result["telemetry_overhead"]
    if tel["overhead_frac"] > tel["target_frac"]:
        print(f"telemetry overhead over budget: {tel['overhead_frac']} > "
              f"{tel['target_frac']}", file=sys.stderr)
        sys.exit(3)
    # the SLO/goodput GATE (ISSUE 11): counter arithmetic on the
    # overloaded stream must hold exactly (see run_slo_goodput)
    if not result["slo_goodput"]["gates_ok"]:
        print(f"slo goodput gates failed: {result['slo_goodput']['gates']}",
              file=sys.stderr)
        sys.exit(3)


if __name__ == "__main__":
    main()

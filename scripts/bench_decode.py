"""Decode/serving benchmark with roofline accounting and spread reporting.

The training side earned its numbers with ranges across sessions
(BASELINE.md); this gives the serving side the same discipline (round-5
verdict items 1 and 6):

* every timing is the MEDIAN over ``--reps`` repeat calls (plus min/max),
  with the host-side fence cost measured separately and reported — a
  single-shot decode number on this 1-core host is unfalsifiable noise;
* every row carries its bytes/step roofline: the parameter stream (decode
  params are stored in the model's compute dtype — ``Trainer.
  _decode_params``) plus the K/V cache stream, over the chip's HBM
  bandwidth.  ``roofline_x`` = measured ms / ideal ms, the factor left on
  the table.

Decode is bandwidth-bound: one step reads every block's K/V prefix and the
full parameter set, and does ~2 FLOPs per byte with them — so bytes/step
over HBM bandwidth IS the floor, and the interesting output is how far
each config sits above it.

Usage:
    python scripts/bench_decode.py [--reps 5] [--new 1024] [--hbm-gbps 819]
Prints one JSON line per config and a final summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DIM, DEPTH, HEADS, VOCAB = 512, 4, 8, 64


def build_trainer(**mk):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="bench_decode", model="causal_lm",
        model_kwargs={"dim": DIM, "depth": DEPTH, "heads": HEADS,
                      "attn": "flash", **mk},
        dataset="retrieval", dataset_kwargs={"vocab": VOCAB, "seq_len": 128},
        n_train=256, n_test=128, batch_size=64, epochs=1, quiet=True,
    )
    return Trainer(cfg)


def measure_fence_s() -> float:
    """Median cost of the timing fence itself (device_get of a ready
    scalar through the tunnel) so per-call timings can be read net of it."""
    import jax
    import jax.numpy as jnp

    x = jnp.zeros(())
    jax.device_get(x)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(x)
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def roofline_bytes(trainer, batch: int, kv_span: int, hkv: int):
    """(param_bytes, cache_bytes) one decode step streams from HBM.

    Params: the decode copy's actual leaves (compute dtype after round 5).
    Cache: every block reads K and V over the attended span — max_len for
    full attention, the W-span for windowed decode; int8 caches stream 1
    byte/element plus the per-(position, head) f32 scales.  Writes (one
    position per block) and S=1 activations are noise and not counted.
    """
    import jax

    params = trainer._decode_params()
    pbytes = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
    head_dim = DIM // HEADS
    if trainer.config.model_kwargs.get("kv_cache_dtype") == "int8":
        per_elem = 1
        scales = DEPTH * 2 * batch * kv_span * hkv * 4
    else:
        per_elem, scales = 2, 0  # bf16
    cache_bytes = DEPTH * 2 * batch * kv_span * hkv * head_dim * per_elem + scales
    return pbytes, cache_bytes


def time_config(trainer, batch: int, prompt_len: int, max_new: int,
                max_len: int, reps: int, fence_s: float, hbm_bps: float,
                label: str, kv_span: int | None = None,
                hkv: int | None = None, **gen_kw):
    import jax
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(1, VOCAB - 1, size=(batch, prompt_len)), jnp.int32)
    out = trainer.generate(prompt, max_new=max_new, max_len=max_len, **gen_kw)
    jax.device_get(jnp.sum(out))  # warmup: compile + params placement
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = trainer.generate(prompt, max_new=max_new, max_len=max_len,
                               **gen_kw)
        jax.device_get(jnp.sum(out))
        ts.append(time.perf_counter() - t0)
    med = statistics.median(ts)
    net = max(med - fence_s, 1e-9)  # decode time net of the fence transfer
    pbytes, cbytes = roofline_bytes(trainer, batch, kv_span or max_len,
                                    hkv if hkv is not None else HEADS)
    ideal_ms = (pbytes + cbytes) / hbm_bps * 1e3
    ms_per_step = net / max_new * 1e3
    # GQA-aware analytic step FLOPs (utils/flops.decode_step_flops: kv
    # projection + cache attention at the GROUPED width) over the full
    # attended span — an upper bound per step (the cache fills as the
    # episode runs), consistent with roofline_bytes' span convention
    from distributed_tensorflow_ibm_mnist_tpu.utils.flops import (
        decode_step_flops, mfu)
    # cp=1 spelled out: this bench decodes on a single chip; the cp>1
    # per-chip variant (sequence-sharded KV) is bench_cp_serving's job
    step_flops = decode_step_flops(
        batch, kv_span or max_len, DIM, HEADS, DIM // HEADS,
        heads_kv=hkv, depth=DEPTH, vocab=VOCAB, cp=1)
    step_mfu = mfu(step_flops / (net / max_new))
    row = {
        "config": label, "batch": batch, "prompt_len": prompt_len,
        "max_new": max_new, "max_len": max_len,
        "median_s": round(med, 4), "min_s": round(min(ts), 4),
        "max_s": round(max(ts), 4), "reps": reps,
        "fence_s": round(fence_s, 4),
        "tokens_per_sec": round(batch * max_new / net, 1),
        "ms_per_step": round(ms_per_step, 4),
        "param_mb_per_step": round(pbytes / 1e6, 2),
        "cache_mb_per_step": round(cbytes / 1e6, 2),
        "ideal_ms_per_step": round(ideal_ms, 4),
        "roofline_x": round(ms_per_step / ideal_ms, 2),
        "model_gflops_per_step": round(step_flops / 1e9, 4),
        "mfu": round(step_mfu, 4) if step_mfu is not None else None,
    }
    print(json.dumps(row), flush=True)
    return row


# ----------------------------------------------------------------------
# quant leg (ISSUE 12): weight-only int8 parity gate + d512 bytes model

QUANT_AGREE_FLOOR = 0.9   # greedy token agreement vs full precision
QUANT_DRIFT_BOUND = 0.05  # max |logit drift| / max |logit|, plain forward

QUANT_CONFIGS = [
    ("base", {}),
    ("gqa_window", {"heads_kv": 2, "window": 8}),
    ("tied", {"tie_embeddings": True}),
]

QUANT_PROMPTS = [[1, 2, 3, 1, 2, 3, 1, 2], [4, 5, 4, 5, 4, 5],
                 [6, 7, 8, 9], [2, 4, 2, 4, 2, 4]]


def _quant_serve(model, params, max_len, **ekw):
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler, InferenceEngine)

    eng = InferenceEngine(
        model, params, slots=2, max_len=max_len,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(16,),
                                max_queue=len(QUANT_PROMPTS)),
        **ekw)
    reqs = [eng.submit(p, max_new=6) for p in QUANT_PROMPTS]
    eng.run()
    outs = [list(r.generated) for r in reqs]
    eng.close()
    return outs


def quant_parity_gate() -> int:
    """Greedy-parity gate: every zoo LM config x {dense, paged} x
    decode_ahead {1, 8} x {plain, speculative}, quant engine vs the
    full-precision reference, on BRIEFLY-FIT weights (random init leaves
    near-argmax ties everywhere, which makes greedy agreement
    unfalsifiable noise; a couple of epochs sharpens the logits so the
    floor means something).  One JSON row per cell; returns the breach
    count (caller exits 4 on any).  Paged and speculative cells are
    skipped for windowed configs (the engine rejects both compositions
    with window > 0)."""
    import jax.numpy as jnp
    import numpy as np

    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.models.quant import (
        quantize_params_int8)
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    breaches = 0
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, 16, size=(2, 16)), jnp.int32)
    for name, mk in QUANT_CONFIGS:
        cfg = RunConfig(
            name=f"quant_{name}", model="causal_lm",
            model_kwargs={"dim": 32, "depth": 2, "heads": 4, **mk},
            dataset="retrieval", dataset_kwargs={"vocab": 32, "seq_len": 16},
            n_train=64, n_test=16, batch_size=16, epochs=2, quiet=True,
            eval_batch_size=16,
        )
        t = Trainer(cfg)
        t.fit()
        model, params = t.model, t._decode_params()
        ref_logits = model.apply({"params": params}, tokens)
        q_logits = model.clone(quant="int8").apply(
            {"params": quantize_params_int8(params)}, tokens)
        drift = (float(jnp.max(jnp.abs(ref_logits - q_logits)))
                 / max(float(jnp.max(jnp.abs(ref_logits))), 1e-9))
        ref = _quant_serve(model, params, 32)
        total = sum(len(t_) for t_ in ref)
        # windowed configs serve dense/plain only (the engine rejects
        # paged and speculative compositions with window > 0)
        windowed = bool(mk.get("window", 0))
        for paged in ((False,) if windowed else (False, True)):
            for k in (1, 8):
                for spec in ((False,) if windowed else (False, True)):
                    ekw = {"quant": "int8", "decode_ahead": k}
                    if paged:
                        ekw["kv_page_size"] = 8
                    if spec:
                        ekw.update(speculative="ngram", draft_len=3)
                    got = _quant_serve(model, params, 32, **ekw)
                    agree = sum(a == b for rt, gt in zip(ref, got)
                                for a, b in zip(rt, gt)) / total
                    ok = agree >= QUANT_AGREE_FLOOR and drift < QUANT_DRIFT_BOUND
                    breaches += not ok
                    print(json.dumps({
                        "quant_parity": name,
                        "layout": "paged" if paged else "dense",
                        "decode_ahead": k, "speculative": spec,
                        "agreement": round(agree, 4),
                        "rel_logit_drift": round(drift, 4), "ok": ok,
                    }), flush=True)
    return breaches


def quant_perf_leg(reps: int, hbm_bps: float):
    """d512 serving wave, full precision vs quant, with the bytes-moved
    model.  On emulated CPU the honest claim is the WEIGHT-STREAM bytes
    ratio (the thing a bandwidth-bound chip converts into step time);
    measured wall time is reported but launch-bound here."""
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.models.quant import (
        quantize_params_int8, weight_stream_bytes)

    model = get_model("causal_lm", num_classes=VOCAB, dim=DIM, depth=DEPTH,
                      heads=HEADS, dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    fbytes = weight_stream_bytes(params)
    qbytes = weight_stream_bytes(quantize_params_int8(params))
    out = {}
    for label, ekw in (("f32", {}), ("int8", {"quant": "int8"})):
        _quant_serve(model, params, 32, **ekw)  # warmup: compile family
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            _quant_serve(model, params, 32, **ekw)
            ts.append(time.perf_counter() - t0)
        out[label] = statistics.median(ts)
    row = {
        "quant_perf": f"d{DIM}",
        "weight_mb_f32": round(fbytes / 1e6, 2),
        "weight_mb_int8": round(qbytes / 1e6, 2),
        "weight_bytes_ratio": round(fbytes / qbytes, 2),
        "ideal_step_ms_f32": round(fbytes / hbm_bps * 1e3, 4),
        "ideal_step_ms_int8": round(qbytes / hbm_bps * 1e3, 4),
        "median_wave_s_f32": round(out["f32"], 4),
        "median_wave_s_int8": round(out["int8"], 4),
        "note": "emulated CPU: wall time is launch-bound; the weight "
                "stream ratio is the bandwidth claim",
    }
    print(json.dumps(row), flush=True)
    return row


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--new", type=int, default=1024)
    ap.add_argument("--hbm-gbps", type=float, default=819.0,
                    help="HBM bandwidth (GB/s); 819 = TPU v5e")
    ap.add_argument("--skip-window", action="store_true")
    ap.add_argument("--big", action="store_true",
                    help="add a serving-scale config (dim 2048, depth 6, "
                         "~300M params) where the roofline actually binds")
    ap.add_argument("--quant-only", action="store_true",
                    help="run the int8 weight-quant leg instead: the "
                         "greedy-parity gate (exit 4 on breach) + the d512 "
                         "bytes-moved row")
    args = ap.parse_args()
    hbm = args.hbm_gbps * 1e9

    if args.quant_only:
        breaches = quant_parity_gate()
        perf = quant_perf_leg(max(args.reps - 2, 3), hbm)
        print(json.dumps({
            "metric": "quant_decode",
            "parity_breaches": breaches,
            "parity_ok": breaches == 0,
            "agree_floor": QUANT_AGREE_FLOOR,
            "drift_bound": QUANT_DRIFT_BOUND,
            **{k: v for k, v in perf.items() if k != "quant_perf"},
        }), flush=True)
        sys.exit(4 if breaches else 0)

    import jax

    fence = measure_fence_s()
    print(json.dumps({"fence_s": round(fence, 4),
                      "device": str(jax.devices()[0])}), flush=True)

    rows = []
    trainer = build_trainer()
    for b in (1, 8, 32):
        rows.append(time_config(trainer, b, 64, args.new, 64 + args.new,
                                args.reps, fence, hbm, f"mha_b{b}"))
    # ragged tax at B=8: same shapes, per-row machinery armed
    import numpy as np

    lens = np.asarray([64, 48, 32, 64, 16, 56, 40, 64], np.int32)
    rows.append(time_config(trainer, 8, 64, args.new, 64 + args.new,
                            args.reps, fence, hbm, "mha_b8_ragged",
                            prompt_lens=lens))

    gqa = build_trainer(heads_kv=2)
    rows.append(time_config(gqa, 8, 64, args.new, 64 + args.new,
                            args.reps, fence, hbm, "gqa2_b8", hkv=2))

    if not args.skip_window:
        win = build_trainer(window=1024)
        rows.append(time_config(win, 8, 64, 2048, 8192, max(args.reps - 2, 3),
                                fence, hbm, "win1024_b8_cache8192",
                                kv_span=1024 + 0))
        full = build_trainer()
        rows.append(time_config(full, 8, 64, 2048, 8192,
                                max(args.reps - 2, 3), fence, hbm,
                                "full_b8_cache8192"))
        # int8 KV cache at the same cache-dominated shape (round 5)
        i8 = build_trainer(kv_cache_dtype="int8")
        rows.append(time_config(i8, 8, 64, 2048, 8192,
                                max(args.reps - 2, 3), fence, hbm,
                                "int8_b8_cache8192"))

    if args.big:
        # serving-scale: bytes dominate, launch overhead amortizes — this
        # is the row where roofline_x approaches 1 (see the roofline note
        # in docs/PERFORMANCE.md; the dim-512 rows are launch-bound)
        global DIM, DEPTH, HEADS
        DIM, DEPTH, HEADS = 2048, 6, 16
        big = build_trainer()
        for b in (1, 8):
            rows.append(time_config(big, b, 64, 256, 320, args.reps, fence,
                                    hbm, f"big2048_b{b}"))

    print(json.dumps({"summary": {r["config"]: r["tokens_per_sec"]
                                  for r in rows}}), flush=True)


if __name__ == "__main__":
    main()

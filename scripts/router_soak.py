"""Router soak: the multi-replica serving tier's acceptance proof (ISSUE 8).

One seeded end-to-end story, emitted as one JSON record:

1. **Train W1** — a real causal-LM :class:`Trainer` (retrieval dataset)
   runs one epoch and checkpoints; its decode params are the tier's first
   weight version.
2. **References** — a fault-free SINGLE engine (same shape as the
   replicas: paged KV + radix, ``decode_ahead=2``) generates every wave's
   expected outputs under W1 and, later, W2.  Token identity against
   these is the router's correctness bar: routing, failover, and hot swap
   must be invisible in the tokens.
3. **Wave 1 under chaos** — a :class:`Router` of 3 replicas serves 10
   requests under a seeded plan: a ``router-dispatch`` fault (one replica
   excluded for one request, retried on the next-best) and a
   ``serving-step`` fault on an engine with NO stall watchdog — the raw
   raise fails the whole replica mid-wave.  The router closes it,
   harvests the ``engine_fault`` collateral, and re-dispatches to the
   survivors.  Asserts: exactly one failover, every request ``done``,
   outputs token-identical to the W1 reference, streaming callbacks
   exactly-once per token (the cross-attempt high-water mark).
4. **Restart** — the dead replica respawns through the same factory; the
   persistent compile cache the first spawn populated makes the respawn
   warm (``spawn_s_by_replica`` records cold vs warm bring-up).
5. **Train W2, watch, hot-swap under chaos** — the trainer resumes for a
   second epoch and checkpoints W2.  Bridge requests are IN FLIGHT when
   the :class:`WeightWatcher` polls: poll 1 validates W2 through
   ``restore_latest_intact`` and starts the rollout, but a ``weight-swap``
   chaos hit aborts the first replica's swap (it re-admits on W1, the
   all-or-nothing contract) — the rollout is incomplete, so the poll
   returns None.  Poll 2 retries exactly the straggler and completes.
   Asserts: zero dropped bridge requests, every bridge output identical
   to the W1 OR W2 reference (a request decodes under one version, never
   a mix), rollout completes on poll 2.
6. **Wave 2** — 10 fresh requests after the swap: outputs token-identical
   to the W2 reference on every replica.
7. **Trace** — the shared tracer exports one timeline; asserts it
   validates clean and carries the per-replica tracks plus the
   ``replica_failed`` / ``failover_redispatch`` / ``swap_aborted`` /
   ``weight_swap`` story instants.

The ``serving-step`` kill index is CALIBRATED, not guessed: the factory
warms each fresh engine with a dummy request (so ``spawn_s`` includes the
compile family), and a throwaway engine counts how many host steps that
warmup takes — the kill lands at ``3 * warmup_steps + 4``, i.e. the
second cluster step of wave 1, on replica 1, deterministically.

Usage:  JAX_PLATFORMS=cpu python scripts/router_soak.py
Emits one line: {"metric": "router", ..., "passed": true}.
bench.py runs this in a subprocess as its `router` block
(DTM_BENCH_SKIP_ROUTER=1 skips); a dropped request exits nonzero.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# one engine shape everywhere — references and replicas must run the same
# program family or "token-identical" compares different machines
ENGINE_KW = dict(slots=2, max_len=24, decode_ahead=2, kv_page_size=4)
BUCKETS = (8,)
WARM_PROMPT = [1, 2, 3]
WARM_NEW = 4


def _mk_prompts(seed: int, n: int):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(1, 16, size=(2 + i % 5,)).astype(np.int32)
               for i in range(n)]
    budgets = [3 + i % 4 for i in range(n)]
    return prompts, budgets


def _scheduler():
    from distributed_tensorflow_ibm_mnist_tpu.serving import FIFOScheduler

    return FIFOScheduler(max_len=ENGINE_KW["max_len"], buckets=BUCKETS,
                         max_queue=64)


def _engine(model, params, **kw):
    from distributed_tensorflow_ibm_mnist_tpu.serving import InferenceEngine

    return InferenceEngine(model, params, scheduler=_scheduler(),
                           **ENGINE_KW, **kw)


def _reference(model, params, prompts, budgets):
    """Fault-free single-engine outputs: the identity bar for one wave."""
    eng = _engine(model, params)
    reqs = [eng.submit(p, max_new=b) for p, b in zip(prompts, budgets)]
    eng.run()
    eng.close()
    assert all(r.status == "done" for r in reqs)
    return [list(r.generated) for r in reqs]


def _warmup_steps(model, params) -> int:
    """Count the host steps the factory's warmup request takes — the
    serving-step chaos calibration (every spawn consumes exactly this
    many serving-step events before real traffic)."""
    eng = _engine(model, params)
    eng.submit(WARM_PROMPT, max_new=WARM_NEW)
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
    eng.close()
    return steps


def train_w1(root: str):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    cfg = RunConfig(
        name="router_soak", model="causal_lm",
        model_kwargs={"dim": 32, "depth": 1, "heads": 2, "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 32},
        n_train=128, n_test=32, batch_size=64, epochs=1, quiet=True,
        eval_batch_size=32, checkpoint_dir=os.path.join(root, "ck"),
    )
    t = Trainer(cfg)
    t.fit()
    t.save_checkpoint(wait=True)
    return cfg, t


def train_w2(cfg):
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer

    t2 = Trainer(cfg.replace(resume=True))   # restores W1, one MORE epoch
    t2.fit()
    t2.save_checkpoint(wait=True)
    return t2


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        Router,
        WeightWatcher,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.metrics import MetricWriter
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer

    root = tempfile.mkdtemp(prefix="router_soak_")
    xc_dir = os.path.join(root, "xc")          # persistent compile cache

    # --- phase 1: W1 + references + calibration (no chaos anywhere yet)
    cfg, t1 = train_w1(root)
    model, w1 = t1.model, t1._decode_params()
    step1 = int(np.asarray(t1.state.step))

    p1, b1 = _mk_prompts(11, 10)               # wave 1
    pb, bb = _mk_prompts(12, 3)                # bridge (in flight at swap)
    p2, b2 = _mk_prompts(13, 10)               # wave 2
    want1 = _reference(model, w1, p1, b1)
    n_warm = _warmup_steps(model, w1)

    # --- phase 2: the seeded plan.  serving-step lands on the SECOND
    # cluster step of wave 1 (3 spawns consume 3*n_warm events, then
    # cluster steps consume one per live replica: +4 = step 2, replica 1);
    # router-dispatch faults wave 1's third submit; weight-swap aborts the
    # rollout's FIRST swap attempt.
    plan = FaultPlan(seed=21, faults=(
        FaultSpec(site="serving-step", kind="transient", at=(3 * n_warm + 4,)),
        FaultSpec(site="router-dispatch", kind="io", at=(2,)),
        FaultSpec(site="weight-swap", kind="io", at=(0,)),
    ))
    inj = FaultInjector(plan)
    tracer = Tracer()
    writer = MetricWriter(path=os.path.join(root, "metrics.jsonl"),
                          stdout=False)

    def make_engine(tid):
        eng = _engine(model, w1, stall_timeout_s=None,  # raw raise => failover
                      compile_cache_dir=xc_dir, chaos=inj,
                      tracer=tracer, trace_tid=tid)
        # warm INSIDE the factory so spawn_s includes the compile family:
        # the first spawn pays cold compiles (and writes the persistent
        # cache), every later spawn reads it back — the cold-vs-warm figure
        eng.submit(WARM_PROMPT, max_new=WARM_NEW)
        while eng.has_work:
            eng.step()
        return eng

    router = Router(make_engine, 3, chaos=inj, tracer=tracer, writer=writer)

    # --- phase 3: wave 1 under chaos — dispatch fault + replica kill
    streams: dict[int, list[int]] = {}
    wave1 = [router.submit(p, max_new=b,
                           callback=lambda rr, tok: streams.setdefault(
                               rr.id, []).append(int(tok)))
             for p, b in zip(p1, b1)]
    t0 = time.perf_counter()
    router.run_until_done()
    wave1_wall = time.perf_counter() - t0

    wave1_done = all(rr.status == "done" for rr in wave1)
    wave1_identical = wave1_done and all(
        list(rr.generated) == want1[i] for i, rr in enumerate(wave1))
    # exactly-once: the replayed prefix of a failed-over request is
    # suppressed, so each stream must equal its final output exactly
    exactly_once = all(
        streams.get(rr.id, []) == list(rr.generated) for rr in wave1)
    failed_idx = [r.index for r in router.replicas if r.state == "failed"]
    redispatched = sum(rr.redispatches for rr in wave1)

    # --- phase 4: restart the dead replica (warm via the compile cache)
    restart_s = router.restart(failed_idx[0]) if failed_idx else None

    # --- phase 5: W2, bridge traffic in flight, watched rollout w/ abort
    t2 = train_w2(cfg)
    w2 = t2._decode_params()
    step2 = int(np.asarray(t2.state.step))
    want2 = _reference(model, w2, p2, b2)
    bridge_w1 = _reference(model, w1, pb, bb)
    bridge_w2 = _reference(model, w2, pb, bb)

    bridge = [router.submit(p, max_new=b) for p, b in zip(pb, bb)]
    for _ in range(2):                      # bridge decode genuinely starts
        router.step()
    watcher = WeightWatcher(cfg.checkpoint_dir, t1.state, router,
                            extract=lambda s: s.params)
    poll1 = watcher.poll()                  # W2 validated; first swap aborted
    poll2 = watcher.poll()                  # straggler retried; rollout done
    router.run_until_done()

    bridge_done = all(rr.status == "done" for rr in bridge)
    bridge_ok = bridge_done and all(
        list(rr.generated) in (bridge_w1[i], bridge_w2[i])
        for i, rr in enumerate(bridge))
    rollout_ok = (poll1 is None and poll2 == step2
                  and router.swapped_steps == [step2]
                  and all(r.weight_step == step2 for r in router.replicas))

    # --- phase 6: wave 2 — every replica now serves W2
    wave2 = [router.submit(p, max_new=b) for p, b in zip(p2, b2)]
    router.run_until_done()
    wave2_identical = all(
        rr.status == "done" and list(rr.generated) == want2[i]
        for i, rr in enumerate(wave2))

    dropped = sum(rr.status != "done" for rr in router.requests)
    summary = router.summary()
    router.close()                          # emits the merged router record
    writer.close()

    # --- phase 7: the timeline must tell the whole story, validly
    trace_path = os.path.join(root, "trace.json")
    tracer.export_trace(trace_path)
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import validate_trace

    problems = validate_trace(trace_path)
    with open(trace_path) as f:
        events = json.load(f)["traceEvents"]
    tracks = {e["args"]["name"] for e in events
              if e.get("ph") == "M" and e.get("name") == "thread_name"}
    instants = {e["name"] for e in events if e.get("ph") == "i"}
    trace_ok = (not problems
                and {"router", "replica 0", "replica 1", "replica 2"} <= tracks
                and {"replica_spawn", "replica_failed", "failover_redispatch",
                     "dispatch_fault", "swap_aborted", "weight_swap"}
                <= instants)

    spawn_hist = summary["spawn_s_by_replica"]
    record = {
        "metric": "router",
        "n_replicas": 3,
        "router_requests": len(router.requests),
        "dropped": dropped,
        "wave1": {
            "n": len(wave1), "identical": wave1_identical,
            "exactly_once_streams": exactly_once,
            "failovers": router.failovers, "redispatched": redispatched,
            "wall_s": round(wave1_wall, 3),
        },
        "restart": {
            "replica": failed_idx[0] if failed_idx else None,
            "spawn_s": round(restart_s, 3) if restart_s is not None else None,
        },
        "hot_swap": {
            "steps": [step1, step2], "poll1": poll1, "poll2": poll2,
            "rollout_complete": rollout_ok,
            "bridge_n": len(bridge), "bridge_ok": bridge_ok,
            "watcher_polls": watcher.polls, "watcher_skipped": watcher.skipped,
        },
        "wave2": {"n": len(wave2), "identical": wave2_identical},
        "bringup": {
            # replica 0's first spawn compiled cold and wrote the cache;
            # every other spawn (replicas 1-2, the restart) read it back
            "cold_spawn_s": round(spawn_hist[0][0], 3),
            "warm_spawn_s": [round(s, 3)
                             for i, hist in enumerate(spawn_hist)
                             for j, s in enumerate(hist)
                             if (i, j) != (0, 0)],
            "spawn_s_by_replica": spawn_hist,
        },
        "cluster": {k: summary.get(k) for k in (
            "n_engines", "n_requests", "n_done", "n_failed", "n_cancelled",
            "n_engine_fault", "weight_swaps", "failovers",
            "tokens_generated", "n_compiled_programs")},
        "faults": inj.summary(),
        "trace": {"valid": not problems, "problems": problems,
                  "tracks": sorted(tracks), "ok": trace_ok},
        "passed": bool(
            wave1_identical and exactly_once and router.failovers == 1
            and redispatched >= 1 and bridge_ok and rollout_ok
            and wave2_identical and dropped == 0 and trace_ok),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

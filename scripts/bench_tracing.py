"""End-to-end distributed-tracing gate (ISSUE 19 tentpole).

Every leg gates a STRUCTURAL property of the trace layer (standing CPU
caveat: no tokens/sec claims), end to end through real sockets where the
property lives on the wire:

1. **failover** — ``daemon-pump`` chaos kills one of two pumps while SSE
   clients are connected.  Every stream that finishes ``done`` must
   yield a CONNECTED span tree — HTTP accept through admission, queue,
   prefill, decode — under the trace id the front door echoed in
   ``traceparent``, and at least one replayed dispatch must carry a span
   **link** back to the attempt that died.  ``validate_trace`` must be
   clean on the export.
2. **disagg** — a prefill/decode tier where the front door runs its OWN
   tracer (two processes in miniature): per-tracer exports are islands,
   the ``merge_traces`` document must join them through the hex
   ``span_ctx``/``parent_ctx`` edge and show ``gather``/``install``
   handoff spans inside each connected tree.
3. **recovery** — requests journaled by a daemon that never starts (the
   crash), replayed via :func:`recover` into a SECOND tracer.  The
   replayed requests must carry their original ``traceparent`` bit for
   bit (the journal round-trips the trace identity), the post-crash
   export must validate clean, and the merged pre+post document must
   join both process generations into one tree per trace (siblings of
   the same lost front-door ctx).
4. **overhead** — alternating ctx-off / ctx-on waves against the same
   warmed, already-traced tier: the marginal wall cost of the
   distributed layer (mint + head sampling + daemon spans + ctx plumb),
   min-of-waves, must stay within 2%.  The tracer-off total rides along
   informationally.

Usage:  JAX_PLATFORMS=cpu python scripts/bench_tracing.py
Emits one JSON line (``"metric": "tracing"``); exits nonzero when any
gate fails.  ``DTM_BENCH_QUICK=1`` shrinks the waves to a tier-1-safe
smoke.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

MODEL_KW = dict(num_classes=16, dim=32, depth=1, heads=2,
                dtype=jnp.float32)
MAX_NEW = 4
N_FAIL = 4 if QUICK else 10
N_DISAGG = 3 if QUICK else 6
N_REC = 3 if QUICK else 4
N_OVER = 6 if QUICK else 12
N_WAVES = 3 if QUICK else 5
WAIT_S = 120.0
OVERHEAD_GATE = 0.02

_MODEL = None


def _model_params():
    global _MODEL
    if _MODEL is None:
        from distributed_tensorflow_ibm_mnist_tpu.models import get_model
        model = get_model("causal_lm", **MODEL_KW)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"]
        _MODEL = (model, params)
    return _MODEL


def _mk_prompts(seed: int, n: int):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, 16, size=(2 + i % 5,))]
            for i in range(n)]


def _factory(tracer=None, chaos=None, roles=None):
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )
    model, params = _model_params()

    def make_engine(tid, index):
        kw = {} if roles is None else {"role": roles[index]}
        return InferenceEngine(
            model, params, slots=2, max_len=16, kv_page_size=4,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=64),
            tracer=tracer, trace_tid=tid, chaos=chaos, **kw)

    return make_engine


def _pools_zero(router) -> bool:
    for rep in router.replicas:
        if not rep.alive or rep.engine._pool is None:
            continue
        eng = rep.engine
        if eng._radix is not None:
            stack = [eng._radix.root]
            while stack:
                node = stack.pop()
                if node.ref != 0:
                    return False
                stack.extend(node.children.values())
            if eng._pool.allocated != eng._radix.n_blocks:
                return False
        elif eng._pool.allocated != 0:
            return False
    return True


def _teardown(daemon, fd=None) -> dict:
    if fd is not None:
        fd.stop()
    drained = daemon.drain(timeout=30.0)
    pools = _pools_zero(daemon.router)
    daemon.close()
    return {"drained_clean": drained, "pools_zero": pools}


def _tree_ok(forest, trace_id, need: set) -> bool:
    g = forest.get(trace_id)
    return (g is not None and g["connected"]
            and need <= set(g["names"]))


def leg_failover(tmpdir: str) -> dict:
    """Pump kill under connected SSE clients: every finished stream's
    trace must be one connected tree and the redispatch must link back
    to the dead attempt."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FrontDoor,
        FrontDoorClient,
        Router,
        ServingDaemon,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        TraceContext,
        Tracer,
        trace_forest,
        validate_trace,
    )

    inj = FaultInjector(FaultPlan(seed=5, faults=(
        FaultSpec(site="daemon-pump", kind="raise", at=(0,)),)))
    tracer = Tracer()
    router = Router(_factory(tracer=tracer, chaos=inj), 2,
                    chaos=inj, tracer=tracer)
    router.prewarm()
    daemon = ServingDaemon(router, max_queue=64,
                           liveness_timeout_s=30.0).start()
    fd = FrontDoor(daemon).start_in_thread()

    results: dict[int, dict] = {}
    lock = threading.Lock()

    def client(i, prompt):
        cli = FrontDoorClient("127.0.0.1", fd.port, timeout=WAIT_S)
        toks = list(cli.stream(prompt, MAX_NEW, deadline_s=WAIT_S,
                               extra_headers={"X-Request-Id": f"fo-{i}"}))
        with lock:
            results[i] = {"tokens": toks, "terminal": cli.last_terminal,
                          "tp": (cli.last_headers or {}).get("traceparent")}

    threads = [threading.Thread(target=client, args=(i, p))
               for i, p in enumerate(_mk_prompts(22, N_FAIL))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=WAIT_S)
    failovers = daemon.router.failovers
    down = _teardown(daemon, fd)

    path = os.path.join(tmpdir, "failover.json")
    tracer.export_trace(path)
    problems = validate_trace(path)
    doc = json.load(open(path))
    forest = trace_forest(doc)
    need = {"http_request", "daemon_request", "request",
            "prefill", "decode"}
    done = incomplete = 0
    for got in results.values():
        term = got["terminal"]
        if term is None or term.get("status") != "done":
            continue
        done += 1
        ctx = TraceContext.parse_traceparent(got["tp"])
        if ctx is None or not _tree_ok(forest, ctx.trace_id, need):
            incomplete += 1
    linked = sum(1 for e in doc["traceEvents"]
                 if e.get("args", {}).get("links"))
    return {
        "streams": len(results), "streams_done": done,
        "incomplete_traces": incomplete, "failovers": failovers,
        "linked_spans": linked, "validate_problems": problems,
        "open_spans": tracer.open_spans, "faults": inj.summary(),
        **down,
    }


def leg_disagg(tmpdir: str) -> dict:
    """Prefill/decode tier with the front door on its OWN tracer: only
    the merged document may connect the HTTP span to the tier's tree,
    through the hex span_ctx/parent_ctx edge."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FrontDoor,
        FrontDoorClient,
        Router,
        ServingDaemon,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        TraceContext,
        Tracer,
        merge_traces,
        trace_forest,
        validate_trace,
    )

    front_tr, tier_tr = Tracer(), Tracer()
    roles = ["prefill", "decode"]
    router = Router(_factory(tracer=tier_tr, roles=roles), 2,
                    roles=roles, tracer=tier_tr)
    router.prewarm()
    daemon = ServingDaemon(router, max_queue=64,
                           liveness_timeout_s=30.0).start()
    fd = FrontDoor(daemon, tracer=front_tr).start_in_thread()

    cli = FrontDoorClient("127.0.0.1", fd.port, timeout=WAIT_S)
    tps = []
    for prompt in _mk_prompts(33, N_DISAGG):
        toks = list(cli.stream(prompt, MAX_NEW, deadline_s=WAIT_S))
        tps.append(((cli.last_headers or {}).get("traceparent"),
                    cli.last_terminal, toks))
    handoffs = router.handoffs
    down = _teardown(daemon, fd)

    path = os.path.join(tmpdir, "disagg.json")
    doc = merge_traces([front_tr, tier_tr], path,
                       names=["frontdoor", "tier"])
    problems = validate_trace(path)
    forest = trace_forest(doc)
    # without the merge each tracer alone is an island: the front span
    # has no in-process child, the tier root a dangling parent_ctx
    islands = trace_forest(tier_tr.to_doc())
    need = {"http_request", "daemon_request", "request",
            "gather", "install"}
    done = incomplete = split_before_merge = 0
    for tp, term, _toks in tps:
        if term is None or term.get("status") != "done":
            continue
        done += 1
        ctx = TraceContext.parse_traceparent(tp)
        if ctx is None or not _tree_ok(forest, ctx.trace_id, need):
            incomplete += 1
        if ctx is not None:
            g = islands.get(ctx.trace_id)
            if g is not None and "http_request" not in g["names"]:
                split_before_merge += 1
    return {
        "streams": len(tps), "streams_done": done,
        "incomplete_traces": incomplete, "handoffs": handoffs,
        "split_before_merge": split_before_merge,
        "validate_problems": problems,
        "open_spans": front_tr.open_spans + tier_tr.open_spans,
        **down,
    }


def leg_recovery(tmpdir: str) -> dict:
    """Crash-replay continuity: the journal must round-trip each
    request's traceparent, and the merged pre+post export must show ONE
    tree per trace spanning both process generations."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        RequestJournal,
        Router,
        ServingDaemon,
    )
    from distributed_tensorflow_ibm_mnist_tpu.serving.journal import recover
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        TraceContext,
        Tracer,
        merge_traces,
        trace_forest,
        validate_trace,
    )

    jdir = os.path.join(tmpdir, "journal")
    pre_tr = Tracer()
    j = RequestJournal(jdir)
    crashed = ServingDaemon(Router(_factory(tracer=pre_tr), 1,
                                   tracer=pre_tr),
                            max_queue=64, journal=j)
    wanted = []
    for i, prompt in enumerate(_mk_prompts(44, N_REC)):
        ctx = TraceContext.mint()
        crashed.submit(prompt, MAX_NEW, trace_ctx=ctx,
                       idempotency_key=f"rk-{i}")
        wanted.append(ctx.to_traceparent())
    j.sync()   # simulated SIGKILL: journal durable, daemon never starts

    post_tr = Tracer()
    rec = recover(jdir, lambda: ServingDaemon(
        Router(_factory(tracer=post_tr), 1, tracer=post_tr),
        max_queue=64, journal=RequestJournal(jdir)))
    finished = rec.wait(WAIT_S)
    replayed = [(r.dr.trace_ctx.to_traceparent()
                 if getattr(r.dr, "trace_ctx", None) is not None else None)
                for r in rec.requests]
    continuity = sorted(tp for tp in replayed if tp) == sorted(wanted)
    down = _teardown(rec.daemon)

    post_path = os.path.join(tmpdir, "recovery_post.json")
    post_tr.export_trace(post_path)
    problems = validate_trace(post_path)
    merged_path = os.path.join(tmpdir, "recovery_merged.json")
    # the pre-crash tracer died mid-request: its daemon_request spans are
    # legitimately unclosed (ph "B"), so the merged doc is for the
    # forest, not for validate_trace
    doc = merge_traces([pre_tr, post_tr], merged_path,
                       names=["gen0", "gen1"])
    forest = trace_forest(doc)
    joined = 0
    for tp in wanted:
        ctx = TraceContext.parse_traceparent(tp)
        g = forest.get(ctx.trace_id)
        if (g is not None and g["connected"]
                and [e["name"] for e in doc["traceEvents"]
                     if e.get("args", {}).get("trace") == ctx.trace_id
                     and e["name"] == "daemon_request"]):
            joined += 1
    return {
        "journaled": len(wanted), "replayed": len(rec.requests),
        "finished": finished, "continuity": continuity,
        "generations_joined": joined,
        "pre_open_spans": pre_tr.open_spans,
        "validate_problems": problems,
        "post_open_spans": post_tr.open_spans,
        "incomplete_at_scan": rec.scan.report()["incomplete"],
        **down,
    }


def leg_overhead() -> dict:
    """Tracing-layer cost as a SHARE of serving wall, self-measured.

    Paired wall-clock deltas cannot resolve a 2% budget here: on a
    shared CPU box the min-of-20-waves ratio swings ±5% run to run
    (measured), and at dim-32 the model step is so small that any
    constant per-request cost is magnified far beyond what a real
    deployment would see.  So — like bench_crash's ``append_share`` —
    the gate measures the instrumentation DIRECTLY: every tracer entry
    point plus :meth:`TraceContext.mint` is wrapped with a timer, and
    the gated number is the MARGINAL tracing-time share — ctx-on waves'
    accumulated tracer time minus ctx-off waves' (the tier's own
    window/dispatch/readback spans fire in both configs and cancel),
    over the ctx-on wall.  Numerator and denominator come from the same
    run, so scheduler noise cancels; the wrapper's own cost lands in
    the numerator, making the share conservative.  The paired ctx-on /
    ctx-off wall ratio is reported informationally (noisy), as is a
    tracer-off tier's total."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        Router,
        ServingDaemon,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        TraceContext,
        Tracer,
    )

    prompts = _mk_prompts(55, N_OVER)

    def build(tracer):
        router = Router(_factory(tracer=tracer), 1, tracer=tracer)
        router.prewarm()
        return ServingDaemon(router, max_queue=64,
                             liveness_timeout_s=30.0).start()

    spent = {"s": 0.0}

    def timed(fn):
        def wrapped(*a, **k):
            t0 = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                spent["s"] += time.perf_counter() - t0
        return wrapped

    def mint():
        t0 = time.perf_counter()
        try:
            return TraceContext.mint()
        finally:
            spent["s"] += time.perf_counter() - t0

    def wave(daemon, traced: bool) -> float:
        t0 = time.perf_counter()
        drs = [daemon.submit(p, MAX_NEW,
                             trace_ctx=mint() if traced else None)
               for p in prompts]
        for dr in drs:
            dr.wait(timeout=WAIT_S)
        return time.perf_counter() - t0

    tracer = Tracer()
    for name in ("begin", "end", "complete", "instant", "annotate",
                 "track"):
        setattr(tracer, name, timed(getattr(tracer, name)))
    tier = build(tracer)
    for _ in range(3):             # warm: compile, pools, thread spin-up
        wave(tier, False)
        wave(tier, True)
    off_w: list[float] = []
    on_w: list[float] = []
    off_spent = on_spent = 0.0
    # gen2 collections of the earlier legs' tiers otherwise land INSIDE
    # wrapped tracer calls and read as tracing time
    gc.collect()
    gc.disable()
    try:
        for _ in range(2 * N_WAVES):
            s0 = spent["s"]
            off_w.append(wave(tier, False))
            off_spent += spent["s"] - s0
            s0 = spent["s"]
            on_w.append(wave(tier, True))
            on_spent += spent["s"] - s0
    finally:
        gc.enable()
    # the tier's own window/dispatch/readback spans fire in BOTH
    # configs — subtracting the ctx-off tracer time leaves exactly what
    # enabling distributed tracing added
    share = max(0.0, on_spent - off_spent) / sum(on_w)
    down_t = _teardown(tier)
    bare = build(None)             # informational total, after the
    wave(bare, False)              # gated phase so it cannot perturb it
    bare_w = [wave(bare, False) for _ in range(N_WAVES)]
    down_b = _teardown(bare)
    return {
        "waves": len(off_w), "requests_per_wave": len(prompts),
        "ctx_off_min_s": round(min(off_w), 4),
        "ctx_on_min_s": round(min(on_w), 4),
        "overhead": round(share, 4),
        "paired_wall_ratio": round(min(on_w) / min(off_w) - 1.0, 4),
        "traced_vs_bare": round(min(on_w) / min(bare_w) - 1.0, 4),
        "open_spans": tracer.open_spans,
        "drained_clean": down_b["drained_clean"] and down_t["drained_clean"],
        "pools_zero": down_b["pools_zero"] and down_t["pools_zero"],
    }


def main() -> None:
    with tempfile.TemporaryDirectory() as td:
        failover = leg_failover(td)
        disagg = leg_disagg(td)
        recovery = leg_recovery(td)
    overhead = leg_overhead()
    gates = {
        "failover_happened": failover["failovers"] >= 1,
        "failover_traces_connected": failover["streams_done"] >= 1
        and failover["incomplete_traces"] == 0,
        "failover_links_present": failover["linked_spans"] >= 1,
        "failover_validate_clean": failover["validate_problems"] == [],
        "disagg_handoffs": disagg["handoffs"] >= disagg["streams_done"] >= 1,
        "disagg_traces_connected": disagg["incomplete_traces"] == 0,
        "disagg_merge_required": disagg["split_before_merge"]
        == disagg["streams_done"],
        "disagg_validate_clean": disagg["validate_problems"] == [],
        "recovery_continuity": recovery["continuity"]
        and recovery["replayed"] == recovery["journaled"],
        "recovery_finished": recovery["finished"],
        "recovery_generations_joined": recovery["generations_joined"]
        == recovery["journaled"],
        "recovery_validate_clean": recovery["validate_problems"] == [],
        "overhead_le_2pct": overhead["overhead"] <= OVERHEAD_GATE,
        "no_open_spans": failover["open_spans"] == 0
        and disagg["open_spans"] == 0
        and recovery["post_open_spans"] == 0
        and overhead["open_spans"] == 0,
        "drained_clean": all(l["drained_clean"] and l["pools_zero"]
                             for l in (failover, disagg, recovery, overhead)),
    }
    record = {
        "metric": "tracing",
        "quick": QUICK,
        "failover": failover,
        "disagg": disagg,
        "recovery": recovery,
        "overhead": overhead,
        "gates": gates,
        "passed": all(gates.values()),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

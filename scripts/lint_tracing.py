#!/usr/bin/env python
"""Static tracing-contract lint for the serving tier (ISSUE 19 satellite).

Two invariants keep tracing zero-cost-off and clock-sane, and both are
mechanical enough to enforce with ``ast`` instead of code review:

1. **Nil-guard contract.**  Every call through a ``_tracer`` attribute
   (``self._tracer.begin(...)``, ``engine._tracer.complete(...)``) must
   be guarded the way ``_chaos``/``_telemetry``/``_journal`` calls are:
   either lexically inside the body of an ``if <x>._tracer is not
   None:`` (or the else-branch of an ``is None`` test), or in a function
   that already bailed early through ``if <x>._tracer is None:
   return/raise/continue``.  An unguarded call is a crash on the
   default ``tracer=None`` configuration — the exact configuration the
   overhead gate (`scripts/bench_tracing.py`) promises costs nothing.

2. **Monotonic-clock contract.**  Serving code must not read
   ``time.time()``: span math runs on the tracer's ``time.monotonic``
   domain, and a wall-clock read silently produces garbage durations
   the moment NTP steps the clock.  ``serving/journal.py`` is the one
   allowlisted file — its two wall-clock reads are the *intentional*
   restart-surviving timestamps the journal format documents.

Run as a script (``python scripts/lint_tracing.py``) for CI — exits
nonzero listing every violation — or import :func:`check_source` /
:func:`check_file` from tests (tests/test_lint_tracing.py wires this
into tier 1, so the contract regresses loudly, not silently).
"""

from __future__ import annotations

import ast
import os
import sys

# files whose time.time() reads are intentionally wall-clock (the
# journal's restart-surviving timestamps) — everything else in serving/
# must stay on the tracer's monotonic domain
WALL_CLOCK_ALLOWLIST = ("journal.py",)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:          # pragma: no cover - unparse is stdlib-solid
        return ""


def _is_tracer_call(node: ast.Call) -> bool:
    """``<anything>._tracer.<method>(...)`` — a call THROUGH the tracer."""
    f = node.func
    return (isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "_tracer")


def _is_wall_clock_call(node: ast.Call) -> bool:
    """``time.time()`` exactly (not ``self.clock()``/``time.monotonic``)."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _guard_exprs(test: ast.AST, op: type) -> list[str]:
    """The atomic comparison sources inside a boolean-joined if-test —
    splitting on ``op`` only: ``and`` for the positive guard (every
    conjunct must hold in the body) and ``or`` for the bail-out guard
    (any disjunct fires the early return / forces the else branch)."""
    if isinstance(test, ast.BoolOp) and isinstance(test.op, op):
        out: list[str] = []
        for v in test.values:
            out.extend(_guard_exprs(v, op))
        return out
    return [_unparse(test)]


def _tests_not_none(test: ast.AST) -> bool:
    return any(s.endswith("._tracer is not None") or s == "_tracer is not None"
               for s in _guard_exprs(test, ast.And))


def _tests_is_none(test: ast.AST) -> bool:
    return any(s.endswith("._tracer is None") or s == "_tracer is None"
               for s in _guard_exprs(test, ast.Or))


def _bails(stmts: list[ast.stmt]) -> bool:
    """Does this branch end control flow (early-return guard shape)?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue))


class _Walker(ast.NodeVisitor):
    """Tracks, for every node, the ancestor (node, field) path — enough
    to decide which BRANCH of an ``if`` a tracer call lives in."""

    def __init__(self, filename: str):
        self.filename = filename
        self.path: list[tuple[ast.AST, str]] = []
        self.violations: list[str] = []

    # -- guard resolution ------------------------------------------------

    def _guarded(self, call: ast.Call) -> bool:
        func_node = None
        derived: list[str] = []   # `if <name> is not None:` guard names
        for node, field in reversed(self.path):
            if isinstance(node, (ast.If, ast.IfExp)):
                if field == "body" and _tests_not_none(node.test):
                    return True
                if field == "orelse" and _tests_is_none(node.test):
                    return True
                if field == "body":
                    src = _unparse(node.test)
                    if src.endswith(" is not None"):
                        derived.append(src[: -len(" is not None")])
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and func_node is None):
                func_node = node
        if func_node is None:
            return False
        for stmt in ast.walk(func_node):
            # early-return form: ``if ..._tracer is None: return`` earlier
            # in the same function covers everything after it
            if (isinstance(stmt, ast.If) and _tests_is_none(stmt.test)
                    and _bails(stmt.body)
                    and stmt.lineno < call.lineno):
                return True
            # derived-guard form: the call sits under ``if span is not
            # None:`` and `span` was itself assigned tracer-conditionally
            # (``span = ... if self._tracer is not None ... else None``)
            if (isinstance(stmt, ast.Assign) and derived
                    and stmt.lineno < call.lineno
                    and "_tracer is not None" in _unparse(stmt.value)):
                for tgt in stmt.targets:
                    if _unparse(tgt) in derived:
                        return True
        return False

    # -- traversal -------------------------------------------------------

    def generic_visit(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            if _is_tracer_call(node):
                if not self._guarded(node):
                    self.violations.append(
                        f"{self.filename}:{node.lineno}: unguarded tracer "
                        f"call `{_unparse(node.func)}(...)` — wrap in "
                        f"`if ..._tracer is not None:`")
            if (_is_wall_clock_call(node)
                    and os.path.basename(self.filename)
                    not in WALL_CLOCK_ALLOWLIST):
                self.violations.append(
                    f"{self.filename}:{node.lineno}: time.time() in serving "
                    f"code — use the tracer/engine monotonic clock "
                    f"(wall-clock is journal.py's exception, by design)")
        for field, value in ast.iter_fields(node):
            if isinstance(value, list):
                for item in value:
                    if isinstance(item, ast.AST):
                        self.path.append((node, field))
                        self.generic_visit(item)
                        self.path.pop()
            elif isinstance(value, ast.AST):
                self.path.append((node, field))
                self.generic_visit(value)
                self.path.pop()


def check_source(src: str, filename: str = "<string>") -> list[str]:
    """Lint one source string; returns violation messages (empty = clean)."""
    w = _Walker(filename)
    w.generic_visit(ast.parse(src, filename=filename))
    return w.violations


def check_file(path: str) -> list[str]:
    with open(path, encoding="utf-8") as f:
        return check_source(f.read(), path)


def serving_dir() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "distributed_tensorflow_ibm_mnist_tpu", "serving")


def check_serving() -> list[str]:
    """Lint every module in the serving package."""
    out: list[str] = []
    for name in sorted(os.listdir(serving_dir())):
        if name.endswith(".py"):
            out.extend(check_file(os.path.join(serving_dir(), name)))
    return out


def main() -> int:
    violations = check_serving()
    for v in violations:
        print(v)
    n = len([f for f in os.listdir(serving_dir()) if f.endswith(".py")])
    print(f"lint_tracing: {n} files, {len(violations)} violation(s)")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())

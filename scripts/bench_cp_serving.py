"""Context-parallel serving: sequence-sharded KV, long prompts, census.

The ISSUE 20 acceptance harness, in four legs:

* **census** (first — the process is still cold) — a cp=2 paged engine's
  ``prewarm()`` compile delta is the COLD budget (``cp_cold``), and a
  full serve after prewarm must compile ZERO new programs
  (``cp_repeat == 0``): the one-program-per-(site, shape-key) claim,
  with the cp-qualified site names (``prefill[b16,cp2]``) pinned in the
  report.
* **memory** — one model served at cp ∈ {1, 2, 4} with an EXPLICIT,
  identical ``kv_pages`` (divisible by every cp, so the pool is the
  same size everywhere and the ratio means layout, not rounding):
  per-chip KV bytes must land at 1/cp of the cp=1 figure (±10% — the
  replicated block table/index is the honest tax), and greedy output
  must be token-identical across cp.
* **long prompt** — the headline: a synthetic single-chip KV budget of
  60% of the cp=1 footprint, which the cp=1 engine EXCEEDS and every
  cp > 1 engine fits.  A prompt long enough to need that footprint is
  admitted, prefills through the ring, and decodes to EXACT greedy AND
  seeded-sampled token parity against a truncation-free cp=1 reference
  (same ``max_len`` — on this emulation box the cp=1 engine physically
  fits, which is exactly what makes it the honest reference).  Analytic
  per-hop ring traffic (utils/flops.ring_hop_bytes) rides the report.
* **chaos** — the event clock is cp-invariant: serving-admit /
  serving-step / kv-handoff counts (the latter through a REAL disagg
  prefill→decode tier at cp ∈ {1, 2}) must be identical across cp,
  with token parity and every request retired ``done``.

Exit status: 2 = census breach, 3 = memory gate breach, 4 = long-prompt
parity/budget breach, 5 = chaos invariance breach.  Designed for a
SUBPROCESS (bench.py spawns it with ``JAX_PLATFORMS=cpu``, skippable via
``DTM_BENCH_SKIP_CP=1``); self-arms 8 virtual CPU devices when run
directly:

    python scripts/bench_cp_serving.py

Prints ONE JSON line (metric "cp_serving").  Honest caveat carried in
the record: on this host the "chips" are virtual CPU devices, so the
BYTES-per-chip figures are layout-exact (the sharding is real) while
wall-clock says nothing about real ICI — the ring hops are memcpys
here; the per-hop byte counts are the analytic charge a real
interconnect would carry.

``DTM_BENCH_QUICK=1`` drops cp=4 everywhere.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

# memory/long legs: big enough that the paged pool dominates the
# replicated block-table tax, small enough for CPU emulation
MEM_KW = dict(num_classes=64, dim=256, depth=4, heads=8)
# census/chaos legs: small and fast
SMALL_KW = dict(num_classes=16, dim=64, depth=2, heads=4)

PROMPTS = [
    [1, 2, 3, 4, 1, 2, 3, 4, 1, 2],
    [5, 6, 5, 6, 5, 6, 5],
    [7, 8, 9, 7, 8, 9],
    [2, 4, 2, 4, 2, 4, 2, 4],
]

# cold-compile budget for the cp=2 program family (prefill + insert +
# extend + pick + window + reset + host glue); generous headroom over
# the ~14 measured so a new tiny program is a nudge, not a page
CP_COLD_BUDGET = 26


def _model_and_params(kw, **over):
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    model = get_model("causal_lm", dtype=jnp.float32, **{**kw, **over})
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(model, params, max_len, *, cp=1, buckets=(16,), n_queue=8,
            **ekw):
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )

    return InferenceEngine(
        model, params, slots=2, max_len=max_len, cp=cp,
        scheduler=FIFOScheduler(max_len=max_len, buckets=buckets,
                                max_queue=n_queue),
        **ekw)


def _serve(eng, prompts, max_new=8, sampling=None):
    reqs = [eng.submit(p, max_new=max_new, sampling=sampling)
            for p in prompts]
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    outs = [list(r.generated) for r in reqs]
    return outs, sum(len(o) for o in outs) / dt


def run_census_leg() -> dict:
    """cp_cold = prewarm's compile bill, cp_repeat = 0 after it."""
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        CompileTracker,
    )

    model, params = _model_and_params(SMALL_KW)
    tracker = CompileTracker.install()
    eng = _engine(model, params, 32, cp=2, kv_page_size=8)
    warm = eng.prewarm()
    before = tracker.snapshot()
    outs, _ = _serve(eng, PROMPTS, max_new=6)
    d = CompileTracker.delta(tracker.snapshot(), before)
    eng.close()
    cp_sites = sorted(s for s in warm["by_site"] if ",cp2]" in s
                      or s.endswith("[cp2]"))
    return {
        "cp_cold": warm["programs"],
        "cp_cold_budget": CP_COLD_BUDGET,
        "cp_repeat": d["n_compiled_programs"],
        "repeat_by_site": d["by_site"],
        "cp_sites": cp_sites,
        "ok": (warm["programs"] <= CP_COLD_BUDGET
               and d["n_compiled_programs"] == 0
               and any(s.startswith("prefill[") for s in cp_sites)),
    }


def run_memory_leg(cps) -> dict:
    """Per-chip KV bytes 1/cp (±10%) at a FIXED pool size, token parity."""
    model, params = _model_and_params(MEM_KW)
    max_len = 48
    # explicit pool size divisible by every cp under test: the ratio
    # then measures the sequence sharding, not default-rounding slack
    kv_pages = 16
    rows, ref, mismatches = {}, None, 0
    for cp in cps:
        eng = _engine(model, params, max_len, cp=cp, kv_page_size=8,
                      kv_pages=kv_pages)
        outs, tok_s = _serve(eng, PROMPTS)
        w, kv = eng.weight_bytes_per_chip(), eng.kv_bytes_per_chip()
        eng.close()
        if ref is None:
            ref = outs
        elif outs != ref:
            mismatches += 1
        rows[str(cp)] = {
            "kv_bytes_per_chip": kv,
            "weight_bytes_per_chip": w,  # replicated over cp — flat
            "useful_tokens_per_sec": round(tok_s, 2),
        }
    kv1 = rows["1"]["kv_bytes_per_chip"]
    ratio_ok = True
    for cp in cps:
        ratio = kv1 / rows[str(cp)]["kv_bytes_per_chip"]
        rows[str(cp)]["kv_reduction_vs_cp1"] = round(ratio, 3)
        if not (0.9 * cp <= ratio <= 1.1 * cp):
            ratio_ok = False
    return {
        "model": f"dim{MEM_KW['dim']} depth{MEM_KW['depth']} "
                 f"heads{MEM_KW['heads']}",
        "kv_pages": kv_pages,
        "per_cp": rows,
        "ratio_ok": ratio_ok,
        "output_mismatches": mismatches,
        "ok": ratio_ok and mismatches == 0,
    }


def run_long_prompt_leg(cps) -> dict:
    """The max_len-ceiling story: a prompt whose KV exceeds the synthetic
    single-chip budget serves at cp>1, greedy- and sampled-identical to
    the truncation-free cp=1 reference."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import SamplingParams
    from distributed_tensorflow_ibm_mnist_tpu.utils.flops import (
        ring_hop_bytes,
    )

    model, params = _model_and_params(MEM_KW)
    max_len, bucket, kv_pages = 64, 48, 16
    long_prompt = [(i * 7) % (MEM_KW["num_classes"] - 2) + 1
                   for i in range(40)]
    sampled = SamplingParams(temperature=0.7, top_k=8, seed=123)

    refs, rows = {}, {}
    budget = None
    fits = {}
    for cp in cps:
        eng = _engine(model, params, max_len, cp=cp, buckets=(bucket,),
                      kv_page_size=8, kv_pages=kv_pages)
        greedy, _ = _serve(eng, [long_prompt], max_new=8)
        samp, _ = _serve(eng, [long_prompt], max_new=8, sampling=sampled)
        kv = eng.kv_bytes_per_chip()
        eng.close()
        if budget is None:  # 60% of the cp=1 footprint: cp=1 must NOT fit
            budget = int(kv * 0.6)
            refs = {"greedy": greedy, "sampled": samp}
        fits[str(cp)] = kv <= budget
        rows[str(cp)] = {
            "kv_bytes_per_chip": kv,
            "greedy_match": greedy == refs["greedy"],
            "sampled_match": samp == refs["sampled"],
        }
    hop = ring_hop_bytes(bucket // max(cps), MEM_KW["heads"],
                         MEM_KW["dim"] // MEM_KW["heads"],
                         dtype_bytes=4, depth=MEM_KW["depth"])
    parity_ok = all(r["greedy_match"] and r["sampled_match"]
                    for r in rows.values())
    budget_ok = (not fits["1"]) and all(
        fits[str(cp)] for cp in cps if cp > 1)
    return {
        "prompt_len": len(long_prompt),
        "bucket": bucket,
        "max_new": 8,
        "chip_kv_budget_bytes": budget,
        "fits_budget": fits,
        "per_cp": rows,
        "ring_hop_bytes_at_max_cp": hop,
        "ring_hops_per_prefill": max(cps) - 1,
        "parity_ok": parity_ok,
        "budget_ok": budget_ok,
        "ok": parity_ok and budget_ok,
    }


def run_chaos_leg() -> dict:
    """admit/step/kv-handoff event counts identical at cp ∈ {1, 2}."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        Router,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
    )

    model, params = _model_and_params(SMALL_KW)
    counts, toks, all_done = {}, {}, True
    for cp in (1, 2):
        inj = FaultInjector(FaultPlan(faults=()))
        roles = ["prefill", "decode"]

        def make_engine(tid, index):
            return InferenceEngine(
                model, params, slots=2, max_len=32, kv_page_size=8,
                cp=cp,
                scheduler=FIFOScheduler(max_len=32, buckets=(16,),
                                        max_queue=16),
                trace_tid=tid, role=roles[index], chaos=inj)

        with Router(make_engine, 2, roles=roles, chaos=inj) as r:
            rrs = [r.submit(p, max_new=6) for p in PROMPTS]
            r.run_until_done(max_steps=500)
            toks[cp] = [list(rr.generated) for rr in rrs]
            all_done &= all(rr.status == "done" for rr in rrs)
        counts[str(cp)] = {
            "serving_admit": inj.events("serving-admit"),
            "serving_step": inj.events("serving-step"),
            "kv_handoff": inj.events("kv-handoff"),
        }
    invariant = counts["1"] == counts["2"]
    parity = toks[1] == toks[2]
    return {
        "per_cp": counts,
        "counts_identical": invariant,
        "token_identical": parity,
        "all_done": all_done,
        "ok": invariant and parity and all_done,
    }


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
        ensure_virtual_cpu_devices,
    )

    n = ensure_virtual_cpu_devices(8)
    if n < 8:
        print(json.dumps({"metric": "cp_serving", "skipped": True,
                          "reason": f"only {n} devices"}), flush=True)
        return
    import jax

    cps = (1, 2) if QUICK else (1, 2, 4)
    census = run_census_leg()   # first: the process is still cold
    memory = run_memory_leg(cps)
    long_prompt = run_long_prompt_leg(cps)
    chaos = run_chaos_leg()
    result = {
        "metric": "cp_serving",
        "census": census,
        "memory": memory,
        "long_prompt": long_prompt,
        "chaos": chaos,
        "quick": QUICK,
        "device": str(jax.devices()[0]),
        "note": (
            "virtual CPU chips: per-chip KV bytes are layout-exact (the "
            "sequence sharding is real), ring hops are memcpys here — "
            "the per-hop byte counts are the analytic charge for real "
            "ICI; tokens/sec shows the emulated trend only"
        ),
    }
    print(json.dumps(result), flush=True)
    if not census["ok"]:
        print(f"cp census breach: cold={census['cp_cold']}/"
              f"{CP_COLD_BUDGET} repeat={census['cp_repeat']} "
              f"{census['repeat_by_site']}", file=sys.stderr)
        sys.exit(2)
    if not memory["ok"]:
        print(f"cp memory gate breach: ratio_ok={memory['ratio_ok']} "
              f"mismatches={memory['output_mismatches']}",
              file=sys.stderr)
        sys.exit(3)
    if not long_prompt["ok"]:
        print(f"cp long-prompt breach: parity_ok="
              f"{long_prompt['parity_ok']} budget_ok="
              f"{long_prompt['budget_ok']} {long_prompt['per_cp']}",
              file=sys.stderr)
        sys.exit(4)
    if not chaos["ok"]:
        print(f"cp chaos invariance breach: {chaos}", file=sys.stderr)
        sys.exit(5)


if __name__ == "__main__":
    main()

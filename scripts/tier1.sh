#!/usr/bin/env bash
# Tier-1 verify: the ROADMAP.md line, verbatim.  Run from the repo root:
#
#     bash scripts/tier1.sh
#
# Prints DOTS_PASSED=<count> (passing tests seen before the 870 s budget
# expires — the suite is larger than the budget on a 1-core box, so this
# count, not a clean exit, is the comparable figure) and exits with
# pytest's status (124 = timeout budget reached).
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc

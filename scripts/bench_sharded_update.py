"""Sharded vs replicated weight update: step time + modeled comm volume.

The `dp_sharded_update` comparison block for bench.py's MULTICHIP section:
runs the SAME dp=8 train step twice — replicated update (pmean + full
`tx.update` on every chip) and ZeRO-1 sharded update (bucketed
reduce-scatter + 1/N update + all-gather) — on the virtual CPU mesh, and
reports measured steady-state step times beside the analytic per-chip
comm/compute/memory model.  Designed to run in a SUBPROCESS (bench.py
spawns it with `JAX_PLATFORMS=cpu` + an 8-device XLA flag env) so the
parent's TPU backend is untouched; it also self-arms when run directly:

    python scripts/bench_sharded_update.py [n_devices] [adam|momentum]

Prints ONE JSON line.  Honest caveat baked into the output: virtual CPU
devices time-share one host, so `step_time_ms` shows parity/no-regression,
not ICI wire time — `modeled_comm_bytes_per_chip` carries the comm math
(ring collectives: all-reduce moves 2(N-1)/N·P elements per chip; the
sharded scheme's reduce-scatter + param all-gather moves the same wire
bytes but cuts the optimizer's update FLOPs and mutable state by N).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEVICES = int(sys.argv[1]) if len(sys.argv) > 1 else 8
OPTIMIZER = sys.argv[2] if len(sys.argv) > 2 else "adam"

# arm the virtual mesh BEFORE jax initializes (subprocess-friendly)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + f" --xla_force_host_platform_device_count={N_DEVICES}"
    ).strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.core.optim import (
        init_sharded_opt_state,
    )
    from distributed_tensorflow_ibm_mnist_tpu.core.state import TrainState
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.parallel.collectives import (
        ShardedUpdate,
        make_bucket_layout,
    )
    from distributed_tensorflow_ibm_mnist_tpu.parallel.data_parallel import (
        make_dp_train_step,
        place_sharded_update_state,
        replicate,
    )
    from distributed_tensorflow_ibm_mnist_tpu.parallel.mesh import make_mesh

    n = N_DEVICES
    assert len(jax.devices()) >= n, (
        f"need {n} devices, have {len(jax.devices())} — run via bench.py or "
        "with JAX_PLATFORMS=cpu and the XLA device-count flag unset elsewhere"
    )
    mesh = make_mesh(dp=n)
    # a hidden stack big enough that the update/comm terms are visible
    # beside the matmuls, small enough for the 1-core virtual mesh
    model = get_model("mlp", num_classes=10, hidden=(512, 512), dtype=jnp.float32)
    tx = optax.adam(1e-3) if OPTIMIZER == "adam" else optax.sgd(1e-2, momentum=0.9)
    state = TrainState.create(
        model, tx, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1), jnp.uint8)
    )
    p_count = state.param_count()
    layout = make_bucket_layout(state.params, n_shards=n, n_buckets=4)

    rng = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(
            rng.integers(0, 255, size=(32 * n, 28, 28, 1), dtype=np.uint8)),
        "label": jnp.asarray(rng.integers(0, 10, size=(32 * n,)).astype(np.int32)),
    }

    sh_state = state.replace(
        opt_state=init_sharded_opt_state(tx, state.params, layout))
    sh_state = place_sharded_update_state(mesh, sh_state, layout)
    # fresh buffers for the replicated leg: device_put may alias the source
    # arrays, and the donating steps would otherwise delete the other leg's
    # state out from under it
    rep_state = replicate(mesh, jax.tree.map(jnp.copy, state))

    sh_step = make_dp_train_step(
        model, tx, mesh, sharded_update=ShardedUpdate(layout=layout),
        state=sh_state)
    rep_step = make_dp_train_step(model, tx, mesh)

    def timed(step, st, iters=30, warmup=5):
        for _ in range(warmup):
            st, m = step(st, batch)
        jax.device_get(m["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            st, m = step(st, batch)
        jax.device_get(m["loss"])  # execution fence
        return (time.perf_counter() - t0) / iters * 1e3, st

    ms_rep, rep_state = timed(rep_step, rep_state)
    ms_sh, sh_state = timed(sh_step, sh_state)

    # parity guard: the two schemes must be walking the same trajectory
    rep_l = jax.tree.leaves(rep_state.params)
    sh_l = jax.tree.leaves(sh_state.params)
    max_dev = max(
        float(jnp.max(jnp.abs(a - b))) for a, b in zip(rep_l, sh_l))

    elem = 4  # f32
    ring = (n - 1) / n
    # mutable opt-state elements per chip (count leaves excluded: scalars)
    opt_elems = {
        "adam": 2 * p_count,        # mu + nu
        "momentum": p_count,        # trace
    }[OPTIMIZER]
    pad = sum(layout.bucket_sizes) - p_count
    result = {
        "metric": "dp_sharded_update",
        "n_devices": n,
        "optimizer": OPTIMIZER,
        "param_count": p_count,
        "buckets": list(layout.bucket_sizes),
        "bucket_pad_elems": pad,
        "step_time_ms_replicated": round(ms_rep, 3),
        "step_time_ms_sharded": round(ms_sh, 3),
        "sharded_over_replicated": round(ms_sh / ms_rep, 4),
        "max_param_deviation": max_dev,  # trajectory parity between schemes
        # analytic per-chip model (ring collectives, f32):
        #   replicated: all-reduce(grads)          = 2(N-1)/N · P
        #   sharded:    reduce-scatter(grads)      =  (N-1)/N · P
        #             + all-gather(updated params) =  (N-1)/N · P
        # equal wire bytes — the win is the optimizer terms below
        "modeled_comm_bytes_per_chip": {
            "replicated_allreduce": int(2 * ring * p_count * elem),
            "sharded_reduce_scatter": int(ring * p_count * elem),
            "sharded_param_all_gather": int(ring * p_count * elem),
        },
        "opt_update_elems_per_chip": {
            "replicated": p_count,
            "sharded": int(-(-p_count // n)),
        },
        "opt_state_bytes_per_chip": {
            "replicated": opt_elems * elem,
            "sharded": int(-(-opt_elems // n)) * elem,
        },
        "device": str(jax.devices()[0]),
        "note": (
            "virtual CPU mesh: step times show parity/no-regression, not "
            "ICI wire time; comm/memory columns are the analytic model"
        ),
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()

"""Crash bench: whole-process SIGKILL recovery with exactly-once
streams, journal overhead, and torn-tail tolerance (ISSUE 18).

Everything here gates a DURABILITY property (the standing CPU caveat:
no tokens/sec numbers), end to end through real sockets and a real
``kill -9``:

1. **overhead** — paired waves through an identical tier with and
   without a :class:`RequestJournal`: the journal's measured append
   share of journaled wall-clock must stay under 2% at the default
   ``interval`` fsync policy.  Per-policy append/fsync stats for
   ``never`` / ``interval`` / ``always`` ride along as data.
2. **sigkill** — a subprocess serving tier (``--serve DIR``: journal +
   front door + fsync'd telemetry) is SIGKILLed while keyed SSE clients
   are mid-stream.  The parent then runs :func:`recover` on the
   journal, seeds a fresh :class:`FrontDoor` with the recovered
   idempotency bindings, and every client retries its POST with the
   same ``Idempotency-Key`` and its ``Last-Event-ID``.  Gates: the kill
   landed mid-flight (>= 1 incomplete journal entry), zero lost
   accepted requests (every incomplete replays to terminal), zero
   gaps and zero divergent duplicates in the stitched client
   transcripts (logical SSE ids), and token parity — each stitched
   transcript's :func:`transcript_digest` equals the uncrashed
   reference's from the same tier.
3. **torn** — the journal's final record is torn on disk (crash
   mid-append); the scan flags ``torn_tail``, drops exactly one
   record, and recovery replays the reopened request to ``done``.
4. **post-mortem** — the killed process's fsync'd Telemetry JSONL and
   MetricWriter logs are readable after the SIGKILL (>= 1 strict-JSON
   line each): the black box survived the crash it exists for.

Usage:  JAX_PLATFORMS=cpu python scripts/bench_crash.py
Emits one JSON line (``"metric": "crash"``); exits nonzero when any
gate fails.  ``DTM_BENCH_QUICK=1`` shrinks the waves to a tier-1-safe
smoke.  bench.py runs this as its ``crash`` block
(``DTM_BENCH_SKIP_CRASH=1`` skips).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

MAX_NEW = 8
CRASH_MAX_NEW = 48                    # long streams widen the kill window
# the 2% gate is a STEADY-STATE claim: long generations, so the
# per-request costs (admitted WAL flush, retirement) amortize and the
# measurement is dominated by the per-token path — delivered marks
# paced by journal_hw_interval_s, not per token
OVERHEAD_MAX_NEW = 48
N_OVERHEAD = 8 if QUICK else 12
N_WAVES = 3
N_CLIENTS = 6 if QUICK else 8   # over the tier's 4 slots: queued work
                                # keeps the kill window wide open
WAIT_S = 120.0
SERVE_SPINUP_S = 240.0


def _model_kw():
    import jax.numpy as jnp

    return dict(num_classes=16, dim=32, depth=1, heads=2,
                dtype=jnp.float32)


def _crash_model_kw():
    """Heavier model for the SIGKILL leg ONLY.  The tiny bench model's
    step is all GIL-held Python dispatch, which starves the child's
    asyncio loop until generation finishes — clients would see their
    tokens only after every request retired, and the kill could never
    land between receipt and retirement.  Real per-step XLA compute
    releases the GIL, so SSE delivery interleaves with generation the
    way it does on real hardware."""
    import jax.numpy as jnp

    return dict(num_classes=16, dim=256, depth=2, heads=4,
                dtype=jnp.float32)


def _mk_prompts(seed: int, n: int):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, 16, size=(2 + i % 3,))]
            for i in range(n)]


def _sampling_kw(i: int):
    """Alternate greedy and seeded-sampled so replay determinism is
    exercised on BOTH decode paths."""
    if i % 2 == 0:
        return None
    return {"temperature": 0.7, "top_k": 5, "seed": 100 + i}


def _build_daemon(journal=None, n_replicas=2, model_kw=None,
                  max_len=16, buckets=(8,)):
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        Router,
        ServingDaemon,
    )

    model = get_model("causal_lm", **(model_kw or _model_kw()))
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine(tid):
        return InferenceEngine(
            model, params, slots=2, max_len=max_len, kv_page_size=4,
            scheduler=FIFOScheduler(max_len=max_len, buckets=buckets,
                                    max_queue=64))

    router = Router(make_engine, n_replicas)
    router.prewarm()
    return ServingDaemon(router, max_queue=64, liveness_timeout_s=30.0,
                         journal=journal).start()


def _pools_zero(router) -> bool:
    for rep in router.replicas:
        if not rep.alive or rep.engine._pool is None:
            continue
        eng = rep.engine
        if eng._radix is not None:
            stack = [eng._radix.root]
            while stack:
                node = stack.pop()
                if node.ref != 0:
                    return False
                stack.extend(node.children.values())
            if eng._pool.allocated != eng._radix.n_blocks:
                return False
        elif eng._pool.allocated != 0:
            return False
    return True


# ----------------------------------------------------------------------
# leg 1: steady-state journal overhead


def _wave(daemon, prompts, max_new=MAX_NEW):
    from distributed_tensorflow_ibm_mnist_tpu.serving import SamplingParams

    t0 = time.perf_counter()
    drs = []
    for i, p in enumerate(prompts):
        kw = _sampling_kw(i)
        sp = SamplingParams(**kw) if kw else None
        drs.append(daemon.submit(p, max_new, sampling=sp))
    for dr in drs:
        dr.wait(timeout=WAIT_S)
    wall = time.perf_counter() - t0
    return wall, [list(dr.tokens) for dr in drs]


def _warm(daemon, max_new=MAX_NEW):
    """Pay compile for BOTH decode paths before anything is timed."""
    _wave(daemon, _mk_prompts(30, 2), max_new=max_new)


def leg_overhead(tmpdir: str) -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.serving import RequestJournal

    prompts = _mk_prompts(31, N_OVERHEAD)
    # one replica: the overhead share is append time over SERVING time,
    # so the denominator is a saturated tier's wall, not idle lanes
    # bare tier first: same prompts, no journal — the paired baseline
    bare = _build_daemon(n_replicas=1, max_len=64, buckets=(16,))
    _warm(bare, max_new=OVERHEAD_MAX_NEW)
    bare_wall = 0.0
    for _ in range(N_WAVES):
        w, bare_toks = _wave(bare, prompts, max_new=OVERHEAD_MAX_NEW)
        bare_wall += w
    bare_drained = bare.drain(timeout=30.0)
    bare_pools = _pools_zero(bare.router)
    bare.close()

    policies = {}
    journaled_wall = append_share = None
    parity = True
    for policy in ("interval", "always", "never"):
        jdir = os.path.join(tmpdir, f"overhead-{policy}")
        journal = RequestJournal(jdir, fsync_policy=policy)
        daemon = _build_daemon(journal=journal, n_replicas=1,
                               max_len=64, buckets=(16,))
        _warm(daemon, max_new=OVERHEAD_MAX_NEW)
        # aggregate over several waves: one wave is ~0.1 s of wall, so a
        # single scheduler hiccup can swing the share past the gate.  The
        # share the gate speaks for is steady-state, i.e. the aggregate.
        wall = wave_append_s = 0.0
        toks = None
        st0 = journal.stats()    # diff out the warmup's appends
        for _ in range(N_WAVES):
            w, toks = _wave(daemon, prompts, max_new=OVERHEAD_MAX_NEW)
            wall += w
            parity = parity and toks == bare_toks
        st = journal.stats()
        drained = daemon.drain(timeout=30.0)
        pools = _pools_zero(daemon.router)
        daemon.close()
        wave_append_s = st["append_s"] - st0["append_s"]
        policies[policy] = {
            "wall_s": round(wall, 4),
            "records": st["records"] - st0["records"],
            "fsyncs": st["fsyncs"] - st0["fsyncs"],
            "append_s": round(wave_append_s, 6),
            "append_share": round(wave_append_s / wall, 6),
            "drained_clean": drained,
            "pools_zero": pools,
        }
        if policy == "interval":
            # the default policy is the one the 2% gate speaks for
            journaled_wall = wall
            append_share = wave_append_s / wall
    return {
        "requests_per_wave": len(prompts),
        "waves": N_WAVES,
        "bare_wall_s": round(bare_wall, 4),
        "journaled_wall_s": round(journaled_wall, 4),
        "wall_ratio": round(journaled_wall / max(bare_wall, 1e-9), 4),
        "append_share": round(append_share, 6),
        "parity_across_policies": parity,
        "policies": policies,
        "drained_clean": bare_drained
        and all(p["drained_clean"] for p in policies.values()),
        "pools_zero": bare_pools
        and all(p["pools_zero"] for p in policies.values()),
    }


# ----------------------------------------------------------------------
# leg 2: SIGKILL mid-flight, recover, stitch exactly-once transcripts


def serve(workdir: str) -> None:
    """Child mode: serving tier + journal + fsync'd black box, port
    published to ``<workdir>/port`` — then wait to be SIGKILLed."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FrontDoor,
        RequestJournal,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.metrics import MetricWriter
    from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import Telemetry

    journal = RequestJournal(os.path.join(workdir, "journal"),
                             fsync_policy="always")
    daemon = _build_daemon(journal=journal, max_len=64, buckets=(16,),
                           model_kw=_crash_model_kw())
    fd = FrontDoor(daemon, keepalive_s=5.0).start_in_thread()
    tele = Telemetry(interval_s=0.1,
                     jsonl_path=os.path.join(workdir, "telemetry.jsonl"),
                     fsync=True)
    tele.register_source("daemon", daemon.summary)
    mw = MetricWriter(os.path.join(workdir, "metrics.jsonl"),
                      stdout=False, fsync=True)

    def black_box():
        while True:
            time.sleep(0.1)
            tele.sample()
            mw.write("serving", requests=daemon.counters["submitted"],
                     tokens=daemon.counters["delivered_tokens"])

    threading.Thread(target=black_box, daemon=True).start()
    tmp = os.path.join(workdir, "port.tmp")
    with open(tmp, "w") as fh:
        fh.write(str(fd.port))
    os.replace(tmp, os.path.join(workdir, "port"))
    while True:          # the parent's SIGKILL is the only exit
        time.sleep(1.0)


def _sse_client(port, i, prompt, out, lock):
    """One keyed streaming client; records (logical id, token) pairs and
    whatever ended the stream — a terminal or a severed connection."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import FrontDoorClient

    cli = FrontDoorClient("127.0.0.1", port, timeout=WAIT_S)
    pairs, err = [], None
    try:
        stream = cli.stream(prompt, CRASH_MAX_NEW, idempotency_key=f"crash-{i}",
                            deadline_s=WAIT_S, **(
                                {"sampling": _sampling_kw(i)}
                                if _sampling_kw(i) else {}))
        for tok in stream:
            pairs.append((cli.last_event_id, tok))
    except Exception as e:          # SIGKILL severs the socket mid-read
        err = type(e).__name__
    with lock:
        out[i] = {"pairs": pairs, "terminal": cli.last_terminal,
                  "error": err,
                  "last_event_id": cli.last_event_id}


def leg_sigkill(tmpdir: str) -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FrontDoor,
        FrontDoorClient,
        RequestJournal,
        SamplingParams,
        recover,
        transcript_digest,
    )

    workdir = os.path.join(tmpdir, "sigkill")
    os.makedirs(workdir)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--serve", workdir],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    port_path = os.path.join(workdir, "port")
    deadline = time.monotonic() + SERVE_SPINUP_S
    while not os.path.exists(port_path):
        if proc.poll() is not None:
            raise RuntimeError("serve subprocess died before publishing "
                               f"its port (rc={proc.returncode})")
        if time.monotonic() > deadline:
            proc.kill()
            raise RuntimeError("serve subprocess spin-up timed out")
        time.sleep(0.05)
    with open(port_path) as fh:
        port = int(fh.read())

    prompts = _mk_prompts(32, N_CLIENTS)
    results: dict[int, dict] = {}
    lock = threading.Lock()
    threads = [threading.Thread(target=_sse_client,
                                args=(port, i, p, results, lock))
               for i, p in enumerate(prompts)]
    for t in threads:
        t.start()
    # one unary keyed client rides along: its retry must bind, not
    # double-execute (and a crashed unary replays from token 0 — the
    # client received nothing until the terminal)
    unary_prompt = _mk_prompts(33, 1)[0]
    unary_box: dict = {}

    def unary_client():
        cli = FrontDoorClient("127.0.0.1", port, timeout=WAIT_S)
        try:
            unary_box["body"] = cli.generate(
                unary_prompt, CRASH_MAX_NEW, idempotency_key="crash-unary",
                deadline_s=WAIT_S)
        except Exception as e:
            unary_box["error"] = type(e).__name__

    tu = threading.Thread(target=unary_client)
    tu.start()

    # kill once streaming has demonstrably begun AND the child's journal
    # (on shared disk — the parent can scan it live, torn-tail tolerant)
    # still shows unretired work.  Client-observed events lag generation
    # (the child's event loop shares the GIL with its pump threads), so
    # gating only on received events can fire after everything retired;
    # the journal is the generation-side truth.
    from distributed_tensorflow_ibm_mnist_tpu.serving import scan_journal
    jdir = os.path.join(workdir, "journal")
    kill_deadline = time.monotonic() + WAIT_S
    while time.monotonic() < kill_deadline:
        with lock:
            seen = sum(len(r["pairs"]) for r in results.values())
            live = sum(1 for r in results.values() if r["pairs"])
        if seen >= 2 and live >= 1:
            try:
                s = scan_journal(jdir)
            except OSError:
                s = None
            if s is not None and s.requests and any(
                    not v["retired"] for v in s.requests.values()):
                break
        time.sleep(0.005)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=30.0)
    for t in threads:
        t.join(timeout=WAIT_S)
    tu.join(timeout=WAIT_S)

    # ---- recovery, in THIS process, from nothing but the journal dir
    rec = recover(
        jdir,
        lambda: _build_daemon(journal=RequestJournal(
            jdir, fsync_policy="always"), max_len=64, buckets=(16,),
            model_kw=_crash_model_kw()),
        resubmit_timeout_s=WAIT_S)
    n_incomplete = len(rec.requests)
    replay_ok = rec.wait(timeout=WAIT_S)
    replay_done = all(r.dr.status in ("done", "cancelled")
                      for r in rec.requests)
    daemon2 = rec.daemon
    fd2 = FrontDoor(daemon2, idempotency_bindings=rec.bindings)
    fd2.start_in_thread()

    # ---- clients without a terminal retry under their original key
    resumed = 0
    for i in range(N_CLIENTS):
        got = results.get(i, {"pairs": [], "terminal": None,
                              "last_event_id": None})
        if got["terminal"] is not None:
            continue
        resumed += 1
        cli = FrontDoorClient("127.0.0.1", fd2.port, timeout=WAIT_S)
        kw = ({"sampling": _sampling_kw(i)} if _sampling_kw(i) else {})
        pairs = []
        for tok in cli.stream(prompts[i], CRASH_MAX_NEW,
                              idempotency_key=f"crash-{i}",
                              last_event_id=got["last_event_id"],
                              deadline_s=WAIT_S, **kw):
            pairs.append((cli.last_event_id, tok))
        got["pairs"] = got["pairs"] + pairs
        got["terminal"] = cli.last_terminal
        results[i] = got
    unary_retried = False
    if "body" not in unary_box or unary_box["body"].get("status") != "done":
        unary_retried = True
        cli = FrontDoorClient("127.0.0.1", fd2.port, timeout=WAIT_S)
        unary_box["body"] = cli.generate(
            unary_prompt, CRASH_MAX_NEW, idempotency_key="crash-unary",
            deadline_s=WAIT_S)
        unary_box["resume_from"] = unary_box["body"].get("resume_from")

    # ---- stitch + gates against the uncrashed reference
    refs = []
    for i, p in enumerate(prompts):
        kw = _sampling_kw(i)
        sp = SamplingParams(**kw) if kw else None
        refs.append(daemon2.submit(p, CRASH_MAX_NEW, sampling=sp))
    unary_ref = daemon2.submit(unary_prompt, CRASH_MAX_NEW)
    for dr in refs + [unary_ref]:
        dr.wait(timeout=WAIT_S)

    no_gaps = dup_consistent = parity = True
    stream_details = []
    for i, dr in enumerate(refs):
        ref = list(dr.tokens)
        got = results.get(i, {"pairs": []})
        stitched: dict[int, int] = {}
        for eid, tok in got["pairs"]:
            if eid in stitched and stitched[eid] != tok:
                dup_consistent = False
            stitched[eid] = tok
        ids = sorted(stitched)
        contiguous = ids == list(range(len(ids)))
        complete = len(ids) == len(ref)
        no_gaps = no_gaps and contiguous and complete
        digest_ok = (contiguous and complete
                     and transcript_digest([stitched[k] for k in ids])
                     == transcript_digest(ref))
        parity = parity and digest_ok
        stream_details.append({
            "client": i, "events": len(got["pairs"]),
            "unique_ids": len(ids), "ref_len": len(ref),
            "contiguous": contiguous, "digest_ok": digest_ok,
            "pre_crash_error": got.get("error"),
        })
    unary_ok = (unary_box.get("body", {}).get("status") == "done"
                and unary_box["body"].get("tokens")
                == list(unary_ref.tokens)
                # a crashed unary replays from token 0 — the client
                # received nothing before the terminal (absent key means
                # the retry executed fresh, which replays from 0 too)
                and (unary_box.get("resume_from") or 0) == 0)

    # ---- fsync'd black box must be readable post-mortem
    def _valid_lines(path):
        n = 0
        try:
            with open(path, encoding="utf-8") as fh:
                for ln in fh:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        json.loads(ln)
                        n += 1
                    except ValueError:
                        pass
        except OSError:
            return 0
        return n

    tele_lines = _valid_lines(os.path.join(workdir, "telemetry.jsonl"))
    mw_lines = _valid_lines(os.path.join(workdir, "metrics.jsonl"))

    fd2.stop()
    drained = daemon2.drain(timeout=30.0)
    pools = _pools_zero(daemon2.router)
    daemon2.close()
    return {
        "clients": N_CLIENTS,
        "incomplete_at_kill": n_incomplete,
        "replay_ok": replay_ok and replay_done,
        "rebound_keys": len(rec.bindings),
        "resumed_streams": resumed,
        "unary_retried": unary_retried,
        "unary_ok": unary_ok,
        "no_gaps": no_gaps,
        "dup_consistent": dup_consistent,
        "token_parity": parity,
        "streams": stream_details,
        "scan": rec.scan.report(),
        "telemetry_lines": tele_lines,
        "metricwriter_lines": mw_lines,
        "drained_clean": drained,
        "pools_zero": pools,
    }


# ----------------------------------------------------------------------
# leg 3: torn final record on disk


def leg_torn(tmpdir: str) -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        RequestJournal,
        recover,
        scan_journal,
    )

    jdir = os.path.join(tmpdir, "torn")
    daemon = _build_daemon(journal=RequestJournal(jdir))
    drs = [daemon.submit(p, MAX_NEW) for p in _mk_prompts(34, 2)]
    for dr in drs:
        dr.wait(timeout=WAIT_S)
    want = [list(dr.tokens) for dr in drs]
    daemon.drain(timeout=30.0)
    daemon.close()
    # tear the tail: the crash lands mid-append of the LAST record (a
    # retirement), re-opening that request in the scanner's eyes
    segs = sorted(p for p in os.listdir(jdir) if p.endswith(".jsonl"))
    last = os.path.join(jdir, segs[-1])
    size = os.path.getsize(last)
    with open(last, "ab") as fh:
        fh.truncate(size - 9)
    scan = scan_journal(jdir)
    rec = recover(jdir,
                  lambda: _build_daemon(journal=RequestJournal(jdir)),
                  resubmit_timeout_s=WAIT_S)
    replay_ok = rec.wait(timeout=WAIT_S)
    statuses = [r.dr.status for r in rec.requests]
    parity = all(
        want[r.orig_id][r.resume_from:] == list(r.dr.tokens)
        for r in rec.requests)
    drained = rec.daemon.drain(timeout=30.0)
    pools = _pools_zero(rec.daemon.router)
    rec.daemon.close()
    return {
        "torn_tail": scan.torn_tail,
        "records_dropped": scan.records_dropped,
        "reopened": len(rec.requests),
        "replay_ok": replay_ok and all(s == "done" for s in statuses),
        "suffix_parity": parity,
        "drained_clean": drained,
        "pools_zero": pools,
    }


def main() -> None:
    if len(sys.argv) >= 3 and sys.argv[1] == "--serve":
        serve(sys.argv[2])
        return
    with tempfile.TemporaryDirectory(prefix="bench-crash-") as tmpdir:
        overhead = leg_overhead(tmpdir)
        crash = leg_sigkill(tmpdir)
        torn = leg_torn(tmpdir)
    gates = {
        "journal_overhead_le_2pct": overhead["append_share"] <= 0.02,
        "journal_parity": overhead["parity_across_policies"],
        "kill_mid_flight": crash["incomplete_at_kill"] >= 1,
        "zero_lost": crash["replay_ok"],
        "no_gaps": crash["no_gaps"],
        "no_dup_divergence": crash["dup_consistent"],
        "token_parity": crash["token_parity"] and crash["unary_ok"],
        "torn_tail_recovered": torn["torn_tail"]
        and torn["records_dropped"] == 1
        and torn["reopened"] >= 1
        and torn["replay_ok"] and torn["suffix_parity"],
        "telemetry_postmortem": crash["telemetry_lines"] >= 1
        and crash["metricwriter_lines"] >= 1,
        "drained_clean": all(l["drained_clean"] and l["pools_zero"]
                             for l in (overhead, crash, torn)),
    }
    record = {
        "metric": "crash",
        "quick": QUICK,
        "overhead": overhead,
        "sigkill": crash,
        "torn": torn,
        "gates": gates,
        "passed": all(gates.values()),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

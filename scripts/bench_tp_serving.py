"""Tensor-parallel serving: per-chip memory, parity cross, failover replay.

The ISSUE 10 acceptance harness, in three legs:

* **memory** — a model sized to EXCEED one chip's (synthetic) HBM budget
  is served at tp ∈ {1, 2, 4}: per-chip weight + KV bytes must land at
  1/tp of the tp=1 figure (±10% — the embedding/logits replication tax
  is the honest remainder), the tp=1 engine must NOT fit the budget while
  every tp > 1 engine does, and aggregate useful tokens/sec is reported
  per tp.  Greedy output must be token-identical across tp.
* **parity cross** — the full composition matrix: {dense, paged} x
  {native, int8 KV} x decode_ahead ∈ {1, 8} x {plain, speculative}, each
  served at tp ∈ {1, 2, 4} and compared token-for-token against the SAME
  config at tp=1.  GSPMD sharding must be invisible in the tokens —
  every mismatch is counted and any nonzero count fails the run.
* **failover replay** — a 2-replica router over DISJOINT 2-chip tp
  groups (`tp_device_groups(2, 2)` on the 8-device virtual platform),
  chaos killing one replica's decode mid-wave: the wave must finish
  token-identical to a fault-free single engine, exactly one failover.

Exit status: 2 = memory/budget gate breach, 4 = parity mismatch,
5 = failover replay breach.  Designed for a SUBPROCESS (bench.py spawns
it with ``JAX_PLATFORMS=cpu``, skippable via ``DTM_BENCH_SKIP_TP=1``);
self-arms 8 virtual CPU devices when run directly:

    python scripts/bench_tp_serving.py

Prints ONE JSON line (metric "tp_serving").  Honest caveat carried in
the record: on this host the "chips" are virtual CPU devices, so the
MEMORY claims (bytes per chip) are real and layout-exact while the
tokens/sec figures only show the collective-overhead TREND — emulated
psums over shared host memory say nothing about real interconnect.

``DTM_BENCH_QUICK=1`` drops tp=4 from the cross and shrinks streams.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

# memory leg: big enough that weights+KV dominate the replication tax
MEM_KW = dict(num_classes=64, dim=256, depth=4, heads=8)
# parity cross: the smallest model whose heads all tp values divide
CROSS_KW = dict(num_classes=32, dim=32, depth=1, heads=4)

# repetitive-suffix prompts so the speculative legs' n-gram drafter has
# real lookup hits (parity must hold either way; this makes the accepted-
# token path actually execute instead of trivially falling back)
PROMPTS = [
    [1, 2, 3, 4, 1, 2, 3, 4, 1, 2],
    [5, 6, 5, 6, 5, 6, 5],
    [7, 8, 9, 7, 8, 9],
    [2, 4, 2, 4, 2, 4, 2, 4],
]


def _model_and_params(kw, **over):
    import jax
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.models import get_model

    model = get_model("causal_lm", dtype=jnp.float32, **{**kw, **over})
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _serve(model, params, max_len, *, tp=1, max_new=8, prompts=PROMPTS,
           **ekw):
    """One engine, one drained stream -> (outputs, useful_tok/s, engine)."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
    )

    eng = InferenceEngine(
        model, params, slots=2, max_len=max_len, tp=tp,
        scheduler=FIFOScheduler(max_len=max_len, buckets=(16,),
                                max_queue=len(prompts)),
        **ekw)
    reqs = [eng.submit(p, max_new=max_new) for p in prompts]
    t0 = time.perf_counter()
    eng.run()
    dt = time.perf_counter() - t0
    outs = [list(r.generated) for r in reqs]
    useful = sum(len(o) for o in outs)
    return outs, useful / dt, eng


def run_memory_leg(tps) -> dict:
    """Per-chip bytes 1/tp (±10%), the budget story, tokens/sec per tp."""
    model, params = _model_and_params(MEM_KW)
    max_len = 48
    rows = {}
    ref = None
    mismatches = 0
    for tp in tps:
        outs, tok_s, eng = _serve(model, params, max_len, tp=tp)
        w, kv = eng.weight_bytes_per_chip(), eng.kv_bytes_per_chip()
        eng.close()
        if ref is None:
            ref = outs
        elif outs != ref:
            mismatches += 1
        rows[str(tp)] = {
            "weight_bytes_per_chip": w, "kv_bytes_per_chip": kv,
            "total_bytes_per_chip": w + kv,
            "useful_tokens_per_sec": round(tok_s, 2),
        }
    t1 = rows["1"]["total_bytes_per_chip"]
    # the synthetic chip: 60% of the tp=1 footprint — the model does NOT
    # fit one chip, and must fit every tp>1 slice (the deployment story
    # the 1/tp claim exists to enable)
    budget = int(t1 * 0.6)
    ratio_ok, fits = True, {}
    for tp in tps:
        total = rows[str(tp)]["total_bytes_per_chip"]
        ratio = t1 / total
        rows[str(tp)]["reduction_vs_tp1"] = round(ratio, 3)
        if not (0.9 * tp <= ratio <= 1.1 * tp):
            ratio_ok = False
        fits[str(tp)] = total <= budget
    budget_ok = (not fits["1"]) and all(
        fits[str(tp)] for tp in tps if tp > 1)
    return {
        "model": f"dim{MEM_KW['dim']} depth{MEM_KW['depth']} "
                 f"heads{MEM_KW['heads']}",
        "per_tp": rows,
        "chip_budget_bytes": budget,
        "fits_budget": fits,
        "ratio_ok": ratio_ok,
        "budget_ok": budget_ok,
        "output_mismatches": mismatches,
        "ok": ratio_ok and budget_ok and mismatches == 0,
    }


def run_parity_cross(tps) -> dict:
    """dense/paged x int8 x k∈{1,8} x spec, token-identical across tp."""
    models = {
        "native": _model_and_params(CROSS_KW),
        "int8": _model_and_params(CROSS_KW, kv_cache_dtype="int8"),
    }
    max_len = 32
    configs = []
    for layout in ("dense", "paged"):
        for kv in ("native", "int8"):
            for k in (1, 8):
                for spec in (False, True):
                    configs.append((layout, kv, k, spec))
    mism = []
    n_checked = 0
    for layout, kv, k, spec in configs:
        model, params = models[kv]
        ekw = {"decode_ahead": k}
        if layout == "paged":
            ekw.update(kv_page_size=8)
        if spec:
            ekw.update(speculative="ngram", draft_len=3)
        name = f"{layout}/{kv}/k{k}/{'spec' if spec else 'plain'}"
        ref = None
        for tp in tps:
            outs, _, eng = _serve(model, params, max_len, tp=tp,
                                  max_new=6, **ekw)
            eng.close()
            if ref is None:
                ref = outs
            else:
                n_checked += 1
                if outs != ref:
                    mism.append(f"{name}@tp{tp}")
    return {
        "n_configs": len(configs),
        "tps": list(tps),
        "n_cross_checks": n_checked,
        "mismatches": mism,
        "ok": not mism,
    }


def run_failover_replay() -> dict:
    """2 replicas x disjoint 2-chip tp groups, one chaos-killed mid-wave."""
    from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
        tp_device_groups,
    )
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        Router,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )

    model, params = _model_and_params(CROSS_KW)
    max_len = 32
    want, _, ref_eng = _serve(model, params, max_len, tp=1, max_new=6)
    ref_eng.close()

    groups = tp_device_groups(2, 2)
    inj = FaultInjector(FaultPlan(faults=(
        FaultSpec(site="serving-step", kind="transient", at=(1,)),)))

    def make_engine(tid, index):
        return InferenceEngine(
            model, params, slots=2, max_len=max_len, tp=2,
            tp_devices=groups[index],
            scheduler=FIFOScheduler(max_len=max_len, buckets=(16,),
                                    max_queue=len(PROMPTS)),
            trace_tid=tid, chaos=inj, stall_timeout_s=None)

    with Router(make_engine, 2) as r:
        rrs = [r.submit(p, max_new=6) for p in PROMPTS]
        r.run_until_done()
        got = [list(rr.generated) for rr in rrs]
        done = all(rr.status == "done" for rr in rrs)
        failovers = r.failovers
    return {
        "tp": 2, "n_replicas": 2,
        "token_identical": got == want,
        "all_done": done,
        "failovers": failovers,
        "ok": got == want and done and failovers == 1,
    }


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
        ensure_virtual_cpu_devices,
    )

    n = ensure_virtual_cpu_devices(8)
    if n < 8:
        print(json.dumps({"metric": "tp_serving", "skipped": True,
                          "reason": f"only {n} devices"}), flush=True)
        return
    import jax

    tps = (1, 2) if QUICK else (1, 2, 4)
    memory = run_memory_leg(tps)
    parity = run_parity_cross(tps)
    failover = run_failover_replay()
    result = {
        "metric": "tp_serving",
        "memory": memory,
        "parity": parity,
        "failover": failover,
        "quick": QUICK,
        "device": str(jax.devices()[0]),
        "note": (
            "virtual CPU chips: bytes-per-chip figures are layout-exact "
            "(the sharding is real), tokens/sec shows the emulated "
            "collective-overhead trend only — psums over shared host "
            "memory say nothing about real interconnect"
        ),
    }
    print(json.dumps(result), flush=True)
    if not memory["ok"]:
        print(f"tp memory gate breach: ratio_ok={memory['ratio_ok']} "
              f"budget_ok={memory['budget_ok']} "
              f"mismatches={memory['output_mismatches']}", file=sys.stderr)
        sys.exit(2)
    if not parity["ok"]:
        print(f"tp parity mismatches: {parity['mismatches']}",
              file=sys.stderr)
        sys.exit(4)
    if not failover["ok"]:
        print(f"tp failover replay breach: {failover}", file=sys.stderr)
        sys.exit(5)


if __name__ == "__main__":
    main()

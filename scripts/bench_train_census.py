"""Training-side compile census: path-qualified program-count regression gate.

The serving engine's program family has been census-gated since ISSUE 7
(scripts/bench_serving.py CENSUS_BUDGET); this closes the ROADMAP 5a
remainder by giving ``Trainer.fit()`` the same discipline.  The Trainer
labels its compile sites with the parallelism PATH the run took
(``train_epoch[dp4_fsdp]``, ``eval[dp2_pp2]``, ``h2d[dp1_stream]`` — the
label is built once at Trainer init from dp/fsdp/tp/sp/pp/
sharded_update/stream), and ``fit()``'s summary now carries the by-site
delta as ``compile_by_site``.  This script runs one tiny fit per path and
pins each path's per-site program counts in ``CENSUS_BUDGET``:

* a site exceeding its pinned count means the path grew a program — a
  compile-storm/cache-churn regression even when every test passes;
* the budgets are the MEASURED counts of the current trainer, pinned
  exact, so one extra program anywhere fails the gate (exit status 3).

Paths covered: plain dp1, dp1 stream-input (the h2d site), dp4, dp4+fsdp
(ZeRO-3), dp4+sharded_update (ZeRO-1), and dp2 x pp2 (GPipe) — every
parallelism family that changes which programs fit() compiles.

Designed to run in a SUBPROCESS (bench.py spawns it with
``JAX_PLATFORMS=cpu``); self-arms 8 virtual CPU devices when run
directly:

    python scripts/bench_train_census.py

Prints ONE JSON line (metric "train_census") and exits 3 on any breach.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Pinned per-path, per-site budgets: the measured program counts of the
# current trainer (site labels come from Trainer._path_label).  The scan
# epoch is ONE program per path; eval is one; the stream path compiles
# the chunk runner + the ragged-tail step + their device_put layouts.
# Exceeding any count is a leak; a MISSING measured site also fails (the
# attribution itself regressed).
CENSUS_BUDGET = {
    "dp1": {"train_epoch[dp1]": 1, "eval[dp1]": 1},
    # stream mode compiles the chunk runner, the ragged-tail per-step
    # runner, and their two metric-stack helpers inside the epoch; the
    # h2d site itself must compile NOTHING (device_put is a transfer,
    # and a program appearing there means the input path grew a jit)
    "dp1_stream": {"train_epoch[dp1_stream]": 4, "h2d[dp1_stream]": 0,
                   "eval[dp1_stream]": 1},
    "dp4": {"train_epoch[dp4]": 1, "eval[dp4]": 1},
    "dp4_fsdp": {"train_epoch[dp4_fsdp]": 1, "eval[dp4_fsdp]": 1},
    "dp4_su": {"train_epoch[dp4_su]": 1, "eval[dp4_su]": 1},
    "dp2_pp2": {"train_epoch[dp2_pp2]": 1, "eval[dp2_pp2]": 1},
}


def _mlp_cfg(**kw):
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    base = dict(
        model="mlp", model_kwargs={"hidden": (32,)}, dataset="mnist",
        synthetic=True, n_train=256, n_test=64, batch_size=64, epochs=1,
        quiet=True, eval_batch_size=64,
    )
    base.update(kw)
    return RunConfig(**base)


def _lm_pp_cfg():
    import jax.numpy as jnp

    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    return RunConfig(
        name="census_pp", model="causal_lm", dp=2, pp=2,
        model_kwargs={"dim": 32, "depth": 2, "heads": 2,
                      "dtype": jnp.float32},
        dataset="retrieval", dataset_kwargs={"vocab": 16, "seq_len": 32},
        n_train=128, n_test=32, batch_size=32, epochs=1, quiet=True,
        eval_batch_size=32,
    )


def run_path(cfg) -> dict:
    """One fit; returns {label, by_site (n per site), n_programs}."""
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer

    t = Trainer(cfg)
    try:
        summary = t.fit()
    finally:
        t.close()
    return {
        "label": t._path_label,
        "by_site": {k: v["n"] for k, v in summary["compile_by_site"].items()},
        "n_programs": summary["n_compiled_programs"],
    }


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
        ensure_virtual_cpu_devices,
    )

    n = ensure_virtual_cpu_devices(8)
    if n < 8:
        print(json.dumps({"metric": "train_census", "skipped": True,
                          "reason": f"only {n} devices"}), flush=True)
        return

    configs = {
        "dp1": _mlp_cfg(),
        "dp1_stream": _mlp_cfg(input_mode="stream", stream_chunk=2),
        "dp4": _mlp_cfg(dp=4),
        "dp4_fsdp": _mlp_cfg(dp=4, fsdp=True),
        "dp4_su": _mlp_cfg(dp=4, sharded_update=True),
        "dp2_pp2": _lm_pp_cfg(),
    }
    paths: dict[str, dict] = {}
    over: dict[str, int] = {}
    for name, cfg in configs.items():
        res = run_path(cfg)
        paths[name] = res
        budget = CENSUS_BUDGET[name]
        if res["label"] != name:
            over[f"{name}:label"] = res["label"]  # attribution regressed
            continue
        for site, pinned in budget.items():
            got = res["by_site"].get(site, 0)
            if got > pinned:
                over[f"{name}:{site}"] = got - pinned
        for site, got in res["by_site"].items():
            # a site outside the pinned set (other than unattributed
            # helper jits) means a NEW program family member appeared
            if site not in budget and site != "unattributed" and got > 0:
                over[f"{name}:{site}"] = got

    result = {
        "metric": "train_census",
        "paths": paths,
        "budget": CENSUS_BUDGET,
        "over_budget": over,
        "census_ok": not over,
    }
    print(json.dumps(result), flush=True)
    if over:
        print(f"train compile census over budget: {over}", file=sys.stderr)
        sys.exit(3)


if __name__ == "__main__":
    main()

"""Microbenchmark for ops/flash_attention on the real chip.

Times fwd and fwd+bwd at the zoo's LM shapes.  Timing fence is a
``jax.device_get`` of a scalar reduced from the output — NOT
``block_until_ready`` which is unreliable under the axon PJRT plugin
(see memory: tpu-env-quirks).

Usage: python scripts/bench_flash.py [--dtype bf16|f32] [--s 8192]
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.ops.flash_attention import flash_attention


def _fence(x):
    return float(jax.device_get(jnp.sum(x.astype(jnp.float32))))


def bench(fn, args, iters=5, warmup=2):
    for _ in range(warmup):
        _fence(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _fence(out)
    return (time.perf_counter() - t0) / iters


def attn_flops(b, s, h, d, causal):
    """Model-FLOPs convention of utils/flops.attention_flops (fwd 4BS^2HD,
    fwd+bwd 3x, causal halved) so TFLOP/s here and Trainer MFU agree."""
    from distributed_tensorflow_ibm_mnist_tpu.utils.flops import attention_flops

    return (
        attention_flops(b, s, h, d, causal=causal, with_backward=False),
        attention_flops(b, s, h, d, causal=causal, with_backward=True),
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bf16", choices=["bf16", "f32"])
    ap.add_argument("--b", type=int, default=4)
    ap.add_argument("--s", type=int, default=8192)
    ap.add_argument("--h", type=int, default=8)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--causal", type=int, default=1)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--bq", type=int, default=0)
    ap.add_argument("--bk", type=int, default=0)
    ap.add_argument("--impl", default="flash", choices=["flash", "vanilla"])
    args = ap.parse_args()

    import distributed_tensorflow_ibm_mnist_tpu.ops.flash_attention as fa

    if args.bq:
        fa._BLOCK_Q = args.bq
    if args.bk:
        fa._BLOCK_K = args.bk

    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32
    rng = np.random.default_rng(0)
    shape = (args.b, args.s, args.h, args.d)
    q, k, v = (
        jnp.asarray(rng.normal(size=shape, scale=0.5).astype(np.float32), dtype)
        for _ in range(3)
    )
    causal = bool(args.causal)
    if args.impl == "vanilla":
        from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import (
            vanilla_attention as attn,
        )
    else:
        attn = flash_attention

    fwd = jax.jit(lambda q, k, v: attn(q, k, v, causal=causal))

    def loss(q, k, v):
        return jnp.sum(attn(q, k, v, causal=causal).astype(jnp.float32) ** 2)

    fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    t_fwd = bench(fwd, (q, k, v), iters=args.iters)
    t_bwd = bench(lambda *a: fwdbwd(*a)[0], (q, k, v), iters=args.iters)

    f_fwd, f_tot = attn_flops(args.b, args.s, args.h, args.d, causal)
    print(
        f"shape B={args.b} S={args.s} H={args.h} D={args.d} causal={causal} dtype={args.dtype}"
    )
    print(f"fwd      {t_fwd*1e3:8.2f} ms   {f_fwd/t_fwd/1e12:6.2f} TFLOP/s (real work)")
    print(f"fwd+bwd  {t_bwd*1e3:8.2f} ms   {f_tot/t_bwd/1e12:6.2f} TFLOP/s (real work)")


if __name__ == "__main__":
    main()

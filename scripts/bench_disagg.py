"""Disaggregated prefill/decode serving bench (ISSUE 16).

Measures the headline of the role-typed tier: SHORT-request TTFT stays
flat while a long-prompt stream saturates prefill capacity, because
prefill-role replicas free their slot at packaging (the whole queue
drains every step) and the paged-KV handoff lands on separately-sized
decode capacity.  On the monolithic tier the same slots serve both
phases, so prompt work and decode tenancy contend for one budget.

Latency is measured in ROUTER STEPS, not wall microseconds: the driver
is a deterministic drip (arrivals pinned to step indices, greedy
sampling, fixed seeds), so TTFT-in-steps is a property of the queueing
structure and reproduces exactly — the "latency-structured" form of the
standing CPU caveat (tiny model, emulated devices: wall numbers are
reported for contrast but never gated, and no tokens/sec is claimed).

Legs over a tiny causal-LM (CPU-sized), buckets (8, 16), paged KV:

1. **control** — unloaded disaggregated tier (prefill(2) + decode(8)
   slots): the short drip alone.  TTFT p99 (steps) is the baseline.
2. **loaded** — the same short drip while a 1-per-step long-prompt
   stream saturates the prefill replica.  GATE: short TTFT p99 (steps)
   within 1.15x of the control — the disaggregation headline.  Every
   request must hand off exactly once (handoffs == requests).
3. **monolithic** — the identical mixed schedule on an equal-total-slot
   monolithic tier (2 x both(5)): measured and reported for contrast
   (short TTFT steps + wall, per-step wall).  GATE: token parity — the
   full mixed stream must generate token-for-token what the
   disaggregated tier generated (greedy; any mismatch exits nonzero).
   On a CPU-sized, slot-abundant tier the monolithic short TTFT can
   stay flat too; the structural contrast the bench pins instead is the
   census (leg 5): monolithic replicas carry the full program family in
   every slot, role-typed replicas provably carry only their half.
4. **chaos** — the mixed drip with a ``kv-handoff`` fault on the first
   delivery attempt: the router releases the hold, re-dispatches
   through a fresh prefill, and the delivered high-water keeps streams
   exactly-once.  GATES: zero drops (all done), stream == final tokens
   per request, >= 1 fault actually fired, pools at refcount zero after.
5. **census** — per-role compile pins from ``prewarm()["by_site"]``:
   decode replicas compile ZERO prefill/extend/insert programs, prefill
   replicas ZERO pick/window programs; and serving compiles NOTHING
   beyond prewarm (post-serve program delta == 0 on both tiers).
6. **reshard** — the tp>1 handoff seam: prefill tp=2 and decode tp=2 on
   DISJOINT 2-chip groups (``tp_device_groups(2, 2)`` over the armed
   virtual-CPU platform), the full mixed drip through it.  Every page
   crossing the handoff is assembled host-side from one mesh's shards
   and re-laid-out onto the other's — the gate is token parity with the
   monolithic tp=1 reference (greedy; any drift fails), plus all-done,
   handoffs == requests, provably disjoint device groups, pools zero.
   Skipped (recorded, gates untouched) when the host can't arm 4
   virtual devices.

Usage:  JAX_PLATFORMS=cpu python scripts/bench_disagg.py
Emits one JSON line (``"metric": "disagg"``); exits nonzero when any
gate fails.  ``DTM_BENCH_QUICK=1`` shrinks the drip to a tier-1-safe
subprocess smoke.  bench.py runs this as its ``disagg`` block
(``DTM_BENCH_SKIP_DISAGG=1`` skips).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

MODEL_KW = dict(num_classes=16, dim=32, depth=1, heads=2,
                dtype=jnp.float32)
BUCKETS = (8, 16)
MAX_LEN = 32
PAGE = 4
KV_PAGES = 96
LONG_LEN, LONG_NEW = 12, 5     # bucket-16 prompt, holds a decode slot
SHORT_LEN, SHORT_NEW = 3, 2    # bucket-8 prompt, two tokens
N_LONGS = 8 if QUICK else 24   # one per step: the saturating stream
N_SHORTS = 3 if QUICK else 8   # dripped every 3rd step
SHORT_EVERY = 3
MAX_STEPS = 3000

DISAGG_ROLES = ["prefill", "decode"]
DISAGG_SLOTS = [2, 8]
MONO_SLOTS = [5, 5]            # equal total decode-capable slots (10)


def _prompts(seed: int):
    rng = np.random.default_rng(seed)
    longs = [rng.integers(1, 16, size=(LONG_LEN,)).astype(np.int32)
             for _ in range(N_LONGS)]
    shorts = [rng.integers(1, 16, size=(SHORT_LEN,)).astype(np.int32)
              for _ in range(N_SHORTS)]
    return longs, shorts


def _arrivals(longs, shorts, *, with_longs: bool):
    """The drip schedule: long k arrives at step k (1/step — saturating),
    short j at step 1 + 3j, longs first within a step so shorts genuinely
    queue behind them."""
    arr = []
    if with_longs:
        for k, p in enumerate(longs):
            arr.append({"step": k, "kind": "long", "prompt": p,
                        "max_new": LONG_NEW})
    for j, p in enumerate(shorts):
        arr.append({"step": 1 + SHORT_EVERY * j, "kind": "short",
                    "prompt": p, "max_new": SHORT_NEW})
    arr.sort(key=lambda a: (a["step"], a["kind"] != "long"))
    return arr


def _build(roles, slots, chaos=None, tp=1, tp_groups=None):
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        Router,
    )

    model = get_model("causal_lm", **MODEL_KW)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine(tid, index):
        return InferenceEngine(
            model, params, slots=slots[index], max_len=MAX_LEN,
            kv_page_size=PAGE, kv_pages=KV_PAGES,
            scheduler=FIFOScheduler(max_len=MAX_LEN, buckets=BUCKETS,
                                    max_queue=64),
            trace_tid=tid, chaos=chaos, tp=tp,
            tp_devices=(tp_groups[index] if tp_groups is not None
                        else None),
            role=(roles[index] if roles is not None else "both"))

    router = Router(make_engine, len(slots), roles=roles, chaos=chaos)
    warm = router.prewarm()
    return router, warm


def _drive(router, arrivals):
    """Deterministic step-pumped driver: submit each arrival just before
    its pinned step, record the step (and wall time) of every request's
    first delivered token.  Returns (records, per-step wall seconds)."""
    cur = [0]
    recs, walls = [], []
    i = 0
    while i < len(arrivals) or router.outstanding:
        step = cur[0]
        while i < len(arrivals) and arrivals[i]["step"] <= step:
            a = arrivals[i]
            i += 1
            rec = {"kind": a["kind"], "submit_step": step,
                   "submit_t": time.monotonic(),
                   "first_step": None, "first_t": None, "stream": []}

            def _cb(rr, tok, rec=rec):
                rec["stream"].append(int(tok))
                if rec["first_step"] is None:
                    rec["first_step"] = cur[0]
                    rec["first_t"] = time.monotonic()

            rec["rr"] = router.submit(a["prompt"], a["max_new"],
                                      callback=_cb)
            recs.append(rec)
        t0 = time.monotonic()
        router.step()
        walls.append(time.monotonic() - t0)
        cur[0] = step + 1
        if cur[0] > MAX_STEPS:
            raise RuntimeError(f"drive exceeded {MAX_STEPS} steps "
                               f"({router.outstanding} outstanding)")
    return recs, walls


def _ttft_steps(recs, kind: str):
    return sorted(r["first_step"] - r["submit_step"] + 1 for r in recs
                  if r["kind"] == kind and r["first_step"] is not None)


def _leg(recs, walls) -> dict:
    shorts = _ttft_steps(recs, "short")
    ttft_ms = sorted((r["first_t"] - r["submit_t"]) * 1e3 for r in recs
                     if r["kind"] == "short" and r["first_t"] is not None)
    return {
        "requests": len(recs),
        "done": sum(r["rr"].status == "done" for r in recs),
        "steps": len(walls),
        "short_ttft_steps_p50": (float(np.percentile(shorts, 50))
                                 if shorts else None),
        "short_ttft_steps_p99": (float(np.percentile(shorts, 99))
                                 if shorts else None),
        "short_ttft_ms_p99": (round(float(np.percentile(ttft_ms, 99)), 3)
                              if ttft_ms else None),
        "step_wall_ms_p50": round(float(np.percentile(walls, 50)) * 1e3, 3),
    }


def _pools_zero(router) -> bool:
    """Every live pool back to refcount zero: pages still allocated are
    trie-owned prefix pages (reclaimable by design), nothing request- or
    packet-held."""
    for rep in router.replicas:
        if not rep.alive or rep.engine is None or rep.engine._pool is None:
            continue
        eng = rep.engine
        if eng._radix is not None:
            stack = [eng._radix.root]
            while stack:
                node = stack.pop()
                if node.ref != 0:
                    return False
                stack.extend(node.children.values())
            if eng._pool.allocated != eng._radix.n_blocks:
                return False
        elif eng._pool.allocated != 0:
            return False
    return True


def _reshard_leg(longs, shorts, mono_tokens) -> dict:
    """Leg 6: prefill tp=2 -> decode tp=2 over disjoint 2-chip groups.

    The handoff path already reassembles pages host-side from the source
    mesh's shards (kv_pool gather) and commits them under the target
    pool's own layout; at tp=2 -> tp=2 over DISJOINT groups both halves
    of that seam run on every delivery.  Token parity against the tp=1
    monolithic reference proves the resharding is bit-invisible.
    """
    from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
        tp_device_groups,
    )

    if len(jax.devices()) < 4:
        return {"skipped": True,
                "reason": f"only {len(jax.devices())} devices"}
    groups = tp_device_groups(2, 2)
    router, _ = _build(DISAGG_ROLES, DISAGG_SLOTS, tp=2, tp_groups=groups)
    recs, walls = _drive(router, _arrivals(longs, shorts, with_longs=True))
    tokens = [list(r["rr"].generated) for r in recs]
    dev_ids = [sorted(d.id for d in rep.engine._mesh.devices.flatten())
               for rep in router.replicas]
    leg = _leg(recs, walls)
    leg.update({
        "tp": 2,
        "handoffs": router.handoffs,
        "device_groups": dev_ids,
        "disjoint_devices": not (set(dev_ids[0]) & set(dev_ids[1])),
        "token_parity": tokens == mono_tokens and all(tokens),
        "pools_zero": _pools_zero(router),
    })
    router.close()
    return leg


def _census(warm, roles) -> dict:
    """Per-role program pins from the prewarm reports."""
    out = {}
    for idx, rep in warm["replicas"].items():
        sites = sorted(rep["by_site"])
        role = roles[int(idx)] if roles is not None else "both"
        prefill_sites = [s for s in sites if s.startswith(
            ("prefill[", "extend[", "slot_insert"))]
        decode_sites = [s for s in sites if s.startswith(
            ("first_pick", "decode_window[", "verify_window["))]
        out[str(idx)] = {"role": role, "sites": sites,
                         "prefill_sites": prefill_sites,
                         "decode_sites": decode_sites}
    return out


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.hostmesh import (
        ensure_virtual_cpu_devices,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        CompileTracker,
    )

    # the reshard leg needs 2 disjoint 2-chip groups; arming BEFORE any
    # array exists keeps the tp=1 legs on device 0 exactly as before
    ensure_virtual_cpu_devices(8)
    tracker = CompileTracker.install()
    longs, shorts = _prompts(7)

    # -- legs 1+2: disaggregated control, then loaded -------------------
    router, warm_d = _build(DISAGG_ROLES, DISAGG_SLOTS)
    census_d = _census(warm_d, DISAGG_ROLES)
    # one long + one short of warmup traffic: the first request through a
    # fresh process compiles a handful of host-glue programs prewarm
    # can't reach (scalar conversions outside any site); the census gate
    # pins the STEADY state — zero programs after first traffic
    _drive(router, _arrivals(longs[:1], shorts[:1], with_longs=True))
    snap = tracker.snapshot()
    recs_c, walls_c = _drive(router, _arrivals(longs, shorts,
                                               with_longs=False))
    handoffs0 = router.handoffs
    recs_l, walls_l = _drive(router, _arrivals(longs, shorts,
                                               with_longs=True))
    serve_delta_d = CompileTracker.delta(tracker.snapshot(), snap)
    control, loaded = _leg(recs_c, walls_c), _leg(recs_l, walls_l)
    loaded["handoffs"] = router.handoffs - handoffs0
    disagg_tokens = [list(r["rr"].generated) for r in recs_l]
    pools_d = _pools_zero(router)
    router.close()

    # -- leg 3: monolithic contrast + token parity ----------------------
    router_m, warm_m = _build(None, MONO_SLOTS)
    snap = tracker.snapshot()
    recs_m, walls_m = _drive(router_m, _arrivals(longs, shorts,
                                                 with_longs=True))
    serve_delta_m = CompileTracker.delta(tracker.snapshot(), snap)
    mono = _leg(recs_m, walls_m)
    mono_tokens = [list(r["rr"].generated) for r in recs_m]
    router_m.close()
    parity = disagg_tokens == mono_tokens and all(disagg_tokens)

    # -- leg 4: kv-handoff chaos — exactly-once under a dropped packet --
    inj = FaultInjector(FaultPlan(seed=5, faults=(
        FaultSpec(site="kv-handoff", at=(0,)),)))
    router_x, _ = _build(DISAGG_ROLES, DISAGG_SLOTS, chaos=inj)
    recs_x, _ = _drive(router_x, _arrivals(longs[:4], shorts[:2],
                                           with_longs=True))
    chaos = {
        "requests": len(recs_x),
        "done": sum(r["rr"].status == "done" for r in recs_x),
        "handoff_faults": router_x.handoff_faults,
        "redispatches": sum(r["rr"].redispatches for r in recs_x),
        "exactly_once": all(r["stream"] == list(r["rr"].generated)
                            for r in recs_x),
        "pools_zero": _pools_zero(router_x),
        "faults": inj.summary(),
    }
    router_x.close()

    # -- leg 6: cross-role tp resharding over disjoint groups -----------
    reshard = _reshard_leg(longs, shorts, mono_tokens)

    # -- gates ----------------------------------------------------------
    p99_c = control["short_ttft_steps_p99"] or 0.0
    p99_l = loaded["short_ttft_steps_p99"] or float("inf")
    by_role = {c["role"]: c for c in census_d.values()}
    gates = {
        "ttft_flat": p99_l <= 1.15 * p99_c,
        "all_done": all(leg["done"] == leg["requests"]
                        for leg in (control, loaded, mono)),
        "every_request_handed_off": loaded["handoffs"] == len(recs_l),
        "token_parity": parity,
        "census_decode_role_pure": (
            by_role["decode"]["prefill_sites"] == []
            and by_role["decode"]["decode_sites"] != []),
        "census_prefill_role_pure": (
            by_role["prefill"]["decode_sites"] == []
            and by_role["prefill"]["prefill_sites"] != []),
        "no_post_prewarm_compiles": (
            serve_delta_d["n_compiled_programs"] == 0
            and serve_delta_m["n_compiled_programs"] == 0),
        "chaos_fault_fired": chaos["handoff_faults"] >= 1,
        "chaos_zero_drops": chaos["done"] == chaos["requests"],
        "chaos_exactly_once": chaos["exactly_once"],
        "pools_zero": pools_d and chaos["pools_zero"],
    }
    if not reshard.get("skipped"):
        gates.update({
            "reshard_token_parity": reshard["token_parity"],
            "reshard_all_done": reshard["done"] == reshard["requests"],
            "reshard_every_request_handed_off": (
                reshard["handoffs"] == reshard["requests"]),
            "reshard_disjoint_devices": reshard["disjoint_devices"],
            "reshard_pools_zero": reshard["pools_zero"],
        })
    record = {
        "metric": "disagg",
        "quick": QUICK,
        "tiers": {
            "disagg": {"roles": DISAGG_ROLES, "slots": DISAGG_SLOTS},
            "monolithic": {"roles": None, "slots": MONO_SLOTS},
        },
        "stream": {"longs": N_LONGS, "shorts": N_SHORTS,
                   "long_len": LONG_LEN, "long_new": LONG_NEW,
                   "short_len": SHORT_LEN, "short_new": SHORT_NEW},
        "control": control,
        "loaded": loaded,
        "monolithic": mono,
        "ttft_ratio": (round(p99_l / p99_c, 4) if p99_c else None),
        "chaos": chaos,
        "reshard": reshard,
        "census": {"disagg": census_d, "monolithic": _census(warm_m, None),
                   "post_prewarm_programs": {
                       "disagg": serve_delta_d["n_compiled_programs"],
                       "monolithic": serve_delta_m["n_compiled_programs"]}},
        "gates": gates,
        "passed": all(gates.values()),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

"""Per-phase latency breakdown of an exported trace (utils/tracing).

Reads a Chrome-trace JSON written by ``Tracer.export_trace``, rebuilds the
span tree from the correlation args (``args.id`` / ``args.parent`` — the
viewer-independent identity every exported span carries), and prints

* a **per-phase table** — one row per ``cat/name`` span kind: count,
  total/mean/p50/p95/max milliseconds.  This is the table the per-request
  percentiles in ServingStats can't show: WHERE inside a request the time
  went (queue vs prefill vs decode), and where inside a training step
  (h2d vs dispatch vs fence);
* a **per-request rollup** (when ``request`` root spans are present) —
  per request: status, bucket, total latency, and the child-phase split,
  plus the unattributed remainder (root minus sum of child phases —
  scheduler hand-off and host-loop slack live there).  When the trace
  carries speculative-decoding spans (ISSUE 9), each request also rolls
  up its summed draft/verify/accept milliseconds and an ``accept_rate``
  column (accepted/drafted over the request's verify windows); on a
  disaggregated tier (ISSUE 16), ``cat="handoff"`` spans roll up into a
  per-request ``handoff_ms`` column (gather + install split, page and
  dedup-page counts) — the cost of moving a prefill between engines;
* the **instant and counter digest** — faults, restarts, cache hits, and
  per-track counter rollups (``queue_depth``, ``occupied_slots``:
  min/mean/max/last over the recorded change points — ISSUE 11), so a
  soak's timeline is summarized without a GUI.

``--critical-path`` (ISSUE 19 satellite) switches to the per-request
TREE view: for every span tree in the file (grouped by the distributed
trace id when spans carry one — one tree per traced request, spanning
front door → daemon → engine in a single-tracer export), it prints the
**longest chain** — from the root, repeatedly descending into the child
span that finishes last, the path a latency fix must shorten — and the
**top-3 self-time contributors** (span duration minus its children's,
the time a span spent NOT delegating).  This answers "where did this
slow request actually wait" without opening a viewer.

Validation runs first (``validate_trace``): a trace with unclosed spans,
dangling parents, or non-strict JSON is reported and (with ``--strict``)
fails the run — the same checks the tier-1 export test pins.

Usage:
    python scripts/trace_report.py TRACE.json [--json] [--strict] [--top N]
    python scripts/trace_report.py TRACE.json --critical-path

``--json`` emits one machine-readable JSON line instead of tables.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (  # noqa: E402
    load_trace,
    validate_trace,
)


def _pct(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (no numpy dep)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, max(0, round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[int(i)]


def analyze(doc: dict) -> dict:
    """Pure analysis of a loaded trace doc — also used by tests."""
    events = doc.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    counters = [e for e in events if e.get("ph") == "C"]

    # --- per-phase aggregation -------------------------------------------
    phases: dict[str, list[float]] = {}
    by_id: dict[int, dict] = {}
    for e in spans:
        key = f"{e.get('cat', '')}/{e['name']}"
        phases.setdefault(key, []).append(e.get("dur", 0) / 1e3)  # us -> ms
        sid = (e.get("args") or {}).get("id")
        if sid is not None:
            by_id[sid] = e

    phase_rows = []
    for key in sorted(phases, key=lambda k: -sum(phases[k])):
        vals = sorted(phases[key])
        phase_rows.append({
            "phase": key,
            "count": len(vals),
            "total_ms": round(sum(vals), 3),
            "mean_ms": round(sum(vals) / len(vals), 3),
            "p50_ms": round(_pct(vals, 50), 3),
            "p95_ms": round(_pct(vals, 95), 3),
            "max_ms": round(vals[-1], 3),
        })

    # --- per-request rollup ----------------------------------------------
    children: dict[int, list[dict]] = {}
    for e in spans:
        parent = (e.get("args") or {}).get("parent")
        if parent is not None:
            children.setdefault(parent, []).append(e)

    def _owning_request(e: dict, depth: int = 8) -> int | None:
        """Follow ``args.parent`` links up to the ``request`` root span
        (speculative draft/verify/accept spans parent on the request's
        open PHASE span, one level below the root)."""
        while depth > 0:
            parent = (e.get("args") or {}).get("parent")
            if parent is None or parent not in by_id:
                return None
            e = by_id[parent]
            if e["name"] == "request":
                return (e.get("args") or {}).get("id")
            depth -= 1
        return None

    # speculative-decoding rollup (ISSUE 9): per request, the summed
    # draft/verify/accept time and the acceptance counters the engine
    # stamps on each window's `accept` span
    spec_by_req: dict[int, dict] = {}
    for e in spans:
        if e.get("cat") != "speculative":
            continue
        rid = _owning_request(e)
        if rid is None:
            continue
        d = spec_by_req.setdefault(rid, {
            "draft_ms": 0.0, "verify_ms": 0.0, "accept_ms": 0.0,
            "windows": 0, "drafted": 0, "accepted": 0})
        key = f"{e['name']}_ms"
        if key in d:
            d[key] += e.get("dur", 0) / 1e3
        if e["name"] == "accept":
            a = e.get("args") or {}
            d["windows"] += 1
            d["drafted"] += int(a.get("drafted", 0))
            d["accepted"] += int(a.get("accepted", 0))
    for d in spec_by_req.values():
        for key in ("draft_ms", "verify_ms", "accept_ms"):
            d[key] = round(d[key], 3)
        d["accept_rate"] = (round(d["accepted"] / d["drafted"], 4)
                            if d["drafted"] > 0 else None)

    # disaggregated-handoff rollup (ISSUE 16): per request, the summed
    # gather (source) + install (destination) transfer time and the page
    # counts the handoff spans carry — the per-request cost of moving a
    # prefill between engines
    handoff_by_req: dict[int, dict] = {}
    for e in spans:
        if e.get("cat") != "handoff":
            continue
        rid = _owning_request(e)
        if rid is None:
            continue
        d = handoff_by_req.setdefault(rid, {
            "handoff_ms": 0.0, "gather_ms": 0.0, "install_ms": 0.0,
            "pages": 0, "dedup_pages": 0})
        dur = e.get("dur", 0) / 1e3
        d["handoff_ms"] += dur
        key = f"{e['name']}_ms"
        if key in d:
            d[key] += dur
        a = e.get("args") or {}
        if e["name"] == "install":
            d["pages"] += int(a.get("pages", 0))
            d["dedup_pages"] += int(a.get("dedup_pages", 0))
    for d in handoff_by_req.values():
        for key in ("handoff_ms", "gather_ms", "install_ms"):
            d[key] = round(d[key], 3)

    requests = []
    for e in spans:
        if e["name"] != "request":
            continue
        args = e.get("args") or {}
        total_ms = e.get("dur", 0) / 1e3
        split = {}
        for c in children.get(args.get("id"), []):
            split[c["name"]] = round(split.get(c["name"], 0.0) + c.get("dur", 0) / 1e3, 3)
        row = {
            "req": args.get("req"),
            "status": args.get("status"),
            "bucket": args.get("bucket"),
            "total_ms": round(total_ms, 3),
            "phases_ms": split,
            "other_ms": round(total_ms - sum(split.values()), 3),
        }
        spec = spec_by_req.get(args.get("id"))
        if spec is not None:
            row["speculative"] = spec
            row["accept_rate"] = spec["accept_rate"]
        ho = handoff_by_req.get(args.get("id"))
        if ho is not None:
            row["handoff"] = ho
            row["handoff_ms"] = ho["handoff_ms"]
        requests.append(row)
    requests.sort(key=lambda r: (r["req"] is None, r["req"]))

    # --- instants / counters ---------------------------------------------
    inst_counts: dict[str, int] = {}
    for e in instants:
        key = f"{e.get('cat', '')}/{e['name']}"
        inst_counts[key] = inst_counts.get(key, 0) + 1
    counter_last: dict[str, float] = {}
    counter_vals: dict[str, list[float]] = {}
    for e in counters:  # export order is chronological; last write wins
        for k, v in (e.get("args") or {}).items():
            key = f"{e['name']}.{k}"
            counter_last[key] = v
            counter_vals.setdefault(key, []).append(v)
    # ISSUE 11 satellite: the full track rollup.  Counters are recorded at
    # their CHANGE points (the tracer dedups repeats), so these are stats
    # over the sequence of distinct recorded values — min/max bound the
    # track exactly; mean is the mean recorded value, NOT time-weighted
    # (a long flat plateau counts once).
    counter_stats = {
        key: {
            "n": len(vals),
            "min": min(vals),
            "mean": round(sum(vals) / len(vals), 3),
            "max": max(vals),
            "last": counter_last[key],
        }
        for key, vals in sorted(counter_vals.items())
    }

    return {
        "n_events": len(events),
        "n_spans": len(spans),
        "phases": phase_rows,
        "requests": requests,
        "instants": dict(sorted(inst_counts.items())),
        "counters_last": dict(sorted(counter_last.items())),
        "counter_stats": counter_stats,
    }


def critical_path(doc: dict) -> list[dict]:
    """Per-tree critical-path analysis (pure; also used by tests).

    Returns one row per span tree, slowest first: the tree's trace id
    (when its spans carry one), root name/request, total duration, the
    longest chain (root → child finishing last → ...), and the top-3
    self-time contributors.  Self time clips negative (overlapping
    children can sum past the parent) to zero.
    """
    events = doc.get("traceEvents", [])
    spans = [e for e in events
             if e.get("ph") == "X"
             and (e.get("args") or {}).get("id") is not None]
    by_id = {e["args"]["id"]: e for e in spans}
    children: dict[int, list[dict]] = {}
    roots = []
    for e in spans:
        p = e["args"].get("parent")
        if p is not None and p in by_id:
            children.setdefault(p, []).append(e)
        else:
            roots.append(e)

    # inherit the trace id down parent edges so a tree whose root alone
    # carries args.trace still labels every row
    trace_of: dict[int, str] = {
        e["args"]["id"]: e["args"]["trace"]
        for e in spans if e["args"].get("trace")}
    changed = True
    while changed:
        changed = False
        for e in spans:
            sid, p = e["args"]["id"], e["args"].get("parent")
            if sid not in trace_of and p in trace_of:
                trace_of[sid] = trace_of[p]
                changed = True

    def _dur_ms(e: dict) -> float:
        return (e.get("dur") or 0) / 1e3

    def _self_ms(e: dict) -> float:
        kids = children.get(e["args"]["id"], [])
        return max(0.0, _dur_ms(e) - sum(_dur_ms(c) for c in kids))

    rows = []
    for root in roots:
        # longest chain: descend into the child that FINISHES last —
        # the dependency path the request's latency actually rode
        chain, node = [root], root
        while True:
            kids = children.get(node["args"]["id"], [])
            if not kids:
                break
            node = max(kids, key=lambda c: c["ts"] + (c.get("dur") or 0))
            chain.append(node)
        tree, stack = [], [root]
        while stack:
            e = stack.pop()
            tree.append(e)
            stack.extend(children.get(e["args"]["id"], []))
        top = sorted(tree, key=_self_ms, reverse=True)[:3]
        args = root.get("args") or {}
        rows.append({
            "trace": trace_of.get(args["id"]),
            "root": root["name"],
            "req": args.get("req", args.get("request")),
            "status": args.get("status"),
            "total_ms": round(_dur_ms(root), 3),
            "n_spans": len(tree),
            "chain": [f"{e['name']}({_dur_ms(e):.3f}ms)" for e in chain],
            "chain_ms": round(sum(_self_ms(e) for e in chain), 3),
            "top_contributors": [
                {"name": e["name"], "cat": e.get("cat", ""),
                 "self_ms": round(_self_ms(e), 3)} for e in top],
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def _fmt_table(rows: list[dict], cols: list[str]) -> str:
    if not rows:
        return "  (none)"
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows)) for c in cols}
    head = "  " + "  ".join(c.ljust(widths[c]) for c in cols)
    sep = "  " + "  ".join("-" * widths[c] for c in cols)
    body = [
        "  " + "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols)
        for r in rows
    ]
    return "\n".join([head, sep] + body)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="path to a Tracer.export_trace JSON file")
    ap.add_argument("--json", action="store_true", help="emit one JSON line")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 if validate_trace finds problems")
    ap.add_argument("--top", type=int, default=0,
                    help="limit per-request rollup to the N slowest (0 = all)")
    ap.add_argument("--critical-path", action="store_true",
                    help="per-request tree view: longest span chain + "
                         "top-3 self-time contributors")
    args = ap.parse_args(argv)

    problems = validate_trace(args.trace)
    if problems and args.strict:
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        return 1

    doc = load_trace(args.trace)

    if args.critical_path:
        rows = critical_path(doc)
        if args.top:
            rows = rows[: args.top]
        if args.json:
            json.dump({"critical_paths": rows, "problems": problems},
                      sys.stdout, allow_nan=False)
            print()
            return 0
        print(f"trace: {args.trace}  ({len(rows)} span tree(s))")
        if problems:
            print(f"\n!! {len(problems)} validation problem(s):")
            for p in problems:
                print(f"  - {p}")
        for r in rows:
            label = r["trace"] or f"{r['root']} #{r['req']}"
            print(f"\n[{label}] root={r['root']} req={r['req']} "
                  f"status={r['status']} total={r['total_ms']}ms "
                  f"({r['n_spans']} spans)")
            print("  critical path: " + " -> ".join(r["chain"]))
            print("  top contributors (self time):")
            for c in r["top_contributors"]:
                cat = f" [{c['cat']}]" if c["cat"] else ""
                print(f"    {c['name']}{cat}: {c['self_ms']}ms")
        return 0

    report = analyze(doc)
    report["problems"] = problems
    if args.top:
        report["requests"] = sorted(
            report["requests"], key=lambda r: -r["total_ms"]
        )[: args.top]

    if args.json:
        json.dump(report, sys.stdout, allow_nan=False)
        print()
        return 0

    print(f"trace: {args.trace}  ({report['n_events']} events, "
          f"{report['n_spans']} spans)")
    if problems:
        print(f"\n!! {len(problems)} validation problem(s):")
        for p in problems:
            print(f"  - {p}")
    print("\nPer-phase latency (ms):")
    print(_fmt_table(report["phases"],
                     ["phase", "count", "total_ms", "mean_ms", "p50_ms",
                      "p95_ms", "max_ms"]))
    if report["requests"]:
        print("\nPer-request rollup (ms):")
        spec_any = any("speculative" in r for r in report["requests"])
        ho_any = any("handoff" in r for r in report["requests"])
        rows = [
            {**{k: r[k] for k in ("req", "status", "bucket", "total_ms",
                                  "other_ms")},
             "phases": " ".join(f"{k}={v}" for k, v in r["phases_ms"].items()),
             **({"accept_rate": r.get("accept_rate")} if spec_any else {}),
             **({"handoff_ms": r.get("handoff_ms")} if ho_any else {})}
            for r in report["requests"]
        ]
        cols = ["req", "status", "bucket", "total_ms", "phases", "other_ms"]
        if spec_any:
            cols.append("accept_rate")
        if ho_any:
            cols.append("handoff_ms")
        print(_fmt_table(rows, cols))
    if report["instants"]:
        print("\nInstant events:")
        for k, v in report["instants"].items():
            print(f"  {k}: {v}")
    if report["counter_stats"]:
        print("\nCounter tracks (over recorded change points):")
        print(_fmt_table(
            [{"track": k, **v} for k, v in report["counter_stats"].items()],
            ["track", "n", "min", "mean", "max", "last"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())

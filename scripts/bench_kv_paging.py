"""Paged KV cache vs dense: concurrent sessions at a FIXED HBM budget.

The dense engine's concurrency is an allocation statement: every slot owns
``max_len`` cache positions whether the request uses them or not, so a
given KV budget buys exactly ``budget / (max_len * token_bytes)`` slots.
The paged engine (ISSUE 7) spends the SAME bytes as a page pool plus
per-slot block tables, so concurrency is bounded by LIVE tokens instead —
and a shared-system-prompt workload (the common serving shape: one long
instruction preamble, short per-user tails) shrinks live tokens further
because the radix cache stores the shared prefix's pages ONCE.

This bench pins that claim with a controlled experiment:

* **dense** — ``slots_dense`` slots at ``max_len``; its KV allocation
  defines the HBM budget for the whole experiment.
* **paged** — the same model with ``kv_page_size`` pages, ``kv_pages``
  chosen so the pool's token capacity EQUALS the dense allocation
  (``slots_dense * max_len / page_size`` pages + the reserved trash
  page), radix prefix sharing on, and 4x the slot count — the pool, not
  the slot array, is the limiting resource (overcommit: admission stalls
  when the pool is dry, which is the memory model under test).

Both legs serve the identical stream — ``n_requests`` prompts that share
one ``shared_len``-token system prefix and diverge into unique tails —
and the harness refuses to report a win unless the paged outputs are
token-identical to dense (greedy decode; slot count and paging must not
change a single token).  Peak CONCURRENT sessions is sampled after every
host step; the headline ``concurrency_ratio`` is paged peak / dense peak
at equal bytes, and the acceptance gate is >= 2x.

Run in a subprocess by bench.py or directly::

    JAX_PLATFORMS=cpu python scripts/bench_kv_paging.py

Prints ONE JSON line (``"metric": "kv_paging"``).  ``DTM_BENCH_QUICK=1``
shrinks the model/stream to a CI smoke of the same code paths.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

# model (FLOPs are not the point here; the memory model is)
VOCAB = 64 if QUICK else 256
DIM = 48 if QUICK else 128
DEPTH = 2 if QUICK else 3
HEADS = 4

# the experiment's geometry
MAX_LEN = 128
PAGE_SIZE = 16
SLOTS_DENSE = 4
SLOTS_PAGED = 16
SHARED_LEN = 48          # system prompt: 3 full shared pages
TAIL_LEN = 8             # unique per-user tail
MAX_NEW = 8 if QUICK else 16
N_REQUESTS = 12 if QUICK else 32
# equal token capacity: dense slots*max_len positions, re-cut into pages
KV_PAGES = SLOTS_DENSE * MAX_LEN // PAGE_SIZE + 1  # +1: reserved trash page


def build_engine(**kw):
    from distributed_tensorflow_ibm_mnist_tpu.models.causal_lm import CausalLM
    from distributed_tensorflow_ibm_mnist_tpu.serving import InferenceEngine

    model = CausalLM(num_classes=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                     dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return InferenceEngine(model, params, max_len=MAX_LEN,
                           buckets=(64, 128), eos_id=None, **kw)


def make_prompts():
    rng = np.random.default_rng(7)
    shared = rng.integers(1, VOCAB, size=SHARED_LEN).tolist()
    return [shared + rng.integers(1, VOCAB, size=TAIL_LEN).tolist()
            for _ in range(N_REQUESTS)]


def kv_bytes(engine) -> int:
    """Total decode-cache bytes (pool/rows + tables + cursors) — the HBM
    figure the budget comparison is made in."""
    return int(sum(leaf.nbytes for leaf in jax.tree.leaves(engine.cache)))


def serve(engine, prompts):
    """Serve the stream with a manual step loop, sampling live sessions
    (occupied slots) after every host step.  Returns (outputs, peak
    concurrency, wall seconds, stats summary)."""
    reqs = [engine.submit(p, max_new=MAX_NEW) for p in prompts]
    peak = 0
    t0 = time.perf_counter()
    while engine.has_work:
        engine.step()
        live = sum(1 for r in engine._slot_req if r is not None)
        peak = max(peak, live)
    wall = time.perf_counter() - t0
    assert all(r.status == "done" for r in reqs), \
        [r.status for r in reqs if r.status != "done"]
    return [tuple(r.generated) for r in reqs], peak, wall, engine.stats.summary()


def main() -> int:
    prompts = make_prompts()

    dense_eng = build_engine(slots=SLOTS_DENSE)
    dense_bytes = kv_bytes(dense_eng)
    dense_out, dense_peak, dense_wall, dense_stats = serve(dense_eng, prompts)

    paged_eng = build_engine(slots=SLOTS_PAGED, kv_page_size=PAGE_SIZE,
                             kv_pages=KV_PAGES)
    paged_bytes = kv_bytes(paged_eng)
    paged_out, paged_peak, paged_wall, paged_stats = serve(paged_eng, prompts)

    outputs_match = paged_out == dense_out
    ratio = paged_peak / dense_peak if dense_peak else 0.0
    useful = N_REQUESTS * MAX_NEW
    record = {
        "metric": "kv_paging",
        "quick": QUICK,
        "model": {"dim": DIM, "depth": DEPTH, "heads": HEADS, "vocab": VOCAB},
        "workload": {
            "requests": N_REQUESTS, "shared_prefix_tokens": SHARED_LEN,
            "tail_tokens": TAIL_LEN, "max_new": MAX_NEW,
        },
        "geometry": {
            "max_len": MAX_LEN, "page_size": PAGE_SIZE,
            "slots_dense": SLOTS_DENSE, "slots_paged": SLOTS_PAGED,
            "kv_pages": KV_PAGES,
        },
        "dense": {
            "kv_bytes": dense_bytes, "peak_concurrency": dense_peak,
            "wall_s": round(dense_wall, 4),
            "tok_per_s": round(useful / dense_wall, 1),
        },
        "paged": {
            "kv_bytes": paged_bytes, "peak_concurrency": paged_peak,
            "wall_s": round(paged_wall, 4),
            "tok_per_s": round(useful / paged_wall, 1),
            "kv_pages_peak": paged_stats["kv_pages_peak"],
            "kv_pages_total": paged_stats["kv_pages_total"],
            "radix_hits": paged_stats["radix_hits"],
            "radix_hit_tokens": paged_stats["radix_hit_tokens"],
        },
        "bytes_ratio": round(paged_bytes / dense_bytes, 4),
        "concurrency_ratio": round(ratio, 2),
        "outputs_match": outputs_match,
        "ok": bool(outputs_match and ratio >= 2.0),
    }
    print(json.dumps(record))
    return 0 if record["ok"] else 4


if __name__ == "__main__":
    sys.exit(main())

"""Paged KV cache vs dense: concurrent sessions at a FIXED HBM budget.

The dense engine's concurrency is an allocation statement: every slot owns
``max_len`` cache positions whether the request uses them or not, so a
given KV budget buys exactly ``budget / (max_len * token_bytes)`` slots.
The paged engine (ISSUE 7) spends the SAME bytes as a page pool plus
per-slot block tables, so concurrency is bounded by LIVE tokens instead —
and a shared-system-prompt workload (the common serving shape: one long
instruction preamble, short per-user tails) shrinks live tokens further
because the radix cache stores the shared prefix's pages ONCE.

This bench pins that claim with a controlled experiment:

* **dense** — ``slots_dense`` slots at ``max_len``; its KV allocation
  defines the HBM budget for the whole experiment.
* **paged** — the same model with ``kv_page_size`` pages, ``kv_pages``
  chosen so the pool's token capacity EQUALS the dense allocation
  (``slots_dense * max_len / page_size`` pages + the reserved trash
  page), radix prefix sharing on, and 4x the slot count — the pool, not
  the slot array, is the limiting resource (overcommit: admission stalls
  when the pool is dry, which is the memory model under test).

Both legs serve the identical stream — ``n_requests`` prompts that share
one ``shared_len``-token system prefix and diverge into unique tails —
and the harness refuses to report a win unless the paged outputs are
token-identical to dense (greedy decode; slot count and paging must not
change a single token).  Peak CONCURRENT sessions is sampled after every
host step; the headline ``concurrency_ratio`` is paged peak / dense peak
at equal bytes, and the acceptance gate is >= 2x.

A third leg (ISSUE 10 satellite) re-runs the paged stream with a GQA
model at the same dim (``heads_kv = heads // 4``): pages are
token-granular, so pages-per-request MATCHES the MHA leg while each
page stores ``heads_kv`` heads — peak live KV bytes drop by ~H/Hkv,
reported as ``gqa.mha_over_gqa_bytes`` and gated at >= 0.9 * H/Hkv,
with token parity pinned against a dense GQA engine.

Run in a subprocess by bench.py or directly::

    JAX_PLATFORMS=cpu python scripts/bench_kv_paging.py

Prints ONE JSON line (``"metric": "kv_paging"``).  ``DTM_BENCH_QUICK=1``
shrinks the model/stream to a CI smoke of the same code paths.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

# model (FLOPs are not the point here; the memory model is)
VOCAB = 64 if QUICK else 256
DIM = 48 if QUICK else 128
DEPTH = 2 if QUICK else 3
HEADS = 4

# the experiment's geometry
MAX_LEN = 128
PAGE_SIZE = 16
SLOTS_DENSE = 4
SLOTS_PAGED = 16
SHARED_LEN = 48          # system prompt: 3 full shared pages
TAIL_LEN = 8             # unique per-user tail
MAX_NEW = 8 if QUICK else 16
N_REQUESTS = 12 if QUICK else 32
# equal token capacity: dense slots*max_len positions, re-cut into pages
KV_PAGES = SLOTS_DENSE * MAX_LEN // PAGE_SIZE + 1  # +1: reserved trash page


def build_engine(heads_kv=None, **kw):
    from distributed_tensorflow_ibm_mnist_tpu.models.causal_lm import CausalLM
    from distributed_tensorflow_ibm_mnist_tpu.serving import InferenceEngine

    mk = {} if heads_kv is None else {"heads_kv": heads_kv}
    model = CausalLM(num_classes=VOCAB, dim=DIM, depth=DEPTH, heads=HEADS,
                     dtype=jnp.float32, **mk)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return InferenceEngine(model, params, max_len=MAX_LEN,
                           buckets=(64, 128), eos_id=None, **kw)


def make_prompts():
    rng = np.random.default_rng(7)
    shared = rng.integers(1, VOCAB, size=SHARED_LEN).tolist()
    return [shared + rng.integers(1, VOCAB, size=TAIL_LEN).tolist()
            for _ in range(N_REQUESTS)]


def kv_bytes(engine) -> int:
    """Total decode-cache bytes (pool/rows + tables + cursors) — the HBM
    figure the budget comparison is made in."""
    return int(sum(leaf.nbytes for leaf in jax.tree.leaves(engine.cache)))


def serve(engine, prompts):
    """Serve the stream with a manual step loop, sampling live sessions
    (occupied slots) after every host step.  Returns (outputs, peak
    concurrency, wall seconds, stats summary)."""
    reqs = [engine.submit(p, max_new=MAX_NEW) for p in prompts]
    peak = 0
    t0 = time.perf_counter()
    while engine.has_work:
        engine.step()
        live = sum(1 for r in engine._slot_req if r is not None)
        peak = max(peak, live)
    wall = time.perf_counter() - t0
    assert all(r.status == "done" for r in reqs), \
        [r.status for r in reqs if r.status != "done"]
    return [tuple(r.generated) for r in reqs], peak, wall, engine.stats.summary()


def main() -> int:
    prompts = make_prompts()

    dense_eng = build_engine(slots=SLOTS_DENSE)
    dense_bytes = kv_bytes(dense_eng)
    dense_out, dense_peak, dense_wall, dense_stats = serve(dense_eng, prompts)

    paged_eng = build_engine(slots=SLOTS_PAGED, kv_page_size=PAGE_SIZE,
                             kv_pages=KV_PAGES)
    paged_bytes = kv_bytes(paged_eng)
    paged_out, paged_peak, paged_wall, paged_stats = serve(paged_eng, prompts)

    # GQA leg (ISSUE 10 satellite): the same dim with heads_kv = heads//4
    # — pages are token-granular, so a request PINS the same page COUNT as
    # the MHA leg while every page holds Hkv instead of H heads: bytes
    # drop by ~heads/heads_kv at equal live tokens.  Token parity is
    # checked against a dense GQA engine (paging stays invisible in the
    # tokens); the MHA comparison is bytes-only (different weights).
    HEADS_KV = max(1, HEADS // 4)
    gq_dense_out, _, _, _ = serve(
        build_engine(heads_kv=HEADS_KV, slots=SLOTS_DENSE), prompts)
    gq_eng = build_engine(heads_kv=HEADS_KV, slots=SLOTS_PAGED,
                          kv_page_size=PAGE_SIZE, kv_pages=KV_PAGES)
    gq_bytes = kv_bytes(gq_eng)
    gq_out, gq_peak, gq_wall, gq_stats = serve(gq_eng, prompts)

    outputs_match = paged_out == dense_out
    gq_match = gq_out == gq_dense_out
    # bytes per live token, MHA paged vs GQA paged — the ~H/Hkv claim
    gq_bytes_ratio = (paged_stats["kv_bytes_peak"]
                      / max(gq_stats["kv_bytes_peak"], 1))
    ratio = paged_peak / dense_peak if dense_peak else 0.0
    useful = N_REQUESTS * MAX_NEW
    record = {
        "metric": "kv_paging",
        "quick": QUICK,
        "model": {"dim": DIM, "depth": DEPTH, "heads": HEADS, "vocab": VOCAB},
        "workload": {
            "requests": N_REQUESTS, "shared_prefix_tokens": SHARED_LEN,
            "tail_tokens": TAIL_LEN, "max_new": MAX_NEW,
        },
        "geometry": {
            "max_len": MAX_LEN, "page_size": PAGE_SIZE,
            "slots_dense": SLOTS_DENSE, "slots_paged": SLOTS_PAGED,
            "kv_pages": KV_PAGES,
        },
        "dense": {
            "kv_bytes": dense_bytes, "peak_concurrency": dense_peak,
            "wall_s": round(dense_wall, 4),
            "tok_per_s": round(useful / dense_wall, 1),
        },
        "paged": {
            "kv_bytes": paged_bytes, "peak_concurrency": paged_peak,
            "wall_s": round(paged_wall, 4),
            "tok_per_s": round(useful / paged_wall, 1),
            "kv_pages_peak": paged_stats["kv_pages_peak"],
            "kv_pages_total": paged_stats["kv_pages_total"],
            "radix_hits": paged_stats["radix_hits"],
            "radix_hit_tokens": paged_stats["radix_hit_tokens"],
        },
        "gqa": {
            "heads_kv": HEADS_KV,
            "kv_bytes": gq_bytes,
            "kv_bytes_live": gq_stats["kv_bytes_live"],
            "kv_bytes_peak": gq_stats["kv_bytes_peak"],
            "kv_pages_peak": gq_stats["kv_pages_peak"],
            "pages_per_request": round(
                gq_stats["kv_pages_total"] / N_REQUESTS, 2),
            "mha_pages_per_request": round(
                paged_stats["kv_pages_total"] / N_REQUESTS, 2),
            "peak_concurrency": gq_peak,
            "tok_per_s": round(useful / gq_wall, 1),
            # MHA-paged peak bytes over GQA-paged peak bytes at the same
            # stream: pages are token-granular so the page COUNT matches
            # and the whole ~H/Hkv saving shows up here
            "mha_over_gqa_bytes": round(gq_bytes_ratio, 3),
            "outputs_match_dense_gqa": gq_match,
        },
        "bytes_ratio": round(paged_bytes / dense_bytes, 4),
        "concurrency_ratio": round(ratio, 2),
        "outputs_match": outputs_match,
        "ok": bool(outputs_match and ratio >= 2.0 and gq_match
                   and gq_bytes_ratio >= 0.9 * HEADS / HEADS_KV),
    }
    print(json.dumps(record))
    return 0 if record["ok"] else 4


if __name__ == "__main__":
    sys.exit(main())

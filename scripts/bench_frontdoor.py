"""Front-door wire bench: HTTP/SSE parity, failover under connected
clients, backpressure on the wire (ISSUE 17).

The frontend's claims are all STRUCTURAL (the standing CPU caveat: no
tokens/sec numbers here), so every leg gates a correctness property of
the protocol layer, end to end through real sockets:

1. **parity** — for the same prompts, greedy AND seeded-sampled, the
   token sequences served over the wire (unary JSON and the SSE stream,
   parsed off the actual bytes) are identical to
   :meth:`ServingDaemon.stream` in-process.  The transport adds nothing
   and loses nothing.
2. **chaos** — ``daemon-pump`` chaos kills one of two pumps while SSE
   clients are CONNECTED and mid-stream: every stream still ends
   ``done`` with its full token sequence delivered exactly once (the
   wire inherits the tier's failover guarantee), and ``/healthz`` shows
   the failover in the census.
3. **backpressure** — a flood against a tiny admission bound with a
   warmed :class:`DeadlineAwarePolicy`: floods see 429/503 with the
   policy's ``Retry-After`` hint on the wire (machine-readable
   ``retry_after_s`` in the body, integer header), the daemon counts
   ``rejected_with_hint``, and conservation stays exact — every
   rejection happened at the door.
4. **observability + drain** — one ``/metrics`` scrape carries frontend
   and tier counters together; every leg drains to ``open_spans == 0``
   and refcount-zero pools (a wire client is not allowed to leak a slot,
   a page, or a span).
5. **tracing** (ISSUE 19) — with distributed tracing ON, the
   instrumentation's self-measured share of unary HTTP wall stays
   within 2% (every tracer entry point timer-wrapped — same
   methodology as bench_tracing's overhead leg), ``traceparent`` is
   echoed on the wire, and at ``trace_sample_rate=0.0`` shed (429/503)
   requests are still tail-kept in the export with a terminal ``shed``
   span while 200s are head-dropped.

Usage:  JAX_PLATFORMS=cpu python scripts/bench_frontdoor.py
Emits one JSON line (``"metric": "frontdoor"``); exits nonzero when any
gate fails.  ``DTM_BENCH_QUICK=1`` shrinks the waves to a tier-1-safe
smoke.  bench.py runs this as its ``frontdoor`` block
(``DTM_BENCH_SKIP_FRONTDOOR=1`` skips).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

MODEL_KW = dict(num_classes=16, dim=32, depth=1, heads=2,
                dtype=jnp.float32)
MAX_NEW = 4
N_PARITY = 3 if QUICK else 6
N_CHAOS = 6 if QUICK else 12
N_FLOOD = 8 if QUICK else 16
N_TRACE = 4 if QUICK else 8
N_TWAVES = 3 if QUICK else 6
WAIT_S = 120.0


def _mk_prompts(seed: int, n: int):
    rng = np.random.default_rng(seed)
    return [[int(t) for t in rng.integers(1, 16, size=(2 + i % 5,))]
            for i in range(n)]


def _build(chaos=None, tracer=None, n_replicas=2, max_queue=64,
           policy=None, trace_sample_rate=None):
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        FrontDoor,
        InferenceEngine,
        Router,
        ServingDaemon,
    )

    model = get_model("causal_lm", **MODEL_KW)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine(tid):
        return InferenceEngine(
            model, params, slots=2, max_len=16, kv_page_size=4,
            scheduler=FIFOScheduler(max_len=16, buckets=(8,), max_queue=64),
            tracer=tracer, trace_tid=tid, chaos=chaos)

    router = Router(make_engine, n_replicas, chaos=chaos, tracer=tracer)
    router.prewarm()
    daemon = ServingDaemon(router, max_queue=max_queue, policy=policy,
                           liveness_timeout_s=30.0).start()
    fd_kw = ({} if trace_sample_rate is None
             else {"trace_sample_rate": trace_sample_rate})
    fd = FrontDoor(daemon, **fd_kw).start_in_thread()
    return daemon, fd


def _pools_zero(router) -> bool:
    for rep in router.replicas:
        if not rep.alive or rep.engine._pool is None:
            continue
        eng = rep.engine
        if eng._radix is not None:
            stack = [eng._radix.root]
            while stack:
                node = stack.pop()
                if node.ref != 0:
                    return False
                stack.extend(node.children.values())
            if eng._pool.allocated != eng._radix.n_blocks:
                return False
        elif eng._pool.allocated != 0:
            return False
    return True


def _teardown(daemon, fd) -> dict:
    fd.stop()
    drained = daemon.drain(timeout=30.0)
    pools = _pools_zero(daemon.router)
    daemon.close()
    return {"drained_clean": drained, "pools_zero": pools}


def leg_parity() -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FrontDoorClient,
        SamplingParams,
    )

    daemon, fd = _build()
    cli = FrontDoorClient("127.0.0.1", fd.port)
    sampled = {"temperature": 0.7, "top_k": 5, "seed": 42}
    compared = 0
    mismatches = []
    for prompt in _mk_prompts(21, N_PARITY):
        for wire_kw, sp in ((None, None),
                            (sampled, SamplingParams(**sampled))):
            kw = {} if wire_kw is None else {"sampling": wire_kw}
            unary = cli.generate(prompt, MAX_NEW, **kw)["tokens"]
            sse = list(cli.stream(prompt, MAX_NEW, **kw))
            dr = daemon.submit(prompt, MAX_NEW, sampling=sp)
            ref = list(daemon.stream(dr))
            compared += 1
            if not (unary == sse == ref):
                mismatches.append({"prompt": prompt, "sampled": sp is not None,
                                   "unary": unary, "sse": sse, "ref": ref})
    out = {"compared": compared, "mismatches": mismatches,
           **_teardown(daemon, fd)}
    out["parity"] = not mismatches
    return out


def leg_chaos() -> dict:
    """Pump kill with clients CONNECTED: the first pump to find work dies
    (daemon-pump raise at event 0) while every request is an open SSE
    stream on a real socket."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import FrontDoorClient
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer

    inj = FaultInjector(FaultPlan(seed=5, faults=(
        FaultSpec(site="daemon-pump", kind="raise", at=(0,)),)))
    tracer = Tracer()
    daemon, fd = _build(chaos=inj, tracer=tracer)
    prompts = _mk_prompts(22, N_CHAOS)
    results: dict[int, dict] = {}
    lock = threading.Lock()

    def client(i, prompt):
        cli = FrontDoorClient("127.0.0.1", fd.port, timeout=WAIT_S)
        toks = list(cli.stream(prompt, MAX_NEW, deadline_s=WAIT_S))
        with lock:
            results[i] = {"tokens": toks, "terminal": cli.last_terminal}

    threads = [threading.Thread(target=client, args=(i, p))
               for i, p in enumerate(prompts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=WAIT_S)
    # reference: the same prompts greedy through the (post-failover) tier
    refs = [daemon.submit(p, MAX_NEW) for p in prompts]
    ok = drops = 0
    exactly_once = True
    for i, dr in enumerate(refs):
        dr.wait(timeout=WAIT_S)
        got = results.get(i)
        if got is None or got["terminal"] is None \
                or got["terminal"].get("status") != "done":
            drops += 1
            continue
        ok += 1
        if got["tokens"] != list(dr.tokens) \
                or len(got["tokens"]) != got["terminal"]["n_tokens"]:
            exactly_once = False
    cli = FrontDoorClient("127.0.0.1", fd.port)
    health = cli.healthz()
    cons = daemon.conservation()
    out = {
        "streams": len(prompts),
        "streams_done": ok,
        "drops": drops,
        "exactly_once": exactly_once,
        "failovers": daemon.router.failovers,
        "pump_faults": daemon.counters["pump_faults"],
        "healthz_spawns": sum(v["spawns"] for v in health["replicas"].values()),
        "conserved": cons["conserved"],
        "faults": inj.summary(),
        **_teardown(daemon, fd),
    }
    out["open_spans"] = tracer.open_spans
    return out


def leg_backpressure() -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        DeadlineAwarePolicy,
        FrontDoorClient,
    )

    policy = DeadlineAwarePolicy(concurrency=4)
    daemon, fd = _build(n_replicas=2, max_queue=3, policy=policy)
    cli = FrontDoorClient("127.0.0.1", fd.port)
    # warm the EMA so rejections carry a predicted wait
    warm = cli.generate(_mk_prompts(23, 1)[0], MAX_NEW)
    warm_ok = cli.last_status == 200 and warm.get("status") == "done"
    flood = _mk_prompts(24, N_FLOOD)
    statuses: list[tuple[int, float | None, str | None]] = []
    lock = threading.Lock()

    def flooder(prompt):
        c = FrontDoorClient("127.0.0.1", fd.port, timeout=WAIT_S)
        body = c.generate(prompt, MAX_NEW, deadline_s=WAIT_S)
        with lock:
            statuses.append((c.last_status, body.get("retry_after_s"),
                             (c.last_headers or {}).get("retry-after")))

    threads = [threading.Thread(target=flooder, args=(p,)) for p in flood]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=WAIT_S)
    n_ok = sum(1 for s, _, _ in statuses if s == 200)
    n_reject = sum(1 for s, _, _ in statuses if s in (429, 503))
    hinted = [(s, b, h) for s, b, h in statuses
              if s in (429, 503) and b is not None]
    hints_consistent = all(h is not None and int(h) >= 1 and b > 0
                           for _, b, h in hinted)
    deadline = time.monotonic() + WAIT_S
    while time.monotonic() < deadline:
        cons = daemon.conservation()
        if cons["outstanding"] == 0:
            break
        time.sleep(0.02)
    metrics_text = cli.metrics()
    out = {
        "flood": len(flood),
        "ok_200": n_ok,
        "rejected_wire": n_reject,
        "hinted": len(hinted),
        "hints_consistent": hints_consistent,
        "rejected_with_hint": daemon.counters["rejected_with_hint"],
        "policy_shed": policy.shed,
        "warm_ok": warm_ok,
        "conserved": cons["conserved"],
        "metrics_has_frontdoor": "frontdoor_requests" in metrics_text,
        "metrics_has_rejects": "frontdoor_rejected" in metrics_text,
        **_teardown(daemon, fd),
    }
    return out


def leg_tracing() -> dict:
    """Distributed tracing ON, measured on the wire (ISSUE 19).

    Overhead: paired wall deltas cannot resolve 2% on a shared CPU box,
    so — like bench_tracing's overhead leg — every tracer entry point is
    wrapped with a timer and the gated number is total tracing time over
    total unary-HTTP wall (conservative: the wrapper's own cost counts
    as tracing).  Shed: against a tiny admission bound with
    ``trace_sample_rate=0.0``, rejected (429/503) requests must still be
    in the export — tail-kept via their terminal ``shed`` span — while
    successful 200s are head-dropped."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        DeadlineAwarePolicy,
        FrontDoorClient,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        TraceContext,
        Tracer,
        trace_forest,
    )

    # -- overhead: instrumentation share of unary HTTP wall
    tracer = Tracer()
    spent = {"s": 0.0}

    def timed(fn):
        def wrapped(*a, **k):
            t0 = time.perf_counter()
            try:
                return fn(*a, **k)
            finally:
                spent["s"] += time.perf_counter() - t0
        return wrapped

    for name in ("begin", "end", "complete", "instant", "annotate",
                 "track"):
        setattr(tracer, name, timed(getattr(tracer, name)))
    daemon, fd = _build(tracer=tracer)
    cli = FrontDoorClient("127.0.0.1", fd.port)
    prompts = _mk_prompts(25, N_TRACE)

    def wave() -> float:
        t0 = time.perf_counter()
        for p in prompts:
            cli.generate(p, MAX_NEW)
        return time.perf_counter() - t0

    wave()
    wave()                       # warm: compile, pools, socket path
    tp_echoed = TraceContext.parse_traceparent(
        (cli.last_headers or {}).get("traceparent")) is not None
    spent["s"] = 0.0
    gc.collect()                 # a gen2 pause inside a wrapped call
    gc.disable()                 # would read as tracing time
    try:
        walls = [wave() for _ in range(N_TWAVES)]
    finally:
        gc.enable()
    share = spent["s"] / sum(walls)
    down_a = _teardown(daemon, fd)
    open_a = tracer.open_spans

    # -- shed tail-keep at sample rate zero
    tracer2 = Tracer()
    policy = DeadlineAwarePolicy(concurrency=4)
    daemon, fd = _build(tracer=tracer2, max_queue=3, policy=policy,
                        trace_sample_rate=0.0)
    cli = FrontDoorClient("127.0.0.1", fd.port)
    warm = cli.generate(_mk_prompts(26, 1)[0], MAX_NEW)
    warm_ok = cli.last_status == 200 and warm.get("status") == "done"
    hits: list[tuple[int, str | None]] = []
    lock = threading.Lock()

    def flooder(prompt):
        c = FrontDoorClient("127.0.0.1", fd.port, timeout=WAIT_S)
        c.generate(prompt, MAX_NEW, deadline_s=WAIT_S)
        with lock:
            hits.append((c.last_status,
                         (c.last_headers or {}).get("traceparent")))

    threads = [threading.Thread(target=flooder, args=(p,))
               for p in _mk_prompts(27, N_FLOOD)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=WAIT_S)
    deadline = time.monotonic() + WAIT_S
    while time.monotonic() < deadline:
        if daemon.conservation()["outstanding"] == 0:
            break
        time.sleep(0.02)
    down_b = _teardown(daemon, fd)
    kept = trace_forest(tracer2.to_doc(sampler=fd.sampler))
    shed_tids = [TraceContext.parse_traceparent(tp).trace_id
                 for s, tp in hits if s in (429, 503) and tp]
    ok_tids = [TraceContext.parse_traceparent(tp).trace_id
               for s, tp in hits if s == 200 and tp]
    shed_kept = all(t in kept and "shed" in kept[t]["names"]
                    for t in shed_tids)
    ok_dropped = all(t not in kept for t in ok_tids)
    return {
        "waves": N_TWAVES, "requests_per_wave": len(prompts),
        "wall_min_s": round(min(walls), 4),
        "tracing_share": round(share, 4),
        "traceparent_echoed": tp_echoed,
        "warm_ok": warm_ok,
        "flood": len(hits),
        "shed_on_wire": len(shed_tids),
        "shed_kept": shed_kept,
        "ok_dropped_at_rate0": ok_dropped,
        "open_spans": open_a + tracer2.open_spans,
        "drained_clean": down_a["drained_clean"] and down_b["drained_clean"],
        "pools_zero": down_a["pools_zero"] and down_b["pools_zero"],
    }


def main() -> None:
    parity = leg_parity()
    chaos = leg_chaos()
    backpressure = leg_backpressure()
    tracing = leg_tracing()
    gates = {
        "tracing_overhead_le_2pct": tracing["tracing_share"] <= 0.02,
        "tracing_traceparent_on_wire": tracing["traceparent_echoed"],
        "tracing_shed_tail_kept": tracing["shed_on_wire"] >= 1
        and tracing["shed_kept"],
        "tracing_ok_dropped_at_rate0": tracing["warm_ok"]
        and tracing["ok_dropped_at_rate0"],
        "tracing_no_open_spans": tracing["open_spans"] == 0,
        "wire_parity": parity["parity"] and parity["compared"] >= 2,
        "chaos_failover_happened": chaos["failovers"] >= 1
        and chaos["pump_faults"] >= 1,
        "chaos_zero_drops": chaos["drops"] == 0
        and chaos["streams_done"] == chaos["streams"],
        "chaos_exactly_once": chaos["exactly_once"],
        "chaos_conserved": chaos["conserved"],
        "no_open_spans": chaos["open_spans"] == 0,
        "backpressure_rejects_on_wire": backpressure["rejected_wire"] >= 1,
        "backpressure_hints": backpressure["hinted"] >= 1
        and backpressure["hints_consistent"]
        and backpressure["rejected_with_hint"] >= 1,
        "backpressure_conserved": backpressure["conserved"],
        "one_scrape_both_worlds": backpressure["metrics_has_frontdoor"]
        and backpressure["metrics_has_rejects"],
        "drained_clean": all(l["drained_clean"] and l["pools_zero"]
                             for l in (parity, chaos, backpressure,
                                       tracing)),
    }
    record = {
        "metric": "frontdoor",
        "quick": QUICK,
        "parity": parity,
        "chaos": chaos,
        "backpressure": backpressure,
        "tracing": tracing,
        "gates": gates,
        "passed": all(gates.values()),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

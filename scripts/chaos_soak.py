"""Chaos soak: seeded multi-fault plans against training AND serving.

The ISSUE 3 acceptance proof, as one JSON record.  Three phases:

1. **Training soak** — a stream-mode run to completion, twice: fault-free,
   then under a seeded :class:`FaultPlan` injecting a torn checkpoint
   write, a train-step NaN, a checkpoint-read fault, and a data-batch
   I/O fault, supervised by ``run_with_recovery``.  Asserts the chaos
   run's final durable state is BIT-IDENTICAL to the fault-free run
   (restore-from-intact + absolute-epoch data schedule make recovery a
   replay, not an approximation), and reports restarts + recovery
   latency (chaos wall-clock minus fault-free wall-clock).
2. **Serving soak** — a mixed request stream through the engine, twice:
   fault-free, then under a plan injecting a poisoned request
   (``serving-admit``), a raising user callback (``serving-callback``),
   and a transient decode fault (``serving-step``, absorbed by the stall
   watchdog).  Asserts every NON-poisoned request retires ``done`` with
   byte-identical outputs, and the casualties land in terminal ``failed``.
3. **Overhead guard** — asserts the zero-overhead contract structurally
   (components built without an injector hold ``_chaos=None``: each site
   is a single attribute test, and there is no injector to consult), then
   measures it: serving steps/sec with no chaos wiring vs an empty-plan
   injector, and the integrity-manifest cost per checkpoint (digest time
   vs save time — the docs/PERFORMANCE.md figure).

Usage:  JAX_PLATFORMS=cpu python scripts/chaos_soak.py
Emits one line: {"metric": "chaos", ..., "passed": true}.
bench.py runs this in a subprocess as its `chaos` block
(DTM_BENCH_SKIP_CHAOS=1 skips).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def _leaves_identical(a, b) -> bool:
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    if len(la) != len(lb):
        return False
    for (pa, xa), (pb, xb) in zip(la, lb):
        if pa != pb or not np.array_equal(np.asarray(xa), np.asarray(xb)):
            return False
    return True


def training_soak(root: str) -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig
    from distributed_tensorflow_ibm_mnist_tpu.utils.elastic import run_with_recovery

    cfg = RunConfig(
        name="chaos_soak", model="mlp", model_kwargs={"hidden": (32,), "dtype": jnp.float32},
        synthetic=True, n_train=512, n_test=128, batch_size=64, epochs=4,
        dp=1, quiet=True, eval_every=1, checkpoint_every=1,
        input_mode="stream", stream_chunk=2,
        checkpoint_dir=os.path.join(root, "free"),
    )

    t0 = time.perf_counter()
    t_free = Trainer(cfg)
    t_free.fit()
    free_wall = time.perf_counter() - t0
    want = jax.device_get(t_free.state)

    # ≥ 4 distinct fault kinds on the training side alone: NaN step, torn
    # checkpoint write, checkpoint-read fault, data-batch I/O fault.  The
    # `at` indices are absolute per-site event counts (they survive
    # restarts), chosen to land mid-run.
    plan = FaultPlan(seed=7, faults=(
        FaultSpec(site="train-step", kind="nan", at=(2,)),
        FaultSpec(site="checkpoint-write", kind="torn", at=(1,)),
        FaultSpec(site="checkpoint-read", kind="io", at=(0,)),
        FaultSpec(site="data-batch", kind="io", at=(27,)),
    ))
    inj = FaultInjector(plan)
    chaos_cfg = cfg.replace(checkpoint_dir=os.path.join(root, "chaos"))
    t1 = time.perf_counter()
    summary = run_with_recovery(
        lambda: Trainer(chaos_cfg, chaos=inj), max_restarts=8,
        backoff_base_s=0.05, jitter_seed=7)
    chaos_wall = time.perf_counter() - t1

    probe = Trainer(chaos_cfg.replace(resume=True, epochs=1))
    got = jax.device_get(probe._ckpt.restore_latest_intact(probe.state))

    return {
        "bit_identical": _leaves_identical(want, got),
        "final_step": int(got.step),
        "restarts": summary["restarts"],
        "faults": inj.summary(),
        "free_wall_s": round(free_wall, 3),
        "chaos_wall_s": round(chaos_wall, 3),
        "recovery_latency_s": round(max(0.0, chaos_wall - free_wall), 3),
    }


def serving_soak() -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import FIFOScheduler, InferenceEngine
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )

    model = get_model("causal_lm", num_classes=16, dim=32, depth=1, heads=2,
                      dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, 16, size=(2 + i % 5,)).astype(np.int32)
               for i in range(12)]
    budgets = [3 + i % 4 for i in range(12)]

    def build(chaos=None, stall=None):
        return InferenceEngine(
            model, params, slots=3, max_len=24, chaos=chaos,
            stall_timeout_s=stall,
            scheduler=FIFOScheduler(max_len=24, buckets=(8,), max_queue=64))

    free = build()
    free_reqs = [free.submit(p, max_new=b) for p, b in zip(prompts, budgets)]
    free.run()
    want = [list(r.generated) for r in free_reqs]

    plan = FaultPlan(seed=13, faults=(
        FaultSpec(site="serving-admit", kind="poison", at=(4,)),
        FaultSpec(site="serving-callback", kind="raise", at=(9,)),
        FaultSpec(site="serving-step", kind="transient", at=(2,)),
    ))
    inj = FaultInjector(plan)
    eng = build(chaos=inj, stall=30.0)
    streamed: list[tuple[int, int]] = []
    reqs = [eng.submit(p, max_new=b,
                       callback=lambda r, t: streamed.append((r.id, t)))
            for p, b in zip(prompts, budgets)]
    t0 = time.perf_counter()
    eng.run()
    wall = time.perf_counter() - t0
    eng.close()

    failed = [i for i, r in enumerate(reqs) if r.status == "failed"]
    fired_request_faults = sum(
        1 for f in inj.fired if f.site in ("serving-admit", "serving-callback"))
    identical = all(
        reqs[i].status == "done" and list(reqs[i].generated) == want[i]
        for i in range(len(reqs)) if i not in failed)
    return {
        "n_requests": len(reqs),
        "n_failed": len(failed),
        "failed_have_errors": all("chaos" in (reqs[i].error or "") for i in failed),
        "outputs_identical": identical and len(failed) == fired_request_faults,
        "faults": inj.summary(),
        "streamed_tokens": len(streamed),
        "wall_s": round(wall, 3),
    }


def overhead_guard(root: str) -> dict:
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import FIFOScheduler, InferenceEngine
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import FaultInjector, FaultPlan
    from distributed_tensorflow_ibm_mnist_tpu.utils.checkpoint import (
        CheckpointManager,
        _digest_step_dir,
    )
    from distributed_tensorflow_ibm_mnist_tpu.core.trainer import Trainer
    from distributed_tensorflow_ibm_mnist_tpu.utils.config import RunConfig

    # --- the structural assert: no injector wired => _chaos is None at
    # every site owner, so each hook is ONE attribute test and there is
    # no injector object to consult on any hot path.
    t = Trainer(RunConfig(
        model="mlp", model_kwargs={"hidden": (16,)}, synthetic=True,
        n_train=128, n_test=64, batch_size=64, epochs=1, quiet=True,
        checkpoint_dir=os.path.join(root, "ov")))
    assert t._chaos is None, "unwired Trainer must hold _chaos=None"
    assert t._ckpt._chaos is None, "unwired CheckpointManager must hold _chaos=None"

    model = get_model("causal_lm", num_classes=16, dim=32, depth=1, heads=2,
                      dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def serve(chaos):
        eng = InferenceEngine(
            model, params, slots=2, max_len=24, chaos=chaos,
            scheduler=FIFOScheduler(max_len=24, buckets=(8,)))
        for i in range(8):
            eng.submit([1 + i % 7, 2, 3], max_new=8)
        t0 = time.perf_counter()
        n = 0
        while eng.has_work:
            eng.step()
            n += 1
        return (time.perf_counter() - t0) / n

    eng_probe = InferenceEngine(
        model, params, slots=2, max_len=24,
        scheduler=FIFOScheduler(max_len=24, buckets=(8,)))
    assert eng_probe._chaos is None, "unwired engine must hold _chaos=None"

    serve(None)  # warm compiles out of the comparison
    per_step_off = serve(None)
    per_step_empty = serve(FaultInjector(FaultPlan()))

    # --- manifest overhead per checkpoint: digest time vs save time
    t.fit()
    t._ckpt.wait()
    step = t._ckpt.latest_step()
    step_dir = t._ckpt._step_path(step)
    size = sum(
        os.path.getsize(os.path.join(dp, f))
        for dp, _d, fs in os.walk(step_dir) for f in fs)
    t0 = time.perf_counter()
    _digest_step_dir(step_dir)
    digest_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    t._ckpt.save(t.state, wait=True)
    save_s = time.perf_counter() - t1

    return {
        "chaos_disabled_is_structural_noop": True,  # the asserts above
        "serve_step_ms_chaos_off": round(per_step_off * 1e3, 4),
        "serve_step_ms_chaos_empty_plan": round(per_step_empty * 1e3, 4),
        "manifest_digest_ms_per_checkpoint": round(digest_s * 1e3, 3),
        "checkpoint_bytes": size,
        "save_with_manifest_ms": round(save_s * 1e3, 3),
        "manifest_frac_of_save": round(digest_s / save_s, 4) if save_s > 0 else None,
    }


def main() -> None:
    root = tempfile.mkdtemp(prefix="chaos_soak_")
    training = training_soak(root)
    serving = serving_soak()
    overhead = overhead_guard(root)
    # distinct fault sites actually hit across both soaks
    kinds = set()
    for blob in (training["faults"], serving["faults"]):
        kinds.update(blob["by_site"].keys())
    record = {
        "metric": "chaos",
        "training": training,
        "serving": serving,
        "overhead": overhead,
        "faults_injected": (
            training["faults"]["faults_injected"]
            + serving["faults"]["faults_injected"]),
        "fault_sites_hit": sorted(kinds),
        "passed": bool(
            training["bit_identical"]
            and serving["outputs_identical"]
            and serving["failed_have_errors"]
            and overhead["chaos_disabled_is_structural_noop"]),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

"""Time-series rollup of a telemetry JSONL file (utils/telemetry).

Reads the append-mode JSONL the :class:`Telemetry` health sampler writes
(one strict-JSON record per sampling interval) and prints

* a **run digest** — samples, time span, sources seen, source errors;
* a **counter table** — per registry counter: first/last value and the
  mean rate over the sampled span (counters are monotone, so
  ``(last - first) / span`` is the honest throughput figure);
* a **gauge table** — per numeric gauge AND per numeric source-vitals
  leaf (``sources.engine0.queue_depth`` flattens to
  ``engine0.queue_depth``): min/mean/max/last over the samples — the
  "what did queue depth / pool occupancy do over the run" view;
* a **histogram table** — per registry histogram: lifetime count and
  p50/p95/p99 from the LAST sample (the sampler re-derives them from the
  full sketch every interval, so the last row is the run's rollup) plus
  the final rolling-window p99;
* the **SLO table** — per engine source: tracked/met/miss counters and
  the met rate, plus the cluster goodput over the sampled span
  (SLO-met requests per second — the ROADMAP item 3 gated metric);
* the **sampling table** (ISSUE 13) — per engine source carrying
  ``n_sampled_requests`` in its vitals: retired sampled-decode requests
  vs total, with the cluster sampled-traffic fraction — the "how much of
  this fleet's traffic is temperature > 0" view.

``--json`` emits the same dict as one machine-readable line.
``--strict`` exits nonzero on any unparseable line, non-dict record, or
non-monotonic ``t`` (an interleaved or truncated file) — without it,
bad lines are counted and skipped.

Usage:
    python scripts/telemetry_report.py TELEMETRY.jsonl [--json] [--strict]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _numeric_leaves(prefix: str, obj, out: dict) -> None:
    """Flatten numeric leaves (bools as 0/1) of a nested dict."""
    if isinstance(obj, bool):
        out[prefix] = 1.0 if obj else 0.0
    elif isinstance(obj, (int, float)) and math.isfinite(obj):
        out[prefix] = float(obj)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            _numeric_leaves(f"{prefix}.{k}" if prefix else str(k), v, out)


def load_records(path: str) -> tuple[list[dict], list[str]]:
    """Parse the JSONL file; returns (records, problems)."""
    records, problems = [], []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                problems.append(f"line {i}: unparseable JSON ({e})")
                continue
            if not isinstance(rec, dict) or "t" not in rec:
                problems.append(f"line {i}: not a telemetry record")
                continue
            records.append(rec)
    for a, b in zip(records, records[1:]):
        if b["t"] < a["t"]:
            problems.append(
                f"non-monotonic t: {a['t']} -> {b['t']} (interleaved "
                "writers or a truncated/concatenated file)")
            break
    return records, problems


def analyze(records: list[dict]) -> dict:
    """Pure rollup of parsed sampler records — also used by tests."""
    if not records:
        return {"n_samples": 0, "span_s": None, "sources": [],
                "source_errors": 0, "counters": {}, "gauges": {},
                "histograms": {}, "slo": None, "sampling": None}
    t0, t1 = records[0]["t"], records[-1]["t"]
    span = t1 - t0 if t1 > t0 else None
    first, last = records[0], records[-1]

    counters = {}
    for name, end in (last.get("counters") or {}).items():
        start = (first.get("counters") or {}).get(name, 0)
        counters[name] = {
            "first": start, "last": end,
            "rate_per_s": (round((end - start) / span, 3)
                           if span else None),
        }

    # gauges + flattened numeric source vitals, min/mean/max/last
    tracks: dict[str, list[float]] = {}
    source_names: set[str] = set()
    source_errors = 0
    for rec in records:
        flat: dict[str, float] = {}
        for k, v in (rec.get("gauges") or {}).items():
            _numeric_leaves(k, v, flat)
        for sname, vitals in (rec.get("sources") or {}).items():
            source_names.add(sname)
            if isinstance(vitals, dict) and "error" in vitals:
                source_errors += 1
                continue
            _numeric_leaves(sname, vitals, flat)
        for k, v in flat.items():
            tracks.setdefault(k, []).append(v)
    gauges = {
        k: {"n": len(vs), "min": min(vs),
            "mean": round(sum(vs) / len(vs), 4), "max": max(vs),
            "last": vs[-1]}
        for k, vs in sorted(tracks.items())
    }

    histograms = {}
    for name, h in (last.get("histograms") or {}).items():
        histograms[name] = {
            "count": h.get("count"),
            "p50": h.get("p50"), "p95": h.get("p95"), "p99": h.get("p99"),
            "window_p99": h.get("window_p99"),
        }

    # SLO table: per source carrying slo_* vitals, plus the cluster sum.
    # Rates/goodput re-derive from the LAST sample's counters over the
    # sampled span (the ServingStats.merge discipline: sums, then ratios).
    slo_rows = []
    tot_tracked = tot_met = tot_miss = 0
    for sname in sorted(source_names):
        vit = (last.get("sources") or {}).get(sname) or {}
        if not isinstance(vit, dict) or "slo_tracked" not in vit:
            continue
        tracked = vit.get("slo_tracked") or 0
        met = vit.get("slo_met") or 0
        miss = vit.get("slo_miss") or 0
        tot_tracked += tracked
        tot_met += met
        tot_miss += miss
        slo_rows.append({
            "source": sname, "tracked": tracked, "met": met, "miss": miss,
            "met_rate": round(met / tracked, 4) if tracked else None,
        })
    slo = None
    if slo_rows:
        slo = {
            "per_source": slo_rows,
            "tracked": tot_tracked, "met": tot_met, "miss": tot_miss,
            "met_rate": (round(tot_met / tot_tracked, 4)
                         if tot_tracked else None),
            "goodput_rps": (round(tot_met / span, 3)
                            if span and tot_tracked else None),
        }

    # sampling table (ISSUE 13): per source carrying n_sampled_requests
    # vitals — sampled vs total retired requests, cluster fraction from
    # the summed counters (the ServingStats.merge discipline)
    samp_rows = []
    tot_sampled = tot_reqs = 0
    for sname in sorted(source_names):
        vit = (last.get("sources") or {}).get(sname) or {}
        if not isinstance(vit, dict) or "n_sampled_requests" not in vit:
            continue
        sampled = vit.get("n_sampled_requests") or 0
        nreq = vit.get("n_requests") or 0
        tot_sampled += sampled
        tot_reqs += nreq
        samp_rows.append({
            "source": sname, "sampled": sampled, "requests": nreq,
            "sampled_frac": round(sampled / nreq, 4) if nreq else None,
        })
    sampling = None
    if samp_rows:
        sampling = {
            "per_source": samp_rows,
            "sampled": tot_sampled, "requests": tot_reqs,
            "sampled_frac": (round(tot_sampled / tot_reqs, 4)
                             if tot_reqs else None),
        }

    return {
        "n_samples": len(records),
        "span_s": round(span, 6) if span else None,
        "sources": sorted(source_names),
        "source_errors": source_errors,
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "slo": slo,
        "sampling": sampling,
    }


def _fmt_table(rows: list[dict], cols: list[str]) -> str:
    if not rows:
        return "  (none)"
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    head = "  " + "  ".join(c.ljust(widths[c]) for c in cols)
    sep = "  " + "  ".join("-" * widths[c] for c in cols)
    body = ["  " + "  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols)
            for r in rows]
    return "\n".join([head, sep] + body)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="telemetry JSONL written by the sampler")
    ap.add_argument("--json", action="store_true", help="emit one JSON line")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on unparseable/non-monotonic records")
    args = ap.parse_args(argv)

    records, problems = load_records(args.jsonl)
    if problems and args.strict:
        for p in problems:
            print(f"PROBLEM: {p}", file=sys.stderr)
        return 1

    report = analyze(records)
    report["problems"] = problems
    if args.json:
        json.dump(report, sys.stdout, allow_nan=False)
        print()
        return 0

    print(f"telemetry: {args.jsonl}  ({report['n_samples']} samples, "
          f"span {report['span_s']}s, sources: "
          f"{', '.join(report['sources']) or '(none)'})")
    if problems:
        print(f"\n!! {len(problems)} problem(s):")
        for p in problems:
            print(f"  - {p}")
    if report["source_errors"]:
        print(f"\n!! {report['source_errors']} source error sample(s)")
    if report["counters"]:
        print("\nCounters:")
        print(_fmt_table(
            [{"counter": k, **v} for k, v in sorted(
                report["counters"].items())],
            ["counter", "first", "last", "rate_per_s"]))
    if report["gauges"]:
        print("\nGauges / source vitals (over samples):")
        print(_fmt_table(
            [{"track": k, **v} for k, v in report["gauges"].items()],
            ["track", "n", "min", "mean", "max", "last"]))
    if report["histograms"]:
        print("\nHistograms (lifetime; window_p99 = rolling):")
        print(_fmt_table(
            [{"histogram": k, **v} for k, v in sorted(
                report["histograms"].items())],
            ["histogram", "count", "p50", "p95", "p99", "window_p99"]))
    if report["slo"]:
        s = report["slo"]
        print("\nSLO accounting:")
        print(_fmt_table(s["per_source"],
                         ["source", "tracked", "met", "miss", "met_rate"]))
        print(f"  cluster: tracked={s['tracked']} met={s['met']} "
              f"miss={s['miss']} met_rate={s['met_rate']} "
              f"goodput_rps={s['goodput_rps']}")
    if report.get("sampling"):
        s = report["sampling"]
        print("\nSampling (temperature > 0) traffic:")
        print(_fmt_table(s["per_source"],
                         ["source", "sampled", "requests", "sampled_frac"]))
        print(f"  cluster: sampled={s['sampled']} requests={s['requests']} "
              f"sampled_frac={s['sampled_frac']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Open-loop SLO/goodput bench for the daemonized tier (ISSUE 15).

The step-pumped benches are CLOSED-loop: the driver waits for the tier,
so offered load can never exceed capacity and overload behaviour is
unmeasurable.  This harness is OPEN-loop — a Poisson arrival process
submits on ITS clock through :class:`ServingDaemon.submit` regardless of
completions (the coordinated-omission-free methodology) — and measures
GOODPUT: requests whose END-TO-END TTFT (daemon submit → first delivered
token, queue wait included) meets their SLO, per second.

Four legs over a 2-replica daemonized tier (tiny causal-LM, CPU-sized):

0. **calibrate** — a closed-loop wave measures service throughput R
   (req/s) and p50 end-to-end TTFT; rates and SLOs below derive from
   these, so the bench self-scales to the box instead of hardcoding
   wall-clock numbers.
1. **control** — unloaded (0.5 R offered, generous SLO = 20x p50 TTFT):
   every request must finish ``done`` AND meet its SLO.  The baseline
   goodput the chaos floor is measured against.
2. **overload** — 4 R offered with a tight SLO (4x p50 TTFT), bounded
   admission + :class:`DeadlineAwarePolicy` shed-at-submit: goodput must
   stay > 0 while conservation stays EXACT (accepted == done + cancelled
   + failed, every rejection raised at submit, nothing lost).
3. **chaos** — control-shaped load while ``daemon-pump`` chaos KILLS one
   of the two pumps mid-wave: failover must keep zero drops (every
   accepted request ``done``), exactly-once streams (delivered stream ==
   final tokens, no replayed failover prefix), and goodput >= 0.25x the
   control leg (one of two replicas died — capacity halves, goodput must
   not collapse).
4. **drain** — every leg ends with ``drain()`` + ``close()``; the chaos
   leg's tracer must end with ``open_spans == 0`` and every live KV pool
   at refcount zero — the graceful-lifecycle gate.

Recorded-trace legs (ISSUE 17, serving/traces.py):

5. **bursty / heavy_tail** — replay recorded arrival traces (on/off
   burst shape; Pareto-length mix) through the same tier and report
   GOODPUT PER CLASS — interactive and batch lines separately, because
   the aggregate hides interactive-starved-by-batch inversions.  Gates
   are structural: exact conservation, exactly-once streams, nothing
   unfinished, and a goodput line actually reported for each class.
6. **autoscale** — the same bursty trace replayed twice at equal
   hardware accounting: a FIXED 2-replica control versus an ELASTIC
   1..2 tier driven by the telemetry autoscaler (warm scale-up through
   replica restart, drain-before-retire scale-down).  Gates:
   goodput-per-chip-second(elastic) >= control's (the whole point of
   breathing capacity), zero drops across every scale-down drain, both
   scale directions actually fired, and the elastic TTFT p99 penalty
   bounded by the measured warm-spawn time plus generous CPU slack.

Usage:  JAX_PLATFORMS=cpu python scripts/bench_slo.py
Emits one JSON line (``"metric": "slo_daemon"``); exits nonzero when any
gate fails.  ``DTM_BENCH_QUICK=1`` shrinks the waves to a tier-1-safe
subprocess smoke.  bench.py runs this as its ``slo_daemon`` block
(``DTM_BENCH_SKIP_SLO_DAEMON=1`` skips).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

QUICK = os.environ.get("DTM_BENCH_QUICK", "") not in ("", "0")

MODEL_KW = dict(num_classes=16, dim=32, depth=1, heads=2,
                dtype=jnp.float32)
ENGINE_KW = dict(slots=2, max_len=16, kv_page_size=4)
BUCKETS = (8,)
MAX_NEW = 4
N_REPLICAS = 2
N_CALIB = 6
N_WAVE = 10 if QUICK else 40
N_TRACE = 12 if QUICK else 30
AUTO_BURST_EVERY_S = 2.5     # autoscaler-leg burst cycle
AUTO_BURST_LEN_S = 0.625     # burst window within each cycle
LEG_TIMEOUT_S = 120.0


def _mk_prompts(seed: int, n: int):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 16, size=(2 + i % 5,)).astype(np.int32)
            for i in range(n)]


def _build(chaos=None, tracer=None, cache_dir=None):
    from distributed_tensorflow_ibm_mnist_tpu.models import get_model
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        FIFOScheduler,
        InferenceEngine,
        Router,
    )

    model = get_model("causal_lm", **MODEL_KW)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine(tid):
        return InferenceEngine(
            model, params,
            scheduler=FIFOScheduler(max_len=ENGINE_KW["max_len"],
                                    buckets=BUCKETS, max_queue=64),
            tracer=tracer, trace_tid=tid, chaos=chaos,
            compile_cache_dir=cache_dir, **ENGINE_KW)

    router = Router(make_engine, N_REPLICAS, chaos=chaos, tracer=tracer)
    router.prewarm()   # no request pays first-use compile as TTFT
    return router


def _open_loop(daemon, prompts, rate_rps: float, seed: int, *,
               ttft_slo_s: float | None):
    """Poisson open-loop generator: submit on the ARRIVAL clock, never
    waiting on the tier.  Returns (accepted, rejected) where accepted is
    a list of (DaemonRequest, stream) and stream accumulates the
    delivered tokens via the daemon callback."""
    from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
        QueueFull,
    )

    rng = np.random.default_rng(seed)
    accepted, rejected = [], 0
    t_next = time.monotonic()
    for p in prompts:
        t_next += rng.exponential(1.0 / rate_rps)
        lag = t_next - time.monotonic()
        if lag > 0:
            time.sleep(lag)
        stream: list[int] = []
        try:
            dr = daemon.submit(
                p, MAX_NEW, ttft_slo_s=ttft_slo_s,
                callback=lambda dr, tok, s=stream: s.append(int(tok)))
        except QueueFull:       # includes SLOUnmeetable shedding
            rejected += 1
            continue
        accepted.append((dr, stream))
    return accepted, rejected


def _leg_result(daemon, accepted, rejected, wall_s: float,
                ttft_slo_s: float | None) -> dict:
    """Per-leg accounting: end-to-end TTFT percentiles, goodput, exact
    conservation, exactly-once streams."""
    done = cancelled = failed = unfinished = 0
    slo_met = 0
    ttfts = []
    exactly_once = True
    for dr, stream in accepted:
        if not dr.done:
            unfinished += 1
            continue
        if dr.status == "done":
            done += 1
            if stream != dr.tokens or (
                    dr.rr is not None and stream != list(dr.rr.generated)):
                exactly_once = False
            if dr.first_token_t is not None:
                ttft = dr.first_token_t - dr.submit_t
                ttfts.append(ttft)
                if ttft_slo_s is None or ttft <= ttft_slo_s:
                    slo_met += 1
        elif dr.status == "cancelled":
            cancelled += 1
        else:
            failed += 1
    cons = daemon.conservation()
    return {
        "offered": len(accepted) + rejected,
        "accepted": len(accepted),
        "rejected": rejected,
        "done": done,
        "cancelled": cancelled,
        "failed": failed,
        "unfinished": unfinished,
        "wall_s": round(wall_s, 3),
        "throughput_rps": round(done / wall_s, 3) if wall_s > 0 else None,
        "goodput_rps": round(slo_met / wall_s, 3) if wall_s > 0 else None,
        "slo_met": slo_met,
        "ttft_slo_s": (round(ttft_slo_s, 4)
                       if ttft_slo_s is not None else None),
        "ttft_p50_s": (round(float(np.percentile(ttfts, 50)), 4)
                       if ttfts else None),
        "ttft_p99_s": (round(float(np.percentile(ttfts, 99)), 4)
                       if ttfts else None),
        "exactly_once_streams": exactly_once,
        "conserved": cons["conserved"],
        "counters": {k: cons[k] for k in (
            "submitted", "rejected", "done", "cancelled", "failed",
            "outstanding", "pump_faults")},
    }


def _pools_zero(router) -> bool:
    """Refcount-zero pools: after a clean drain no REQUEST may hold a
    page — every radix node's refcount is 0 and every page still
    allocated is trie-owned (the radix cache retains zero-ref prefix
    pages for reuse by design; those are reclaimable, not leaked)."""
    for rep in router.replicas:
        if not rep.alive or rep.engine._pool is None:
            continue
        eng = rep.engine
        if eng._radix is not None:
            stack = [eng._radix.root]
            while stack:
                node = stack.pop()
                if node.ref != 0:
                    return False
                stack.extend(node.children.values())
            if eng._pool.allocated != eng._radix.n_blocks:
                return False
        elif eng._pool.allocated != 0:
            return False
    return True


def _run_leg(*, seed: int, rate_rps: float, ttft_slo_s: float | None,
             n: int, policy=None, max_queue: int = 256,
             chaos=None, tracer=None):
    from distributed_tensorflow_ibm_mnist_tpu.serving import ServingDaemon

    router = _build(chaos=chaos, tracer=tracer)
    daemon = ServingDaemon(router, policy=policy, max_queue=max_queue,
                           liveness_timeout_s=30.0)
    daemon.start()
    t0 = time.monotonic()
    accepted, rejected = _open_loop(daemon, _mk_prompts(seed, n), rate_rps,
                                    seed, ttft_slo_s=ttft_slo_s)
    deadline = time.monotonic() + LEG_TIMEOUT_S
    for dr, _ in accepted:
        dr.wait(timeout=max(0.0, deadline - time.monotonic()))
    wall_s = time.monotonic() - t0
    drained = daemon.drain(timeout=30.0)
    leg = _leg_result(daemon, accepted, rejected, wall_s, ttft_slo_s)
    leg["drained_clean"] = drained
    leg["pools_zero"] = _pools_zero(router)
    leg["failovers"] = router.failovers
    daemon.close()
    return leg


def _mk_traces(rate: float, p50: float):
    """The two recorded shapes, rates in units of the calibrated service
    rate, SLOs stamped per class at replay time (generous for batch,
    tighter for interactive — both meetable at these offered loads)."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        bursty_trace,
        heavy_tail_trace,
        with_slos,
    )

    cycle_s = 8.0 / rate
    bursty = bursty_trace(
        N_TRACE, 0.25 * rate, 3.0 * rate, seed=31,
        burst_every_s=cycle_s, burst_len_s=0.25 * cycle_s,
        prompt_len=(2, 6), max_new=(2, 4))
    heavy = heavy_tail_trace(N_TRACE, 0.75 * rate, seed=32, alpha=1.5,
                             prompt_len=(2, 8), max_new=(2, 6))
    stamp = dict(interactive_ttft_slo_s=10.0 * p50,
                 batch_ttft_slo_s=40.0 * p50)
    return {"bursty": with_slos(bursty, **stamp),
            "heavy_tail": with_slos(heavy, **stamp)}


def _run_trace_leg(trace) -> dict:
    """Replay one recorded trace through a fixed 2-replica tier and
    report per-class goodput."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        ServingDaemon,
        replay_trace,
    )

    router = _build()
    daemon = ServingDaemon(router, max_queue=256,
                           liveness_timeout_s=30.0).start()
    report = replay_trace(daemon, trace, vocab=16, seed=41,
                          timeout_s=LEG_TIMEOUT_S)
    report["trace"] = trace.name
    report["n_events"] = len(trace)
    report["drained_clean"] = daemon.drain(timeout=30.0)
    report["pools_zero"] = _pools_zero(router)
    report["conserved"] = daemon.conservation()["conserved"]
    daemon.close()
    return report


def _autoscaler_leg(rate: float, p50: float) -> dict:
    """A LONG bursty trace (seconds of quiet between bursts — elasticity
    needs wall time to amortize) against a FIXED 2-replica control and
    an ELASTIC 1..2 tier (autoscaler-driven), compared at goodput per
    chip-second.  Both tiers share one persistent compile cache, so the
    elastic scale-up is genuinely WARM: the restarted replica's programs
    come from cache, and its bring-up cost is the measured ``spawn_s``
    the TTFT-penalty gate is bounded by."""
    import tempfile
    import time as _time

    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        Autoscaler,
        ServingDaemon,
        bursty_trace,
        replay_trace,
        with_slos,
    )

    # ~0.4x capacity on average, ~1.2x during the 0.625 s bursts every
    # 2.5 s: the quiet phases idle a fixed tier and the bursts overrun a
    # single replica — exactly the shape capacity should breathe with
    n_events = 60 if QUICK else 150
    trace = with_slos(
        bursty_trace(n_events, 0.15 * rate, 1.2 * rate, seed=33,
                     burst_every_s=AUTO_BURST_EVERY_S,
                     burst_len_s=AUTO_BURST_LEN_S,
                     prompt_len=(2, 6), max_new=(2, 4)),
        interactive_ttft_slo_s=20.0 * p50, batch_ttft_slo_s=40.0 * p50)
    cache_dir = tempfile.mkdtemp(prefix="dtm_autoscale_xc_")

    def _drive(elastic: bool) -> dict:
        router = _build(cache_dir=cache_dir)
        daemon = ServingDaemon(router, max_queue=256,
                               liveness_timeout_s=30.0).start()
        asc = None
        if elastic:
            # start at 1 replica: retire #1 (drains instantly — idle) so
            # scale-up exercises the WARM restart path
            assert daemon.retire_replica(1)
            deadline = _time.monotonic() + 30.0
            while _time.monotonic() < deadline and router._retiring:
                _time.sleep(0.01)
            asc = Autoscaler(daemon, min_replicas=1, max_replicas=2,
                             up_backlog_per_slot=1.0, down_occupancy=0.45,
                             hysteresis_up=1, hysteresis_down=4,
                             interval_s=0.03).start()
        t0 = _time.monotonic()
        report = replay_trace(daemon, trace, vocab=16, seed=42,
                              timeout_s=LEG_TIMEOUT_S)
        wall = _time.monotonic() - t0
        if asc is not None:
            chip_s = asc.chip_seconds()
            asc.stop()
            report["autoscaler"] = asc.summary()
            report["scale_events"] = [
                {k: e[k] for k in ("action", "replica", "spawn_s", "warm")}
                for e in asc.events]
        else:
            chip_s = 2.0 * wall
        report["wall_s"] = round(wall, 3)
        report["chip_seconds"] = round(chip_s, 3)
        tot = report["total"]
        report["goodput_per_chip_s"] = (
            round(tot["slo_met"] / chip_s, 4) if chip_s > 0 else None)
        report["drained_clean"] = daemon.drain(timeout=30.0)
        report["pools_zero"] = _pools_zero(router)
        report["conserved"] = daemon.conservation()["conserved"]
        daemon.close()
        return report

    fixed = _drive(elastic=False)
    elastic = _drive(elastic=True)
    for leg in (fixed, elastic):
        leg["trace"] = trace.name
        leg["n_events"] = n_events
    ups = sum(1 for e in elastic.get("scale_events", ())
              if e["action"] == "up")
    downs = sum(1 for e in elastic.get("scale_events", ())
                if e["action"] == "down")
    max_spawn = max((e["spawn_s"] for e in elastic.get("scale_events", ())
                     if e["spawn_s"] is not None), default=0.0)
    return {"fixed": fixed, "elastic": elastic, "scale_ups": ups,
            "scale_downs": downs, "max_spawn_s": round(max_spawn, 6)}


def _calibrate() -> tuple[float, float]:
    """Closed-loop service rate R (req/s) and p50 end-to-end TTFT of an
    unloaded tier — the units every leg's rate and SLO derive from."""
    from distributed_tensorflow_ibm_mnist_tpu.serving import ServingDaemon

    router = _build()
    daemon = ServingDaemon(router, max_queue=256)
    daemon.start()
    t0 = time.monotonic()
    drs = [daemon.submit(p, MAX_NEW) for p in _mk_prompts(3, N_CALIB)]
    for dr in drs:
        dr.wait(timeout=LEG_TIMEOUT_S)
    wall = time.monotonic() - t0
    ttfts = [dr.first_token_t - dr.submit_t for dr in drs
             if dr.first_token_t is not None]
    assert all(dr.status == "done" for dr in drs), "calibration wave failed"
    daemon.drain(timeout=30.0)
    daemon.close()
    rate = N_CALIB / wall
    p50 = float(np.percentile(ttfts, 50))
    return rate, max(p50, 1e-4)


def main() -> None:
    from distributed_tensorflow_ibm_mnist_tpu.serving import (
        DeadlineAwarePolicy,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
        FaultInjector,
        FaultPlan,
        FaultSpec,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import Tracer

    rate, p50_ttft = _calibrate()

    control = _run_leg(seed=11, rate_rps=0.5 * rate,
                       ttft_slo_s=20.0 * p50_ttft, n=N_WAVE)

    overload = _run_leg(
        seed=12, rate_rps=4.0 * rate, ttft_slo_s=4.0 * p50_ttft,
        n=N_WAVE, max_queue=max(4, N_WAVE // 4),
        policy=DeadlineAwarePolicy(
            concurrency=N_REPLICAS * ENGINE_KW["slots"]))

    # chaos leg: the FIRST pump to find work dies mid-wave (kind="raise"
    # at daemon-pump event 0); its collateral fails over to the survivor
    inj = FaultInjector(FaultPlan(seed=5, faults=(
        FaultSpec(site="daemon-pump", kind="raise", at=(0,)),)))
    tracer = Tracer()
    chaos = _run_leg(seed=13, rate_rps=0.5 * rate,
                     ttft_slo_s=20.0 * p50_ttft, n=N_WAVE,
                     chaos=inj, tracer=tracer)
    chaos["open_spans"] = tracer.open_spans
    chaos["faults"] = inj.summary()

    # recorded-trace legs (ISSUE 17): per-class goodput + elastic capacity
    traces = _mk_traces(rate, p50_ttft)
    trace_legs = {name: _run_trace_leg(tr) for name, tr in traces.items()}
    autoscale = _autoscaler_leg(rate, p50_ttft)

    def _classes_reported(leg):
        return all(leg["per_class"][c]["goodput_rps"] is not None
                   and leg["per_class"][c]["offered"] > 0
                   for c in ("interactive", "batch"))

    def _nothing_lost(leg):
        tot = leg["total"]
        return (leg["conserved"] and tot["unfinished"] == 0
                and tot["failed"] == 0 and tot["exactly_once"])

    el, fx = autoscale["elastic"], autoscale["fixed"]
    # the elastic TTFT tail = detection + warm spawn + draining the one
    # burst's overflow that queued during that reaction window.  Overflow
    # drains within about one burst length once capacity doubles, so the
    # bound is spawn + burst_len + CPU-noise slack — structural, not a
    # tuned constant
    ttft_bound = (autoscale["max_spawn_s"] + AUTO_BURST_LEN_S
                  + max(0.5, 10.0 * p50_ttft))
    el_p99 = max(el["per_class"][c]["ttft_p99_s"] or 0.0
                 for c in ("interactive", "batch"))
    fx_p99 = max(fx["per_class"][c]["ttft_p99_s"] or 0.0
                 for c in ("interactive", "batch"))

    floor = 0.25 * (control["goodput_rps"] or 0.0)
    gates = {
        "control_all_done": control["done"] == control["accepted"]
        and control["unfinished"] == 0,
        "control_meets_all_slos": control["slo_met"] == control["done"]
        and control["done"] > 0,
        "control_conserved": control["conserved"],
        "overload_goodput_positive": (overload["goodput_rps"] or 0) > 0,
        "overload_conserved": overload["conserved"]
        and overload["unfinished"] == 0,
        "chaos_failover_happened": chaos["failovers"] >= 1
        and chaos["counters"]["pump_faults"] >= 1,
        "chaos_zero_drops": chaos["done"] == chaos["accepted"]
        and chaos["unfinished"] == 0 and chaos["rejected"] == 0,
        "chaos_exactly_once": chaos["exactly_once_streams"],
        "chaos_goodput_floor": (chaos["goodput_rps"] or 0) >= floor,
        "drained_clean": all(l["drained_clean"] and l["pools_zero"]
                             for l in (control, overload, chaos)),
        "no_open_spans": chaos["open_spans"] == 0,
        "traces_per_class_goodput": all(
            _classes_reported(leg) for leg in trace_legs.values()),
        "traces_nothing_lost": all(
            _nothing_lost(leg) and leg["drained_clean"]
            and leg["pools_zero"] for leg in trace_legs.values()),
        # elastic >= fixed at equal hardware accounting: the elastic
        # tier runs fewer chip-seconds through the quiet phases, so its
        # goodput per chip-second must not lose to always-on capacity
        "autoscale_goodput_per_chip": (el["goodput_per_chip_s"] or 0.0)
        >= 0.95 * (fx["goodput_per_chip_s"] or 0.0),
        "autoscale_zero_drops": _nothing_lost(el) and _nothing_lost(fx)
        and el["total"]["cancelled"] == 0
        and el["drained_clean"] and el["pools_zero"]
        and fx["drained_clean"] and fx["pools_zero"],
        # both directions must actually fire on the bursty shape (the
        # quick smoke's wave is too short to guarantee a full cycle)
        "autoscale_both_directions": QUICK or (
            autoscale["scale_ups"] >= 1 and autoscale["scale_downs"] >= 1),
        # scale-up cost on the wire: elastic p99 TTFT may exceed fixed by
        # at most the measured warm-spawn time + generous CPU-noise slack
        "autoscale_ttft_bounded": QUICK
        or el_p99 <= fx_p99 + ttft_bound,
    }
    record = {
        "metric": "slo_daemon",
        "quick": QUICK,
        "n_replicas": N_REPLICAS,
        "calibration": {"service_rps": round(rate, 3),
                        "ttft_p50_s": round(p50_ttft, 4)},
        "goodput_floor_rps": round(floor, 3),
        "control": control,
        "overload": overload,
        "chaos": chaos,
        "traces": trace_legs,
        "autoscale": {**autoscale,
                      "ttft_penalty_bound_s": round(ttft_bound, 4),
                      "elastic_p99_s": round(el_p99, 4),
                      "fixed_p99_s": round(fx_p99, 4)},
        "gates": gates,
        "passed": all(gates.values()),
    }
    print(json.dumps(record), flush=True)
    if not record["passed"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

"""TPU-native distributed training framework.

A ground-up JAX/XLA/Pallas reimplementation of the capability surface of
``cybera/distributed_tensorflow_ibm_mnist`` (a TF1 parameter-server MNIST
trainer for IBM Cloud GPU workers — see SURVEY.md; the reference mount was
empty at survey time, so citations point at BASELINE.json / SURVEY.md
reconstruction tags instead of file:line).

Reference capability -> TPU-native design mapping (SURVEY.md §2.2, §2.4):

* TF1 graph executor + feed_dict/session.run hot loop
  -> pure jitted train step; the whole forward/backward/update lowers to a
     single XLA HLO module; data lives on-device, batches are gathered
     inside a ``lax.scan`` epoch so zero host<->device traffic per step.
* tf.train.Server / ClusterSpec chief-ps-worker topology + NCCL all-reduce
  -> SPMD over a ``jax.sharding.Mesh``; gradients are ``psum``-ed over the
     ``data`` mesh axis inside the compiled step (XLA collectives over ICI).
* IBM-Cloud Kubernetes submit scripts
  -> ``launch/`` TPU-VM process bootstrap + config presets + CLI.
* MonitoredTrainingSession checkpoint hook
  -> ``utils/checkpoint.py`` (orbax), full train-state round-trip.
"""

__version__ = "0.1.0"

"""ResNets for the scale-out configs (BASELINE.md configs 4-5).

ResNet-20 (CIFAR-style basic blocks, widths 16/32/64) for Fashion-MNIST and
ResNet-50 (bottleneck blocks) for CIFAR-10.  BatchNorm statistics live in the
``batch_stats`` collection; under data parallelism pass ``axis_name`` so the
batch moments are computed over the *global* batch via a cross-replica mean
(the XLA-collective analog of TF's cross-replica BN).  Compute in bfloat16,
params and BN stats in float32.
"""

from __future__ import annotations

from collections.abc import Sequence
from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class BasicBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16
    norm: Any = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), self.strides, padding="SAME", name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), padding="SAME", name="conv2")(y)
        y = self.norm(name="bn2")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), self.strides, name="proj")(residual)
            residual = self.norm(name="bn_proj")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    filters: int
    strides: tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16
    norm: Any = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = self.norm(name="bn1")(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), self.strides, padding="SAME", name="conv2")(y)
        y = self.norm(name="bn2")(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = self.norm(name="bn3")(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), self.strides, name="proj")(residual)
            residual = self.norm(name="bn_proj")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Generic ResNet; ``low_res=True`` uses the CIFAR stem (3x3, no maxpool)."""

    stage_sizes: Sequence[int]
    block: Any = BasicBlock
    num_classes: int = 10
    width: int = 16
    low_res: bool = True
    dtype: Any = jnp.bfloat16
    bn_momentum: float = 0.9
    axis_name: str | None = None  # set under shard_map for cross-replica BN
    block_remat: bool = False  # jax.checkpoint each residual block: backward
    #   recomputes within-block activations, peak memory drops to O(blocks)
    #   boundaries.  (Whole-forward remat does NOT lower the peak — the
    #   recompute replays the same live set; block granularity is what pays:
    #   measured on v5e, batch-4096 ResNet-50 OOMs at 19.7G without this.)

    @nn.compact
    def __call__(self, x, train: bool = False):
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            dtype=self.dtype,
            axis_name=self.axis_name if train else None,
        )
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = x.astype(self.dtype)
        if self.low_res:
            x = conv(self.width, (3, 3), padding="SAME", name="stem")(x)
        else:
            x = conv(self.width, (7, 7), (2, 2), padding="SAME", name="stem")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        if self.low_res:
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
        block_cls = nn.remat(self.block) if self.block_remat else self.block
        for i, n_blocks in enumerate(self.stage_sizes):
            filters = self.width * (2**i)
            for j in range(n_blocks):
                strides = (2, 2) if (i > 0 and j == 0) else (1, 1)
                x = block_cls(
                    filters, strides=strides, dtype=self.dtype, norm=norm,
                    name=f"stage{i}_block{j}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)


def ResNet20(num_classes: int = 10, dtype: Any = jnp.bfloat16, axis_name: str | None = None, block_remat: bool = False, **kw):
    """CIFAR-style ResNet-20: 3 stages x 3 basic blocks, widths 16/32/64."""
    return ResNet(
        stage_sizes=(3, 3, 3), block=BasicBlock, num_classes=num_classes,
        width=16, low_res=True, dtype=dtype, axis_name=axis_name,
        block_remat=block_remat, **kw,
    )


def ResNet50(num_classes: int = 10, dtype: Any = jnp.bfloat16, axis_name: str | None = None, low_res: bool = True, block_remat: bool = False, **kw):
    """ResNet-50: bottleneck [3, 4, 6, 3], width 64 (x4 expansion)."""
    return ResNet(
        stage_sizes=(3, 4, 6, 3), block=BottleneckBlock, num_classes=num_classes,
        width=64, low_res=low_res, dtype=dtype, axis_name=axis_name,
        block_remat=block_remat, **kw,
    )

"""Decoder-only causal language model — the zoo's text/sequence family.

The reference's model layer was a single image CNN (SURVEY.md §1 L3); this
is the rebuild's language-model counterpart, promoted from the hand-rolled
examples/06 net so the long-context machinery is config-driven end to end:

    RunConfig(model="causal_lm", dataset="retrieval", causal=True,
              sp=4, sp_impl="ring", model_kwargs={"attn": "flash"})

Inputs are int token arrays (B, S); logits are per-position (B, S, vocab)
and the framework's loss/accuracy/eval paths handle the extra position axis
unchanged (per-token cross-entropy and accuracy).  Attention is causal by
default; a trainer-supplied ``attn_fn`` (the sp ring/Ulysses island) takes
priority and the Trainer DERIVES its causal flag from this family default
(``Trainer.causal``), so ``RunConfig(model="causal_lm", sp=4)`` is causal
without restating ``causal=True`` — pass ``model_kwargs={"causal": False}``
to explicitly train bidirectionally.

Positions are rotary by default (``pos="rope"``, models/transformer.py
``apply_rope``): relative-position attention with no per-position
parameters, so checkpoints don't bake in a maximum length and the model
runs on sequences longer than it trained on — the right default for the
long-context story the ring buys (VERDICT.md r2 item 5).  ``pos="learned"``
keeps the (1, S, dim) table for ablation.

Reuses :class:`~.transformer.TransformerBlock`, so TP (qkv/proj Megatron
specs), MoE blocks, and block remat all apply as they do to the ViT.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_ibm_mnist_tpu.models.transformer import TransformerBlock
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import vanilla_attention


class CausalLM(nn.Module):
    """Embed -> pre-norm causal blocks -> per-position vocab head."""

    num_classes: int = 64  # vocabulary size (named for zoo consistency)
    dim: int = 128
    depth: int = 2
    heads: int = 4
    heads_kv: int = 0  # 0 = heads; <heads = grouped-query attention (GQA):
    #   smaller kv projections and a heads_kv-sized decode cache
    window: int = 0  # causal sliding-window attention width (0 = full
    #   context); tile-skipped in the flash kernel so cost is S*window
    mlp_ratio: int = 4
    dropout: float = 0.0
    attn_fn: Callable | None = None  # sp island (brings its OWN causal flag)
    attn: str = "vanilla"  # 'vanilla' | 'flash' for the local kernels
    causal: bool = True
    pos: str = "rope"  # 'rope' (rotary, default: length-extrapolating, no
    #   per-position params) | 'learned' (the (1, S, dim) table — bakes max
    #   length into the checkpoint; kept for ablation) | 'none'
    sow_kv: bool = False  # sow per-block K/V on the normal forward (the
    #   flash-prefill capture; core/generate.py clones the model with this)
    kv_cache_dtype: str = "native"  # "int8": quantized decode cache with
    #   per-(position, head) scales — halves the decode's dominant HBM
    #   stream (models/transformer.quantize_kv_int8); training is untouched
    page_size: int = 0  # >0: paged decode cache — blocks read/write K/V
    #   through a shared page pool + block table (serving/kv_pool.py)
    #   instead of dense (B, max_len) rows; serving engine state, training
    #   and prefill are untouched (see TransformerBlock.page_size)
    tie_embeddings: bool = False  # share the token embedding with the
    #   output head (logits = x @ embed^T): V*dim fewer params, the
    #   standard small-LM regularizer.  The Megatron rule's feature-dim
    #   embedding sharding doubles as the head's row-parallel layout.
    quant: str = "none"  # "int8": WEIGHT-only int8 matmuls (ISSUE 12) —
    #   block projections and the untied logits head store int8 kernels +
    #   per-output-channel f32 scales with dequant fused into the matmul
    #   (models/quant.py).  Params must pass quantize_params_int8 (the
    #   serving engine's upload/swap seams do).  Embedding stays full
    #   precision (a gather, and the tied head shares it); orthogonal to
    #   kv_cache_dtype (weights vs decode cache).
    moe_every: int = 0
    n_experts: int = 8
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1  # experts per token: 1 = Switch, >1 = GShard top-k
    moe_z_weight: float = 0.0  # router z-loss coefficient (ST-MoE; 0 = off)
    moe_fn: Callable | None = None
    pp_stages: int = 0  # >0: stack blocks for the GPipe island (see the
    #                     ViT's StackedBlocks; params shardable over 'pipe')
    pipeline_fn: Callable | None = None  # (stage_fn, stacked_params, x) -> y
    block_remat: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, train: bool = False, decode: bool = False,
                 max_len: int = 0, ragged: bool = False):
        b, s = tokens.shape
        if self.window < 0:
            raise ValueError(f"window must be >= 0, got {self.window}")
        if self.quant not in ("none", "int8"):
            raise ValueError(
                f"quant must be 'none' or 'int8', got {self.quant!r}")
        if self.quant != "none" and self.pp_stages > 0:
            raise ValueError(
                "quant composes with the plain block stack only: pp_stages "
                "stacks params (n_stages, per_stage, ...) for the training "
                "pipeline, which the int8 kernel/scale layout does not "
                "cover — decode already unstacks pp weights (core/trainer."
                "_decode_param_tree), so quantize the unstacked tree"
            )
        if decode and self.pos == "learned":
            raise ValueError(
                "decode mode needs position-free params: pos='learned' bakes "
                "the trained length into a (1, S, dim) table that cannot "
                "address incremental positions — use pos='rope' (the default)"
            )
        if decode and self.pp_stages > 0:
            raise ValueError(
                "decode mode runs the plain block stack, not stage-stacked "
                "params — Trainer.generate unstacks pp-trained weights into "
                "this layout for you (core/trainer._decode_param_tree)"
            )
        embed = nn.Embed(self.num_classes, self.dim, dtype=self.dtype,
                         name="embed")
        x = embed(tokens.astype(jnp.int32))
        if self.pos == "learned":
            pos = self.param("pos_embed", nn.initializers.normal(0.02), (1, s, self.dim))
            x = x + pos.astype(self.dtype)
        elif self.pos not in ("rope", "none"):
            raise ValueError(
                f"unknown pos {self.pos!r}; use 'rope', 'learned' or 'none'"
            )
        rope = self.pos == "rope"  # applied to q/k inside each block
        attn_fn = self.attn_fn
        if attn_fn is None:
            if self.attn == "flash":
                from distributed_tensorflow_ibm_mnist_tpu.ops.flash_attention import (
                    flash_attention,
                )

                attn_fn = partial(flash_attention, causal=self.causal,
                                  window=self.window)
            else:
                attn_fn = partial(vanilla_attention, causal=self.causal,
                                  window=self.window)
        if self.pp_stages > 0:
            from distributed_tensorflow_ibm_mnist_tpu.models.transformer import (
                StackedBlocks,
            )

            if self.depth % self.pp_stages:
                raise ValueError(
                    f"depth {self.depth} not divisible by pp_stages {self.pp_stages}"
                )
            if self.dropout > 0.0 or self.moe_every > 0:
                raise ValueError(
                    "pipeline stages need identical per-block programs: "
                    "dropout and MoE blocks don't compose with pp_stages"
                )
            x = StackedBlocks(
                dim=self.dim, heads=self.heads, heads_kv=self.heads_kv,
                n_stages=self.pp_stages,
                per_stage=self.depth // self.pp_stages, mlp_ratio=self.mlp_ratio,
                attn_fn=attn_fn, pipeline_fn=self.pipeline_fn,
                block_remat=self.block_remat, rope=rope, dtype=self.dtype,
                name="pipe_blocks",
            )(x, train=train)
            x = nn.LayerNorm(dtype=self.dtype, name="norm_out")(x)
            if self.tie_embeddings:
                x = embed.attend(x)  # logits = x @ embed^T, weights shared
            else:
                x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
            return x.astype(jnp.float32)
        block_cls = (
            nn.remat(TransformerBlock, static_argnums=(2,))
            if self.block_remat and not decode  # remat is a backward-pass
            else TransformerBlock               # lever; decode has no bwd
        )
        # decode/max_len ride as kwargs only when decoding so the training
        # trace (incl. the remat-wrapped class, whose static_argnums cover
        # positional train only) is byte-identical to previous rounds
        extra = (
            {"decode": True, "max_len": max_len, "ragged": ragged}
            if decode else {}
        )
        for i in range(self.depth):
            x = block_cls(
                dim=self.dim, heads=self.heads, heads_kv=self.heads_kv,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout, attn_fn=attn_fn,
                use_moe=self.moe_every > 0 and (i + 1) % self.moe_every == 0,
                n_experts=self.n_experts, moe_capacity_factor=self.moe_capacity_factor,
                moe_top_k=self.moe_top_k, moe_z_weight=self.moe_z_weight,
                moe_fn=self.moe_fn, rope=rope, sow_kv=self.sow_kv,
                window=self.window, kv_cache_dtype=self.kv_cache_dtype,
                page_size=self.page_size, quant=self.quant,
                dtype=self.dtype, name=f"block_{i}",
            )(x, train, **extra)
        x = nn.LayerNorm(dtype=self.dtype, name="norm_out")(x)
        if self.tie_embeddings:
            # the tied head reads the (full-precision) embedding table —
            # quantizing it would also quantize the token lookup, so a
            # quant model with tied embeddings keeps its head at full
            # precision (documented in docs/PERFORMANCE.md)
            x = embed.attend(x)  # logits = x @ embed^T, weights shared
        elif self.quant == "int8":
            from distributed_tensorflow_ibm_mnist_tpu.models.quant import Int8Dense

            x = Int8Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        else:
            x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)

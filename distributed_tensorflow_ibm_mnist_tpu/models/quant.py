"""Weight-only int8 quantization for the serving decode path (ISSUE 12).

Decode is weight-bandwidth-bound: every step streams the full parameter
set from HBM and does ~2 FLOPs per byte with it (scripts/bench_decode.py's
roofline).  Storing the matmul weights as int8 with per-OUTPUT-CHANNEL
symmetric f32 scales cuts that dominant stream ~4x vs f32 masters (~2x vs
the bf16 compute-dtype copy) at a bounded accuracy cost — the same move
the int8 KV cache (models/transformer.py::quantize_kv_int8) made for the
cache stream in round 5, now applied to the weights.

Scheme
------
For a 2-D kernel ``W`` (in, out): ``scale[o] = max_i |W[i, o]| / 127``,
``W_q = round(W / scale)`` stored int8, ``scale`` kept f32.  Per-output-
channel (not per-tensor) so one outlier column cannot flatten every other
column's resolution, and — the tensor-parallel reason — so the scale
vector partitions EXACTLY like the kernel's output features:

* column-parallel kernels (``qkv``/``q_proj``/``kv_proj``/even
  ``dense_i``: ``P(None, tp)``) shard their scales ``P(tp)`` — each chip
  dequantizes its own output slice;
* row-parallel kernels (``proj``/odd ``dense_i``/``logits``:
  ``P(tp, None)``) keep output features whole per chip, so their scales
  REPLICATE — and because the scale is uniform over the contraction axis
  it distributes over the psum (``sum_chips(partial) * scale`` ==
  ``sum_chips(partial * scale)``), which is what makes quant compose with
  the Megatron splits without touching the reduction structure.

The dequant never materializes a full-precision weight copy:
:class:`Int8Dense` feeds the int8 kernel into the contraction as the
compute dtype (int8 -> bf16 is EXACT — every value in [-127, 127] is
representable), accumulates in f32 (``preferred_element_type``), and
applies the scale post-contraction — one multiply per output element, 1/d_in
the cost of scaling the weight itself.  The HBM stream stays int8-sized.

What is NOT quantized: embeddings (a gather, not a matmul — and the tied
head ``embed.attend`` shares the same table), norm scales/biases, biases,
and MoE expert weights (3-D einsum leaves routed by ``MoEBlock``; a
follow-on).  :func:`quantize_params_int8` passes all of these through
untouched, so a tied-embedding or MoE model quantizes its blocks and
keeps the rest at full precision — documented, never silent: the leaf
report is in the returned tree itself (int8 kernels + ``scale`` siblings
exactly where the quant model expects them).
"""

from __future__ import annotations

import re
from collections.abc import Mapping
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

# module names whose 2-D `kernel` the serving decode path quantizes —
# exactly the names megatron_rule (parallel/tensor_parallel.py) shards,
# so the inserted `scale` siblings land where the sharding rule expects
_QUANT_MODULE = re.compile(r"qkv|q_proj|kv_proj|proj|dense_\d+|logits|fc\d*")


def quantize_kernel_int8(w):
    """(in, out) kernel -> (int8 kernel, (out,) f32 scale), symmetric
    per-output-channel: ``scale = max|W[:, o]| / 127`` (floored so an
    all-zero column quantizes to zeros instead of NaN)."""
    wf = jnp.asarray(w).astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=0)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(wf / scale).astype(jnp.int8)
    return q, scale


def quantize_params_int8(params):
    """Host/device param tree -> the quant model's tree: every 2-D
    ``kernel`` under a quantizable module name is replaced by an int8
    kernel plus a ``scale`` sibling; every other leaf passes through
    unchanged (embeddings, norms, biases, MoE experts).

    Idempotent: kernels already stored int8 (with their ``scale``
    sibling present) pass through, so the engine can call this
    unconditionally at upload AND at every ``swap_params`` — a caller
    handing an already-quantized tree is a no-op, not a double-round.
    """

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if isinstance(sub, Mapping):
                kern = sub.get("kernel")
                if (_QUANT_MODULE.fullmatch(name)
                        and getattr(kern, "ndim", 0) == 2):
                    if kern.dtype == jnp.int8:
                        out[name] = dict(sub)  # already quantized
                        continue
                    q, s = quantize_kernel_int8(kern)
                    new = {k: v for k, v in sub.items() if k != "scale"}
                    new["kernel"] = q
                    new["scale"] = s
                    out[name] = new
                else:
                    out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    return walk(params)


def is_quantized(params) -> bool:
    """True when the tree holds at least one int8 kernel with its
    ``scale`` sibling — the quant model's storage layout."""
    found = False

    def walk(tree):
        nonlocal found
        for name, sub in tree.items():
            if isinstance(sub, Mapping):
                kern = sub.get("kernel")
                if (getattr(kern, "dtype", None) == jnp.int8
                        and "scale" in sub):
                    found = True
                else:
                    walk(sub)

    walk(params)
    return found


class Int8Dense(nn.Module):
    """Drop-in ``nn.Dense`` with int8-stored weights and fused dequant.

    Declares ``kernel`` (int8, (in, out)), ``scale`` (f32, (out,)), and
    ``bias`` (f32, (out,)) under the SAME module name its full-precision
    sibling would use, so :func:`quantize_params_int8` output binds by
    name and ``megatron_rule`` path-matching applies unchanged.  The
    contraction runs int8-as-compute-dtype x activation with f32
    accumulation; the per-channel scale (and the bias, still f32) apply
    post-contraction in f32, then the result drops back to the compute
    dtype — strictly MORE accurate than ``nn.Dense``'s bias-add in bf16.

    Init gives zero kernels / unit scales: structurally valid (shape and
    dtype probes, ``model.init`` in tests), numerically meaningless — real
    weights always arrive via :func:`quantize_params_int8` at the
    engine's upload/swap seams.
    """

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        d_in = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.zeros, (d_in, self.features), jnp.int8)
        scale = self.param(
            "scale", nn.initializers.ones, (self.features,), jnp.float32)
        bias = self.param(
            "bias", nn.initializers.zeros, (self.features,), jnp.float32)
        x = x.astype(self.dtype)
        # int8 -> compute dtype inside the contraction: XLA fuses the
        # convert into the matmul read, so HBM traffic stays int8-sized
        y = jnp.einsum(
            "...i,io->...o", x, kernel.astype(self.dtype),
            preferred_element_type=jnp.float32)
        y = y * scale + bias
        return y.astype(self.dtype)


def weight_stream_bytes(params) -> int:
    """Total parameter bytes one decode step streams from HBM — the
    honest bytes-moved figure the bench quant leg reports (int8 kernels
    count 1 byte/element, their f32 scales 4, everything else its own
    itemsize)."""
    return sum(
        l.size * l.dtype.itemsize for l in jax.tree.leaves(params))

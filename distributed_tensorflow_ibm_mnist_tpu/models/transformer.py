"""Vision Transformer classifier — the sequence-model family of the zoo.

The reference's model layer was a single MNIST CNN (SURVEY.md §1 L3); the
rebuild adds a transformer so the framework's sequence-parallel machinery
(parallel/ring_attention.py) has a first-class consumer.  Architecture is a
small ViT: patchify -> learned positional embedding -> pre-norm blocks
(MHA + MLP) -> mean-pool -> linear head.

Parallelism hooks:

* ``attn_fn`` — drop-in attention callable ``(q, k, v) -> out`` on
  (B, S, H, D).  ``None`` uses in-module vanilla attention; pass the result
  of :func:`~...parallel.ring_attention.make_ring_attention` to shard the
  sequence over the ``seq`` mesh axis (the callable is a shard_map island,
  so this module stays ordinary GSPMD-jitted code).
* MLP sublayers are named ``dense_0``/``dense_1``, so the Megatron
  alternating TP rule (parallel/tensor_parallel.py) shards them over
  ``model`` with one reduction per block.
* The decode-cache leaves this module sows — dense ``k``/``v``
  ``(B, max_len, H_kv, D)`` slabs (+ int8 ``k_scale``/``v_scale``
  ``(B, max_len, H_kv)``) and paged ``pages_k``/``pages_v``
  ``(n_pages, page_size, H_kv, D)`` pools — all carry the KV-HEAD axis at
  a fixed position, which is what the SERVING tensor-parallel path shards
  (``kv_cache_rule`` in parallel/tensor_parallel.py: heads split over the
  ``tp`` mesh axis, block tables/cursors replicated).  Nothing in this
  module is mesh-aware: under ``InferenceEngine(tp=N)`` the same decode
  code runs SPMD with q/kv projections column-sharded, each chip
  attending over its own H/tp heads against its own cache shard, and one
  psum per attention block (the row-sharded out-projection) — so cache
  layout changes here must keep the head axis intact per leaf.

Compute in ``dtype`` (bf16 default, MXU-friendly); params and logits f32.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import vanilla_attention


def apply_rope(x: jnp.ndarray, theta: float = 10000.0, offset=0) -> jnp.ndarray:
    """Rotary position embedding on (B, S, H, D) queries/keys (D even).

    Pairs dimension d with d + D/2 and rotates each pair by pos * theta^(-2d/D),
    making attention scores a function of RELATIVE position — no learned
    (1, S, dim) table baking the trained length into the checkpoint, and
    graceful length extrapolation (VERDICT.md r2 item 5).  Angles are
    computed in f32 from the GLOBAL sequence axis: under sequence
    parallelism this runs in GSPMD-jitted model code BEFORE the sp island,
    so each shard's positions come from its global iota slice and the
    rotation composes with ring/Ulysses unchanged.

    ``offset`` shifts the positions (may be a traced int32 scalar): the
    KV-cache decode path rotates the current chunk at its absolute
    position ``cache_index + arange(s)``.  A (B,)-shaped ``offset`` gives
    each batch row its own absolute position — the ragged-prompt decode
    path, where row b's cursor sits at its own prompt length.
    """
    b, s, h, d = x.shape
    if d % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {d}")
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    off = jnp.asarray(offset, jnp.float32)
    if off.ndim == 0:
        pos = off + jnp.arange(s, dtype=jnp.float32)
        ang = pos[:, None] * freqs[None, :]  # (S, half)
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]
    else:  # (B,) per-row offsets
        pos = off[:, None] + jnp.arange(s, dtype=jnp.float32)[None, :]
        ang = pos[..., None] * freqs  # (B, S, half)
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def quantize_kv_int8(x):
    """Symmetric per-(token, head) int8 quantization for the decode cache:
    ``scale = max|x| / 127`` over the head_dim axis, so each cached
    position/head pair carries one f32 scale (1/D the cache's own bytes)
    and the (B, max_len, H_kv, D) payload stores int8 — HALF the HBM
    stream of a bf16 cache, the bandwidth-bound decode's next constant
    factor after GQA (round-5 verdict item 10).

    The scale factors NEVER multiply the cache payload on the read side:
    scores dequantize per (q, k) PAIR (``scores *= k_scale``) and the PV
    contraction folds ``v_scale`` into the probabilities — both D-times
    smaller than dequantizing the cache itself, so the int8 stream rides
    into the MXU through a fused convert.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(xf / scale[..., None])
    return q.astype(jnp.int8), scale


def reset_cache_slots(cache, slot_mask):
    """Zero the decode-cache state of selected batch rows: K/V payloads,
    int8 scales, and the (B,) write cursor of every row where ``slot_mask``
    is True, leaving other rows untouched.

    This is the per-slot reset the continuous-batching serving engine
    (serving/engine.py) runs when it retires a request: the freed slot's
    cursor returns to 0 so an idle slot's lockstep decode steps stay inside
    its own (max_len,) row, and the next admitted request starts from a
    clean row.  Every leaf of the cache pytree is (B, ...)-leading
    (``_decode_attention`` keeps the cursor (B,)-shaped in both ragged
    modes), so one broadcasted ``where`` per leaf suffices — cheap enough
    to jit per retire batch.
    """
    import jax

    mask = jnp.asarray(slot_mask, bool)

    def _reset(leaf):
        m = mask.reshape(mask.shape + (1,) * (leaf.ndim - 1))
        return jnp.where(m, jnp.zeros_like(leaf), leaf)

    return jax.tree.map(_reset, cache)


def _attend_cached(q, kc, vc, ksc, vsc, mask, dtype):
    """Score queries against a gathered cache span — the shared tail of the
    dense and paged decode-attention paths.

    ``kc``/``vc`` are (B, L, H_kv, D) cache operands in their STORED dtype
    (int8 payloads convert to ``dtype`` inside the contraction, keeping the
    HBM stream int8-sized); ``ksc``/``vsc`` are the per-(position, head)
    int8 scales or None for native caches; ``mask`` is (B|1, S, L).  The
    int8 scales apply at (q, k)-pair granularity: scores pick up k_scale
    per key position and probabilities fold v_scale before the PV
    contraction — both D-times cheaper than dequantizing the cache, and
    the softmax sees exactly the dequantized scores.  GQA queries score a
    grouped einsum against the hkv-sized cache with no materialized repeat.

    When the cache is int8, the scaled probabilities stay f32 INTO the PV
    einsum (ISSUE 12 satellite / ADVICE.md): ``p * v_scale`` spans the
    scale's dynamic range, so rounding it to bf16 BEFORE the contraction
    compounded the int8 error for bf16 models — the einsum accumulates in
    f32 anyway (``preferred_element_type``), and the int8 payload still
    converts in-register (the HBM stream is unchanged), so keeping p at
    f32 costs no cache bandwidth.  Native caches keep the compute-dtype p
    (bit-identical to every previous round).
    """
    import jax

    b, s, h, d = q.shape
    hkv = kc.shape[2]
    quant = ksc is not None
    scale = d ** -0.5
    kc_op = kc.astype(dtype) if quant else kc
    vc_op = vc.astype(dtype) if quant else vc
    p_dtype = jnp.float32 if quant else dtype
    if hkv != h:
        qg = q.reshape(b, s, hkv, h // hkv, d)
        scores = jnp.einsum(
            "bqhgd,bkhd->bhgqk", qg, kc_op,
            preferred_element_type=jnp.float32) * scale
        if quant:
            scores = scores * ksc.transpose(0, 2, 1)[:, :, None, None, :]
        scores = jnp.where(mask[:, None, None], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        if quant:
            p = p * vsc.transpose(0, 2, 1)[:, :, None, None, :]
        out = jnp.einsum(
            "bhgqk,bkhd->bqhgd", p.astype(p_dtype), vc_op,
            preferred_element_type=jnp.float32).reshape(b, s, h, d)
    else:
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, kc_op,
            preferred_element_type=jnp.float32) * scale
        if quant:
            scores = scores * ksc.transpose(0, 2, 1)[:, :, None, :]
        scores = jnp.where(mask[:, None], scores, -1e30)
        p = jax.nn.softmax(scores, axis=-1)
        if quant:
            p = p * vsc.transpose(0, 2, 1)[:, :, None, :]
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(p_dtype), vc_op,
            preferred_element_type=jnp.float32)
    return out.astype(dtype)


def _resolve_attn(attn_fn: Callable | None, attn: str) -> Callable:
    """attn_fn (explicit callable, e.g. a ring-attention island) wins; else
    pick by name: 'vanilla' (XLA) or 'flash' (the Pallas kernel) — a string
    so RunConfig/CLI can select it (``--set model_kwargs={'attn':'flash'}``)."""
    if attn_fn is not None:
        return attn_fn
    if attn == "flash":
        from distributed_tensorflow_ibm_mnist_tpu.ops.flash_attention import flash_attention

        return flash_attention
    if attn == "vanilla":
        return vanilla_attention
    raise ValueError(f"unknown attn {attn!r}; use 'vanilla' or 'flash'")


class TransformerBlock(nn.Module):
    dim: int
    heads: int
    heads_kv: int = 0  # 0 = heads (MHA).  Grouped-query attention: K/V
    #   projected to heads_kv < heads head groups — smaller kv params and a
    #   heads_kv-sized decode cache; the flash kernel routes q-heads to
    #   shared K/V blocks via index maps (no repeat copies)
    mlp_ratio: int = 4
    dropout: float = 0.0
    attn_fn: Callable | None = None
    attn: str = "vanilla"
    use_moe: bool = False
    n_experts: int = 8
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1  # experts per token: 1 = Switch, >1 = GShard top-k
    moe_z_weight: float = 0.0  # router z-loss coefficient (ST-MoE; 0 = off)
    moe_fn: Callable | None = None  # expert-parallel dispatch island (make_moe_dispatch)
    rope: bool = False  # rotary position embedding on q/k (apply_rope) —
    #   set by models whose pos="rope"; runs BEFORE attn_fn so sp islands
    #   receive already-rotated shards with global positions
    window: int = 0  # causal sliding-window attention width (0 = full);
    #   enforced by the model-built attn_fn on the training path and by the
    #   decode mask here; requires a causal family
    sow_kv: bool = False  # sow the (post-rope) K/V into "intermediates" on
    #   the NORMAL forward path — core/generate.py's flash prefill runs the
    #   prompt through the ordinary (flash) attention and assembles the
    #   decode cache from these, instead of attending over the max_len
    #   cache (O(S*max_len) scores, OOM for long prompts)
    kv_cache_dtype: str = "native"  # "native" (= dtype) | "int8": quantized
    #   decode cache with per-(position, head) scales — see quantize_kv_int8
    page_size: int = 0  # >0: PAGED decode cache — K/V live in a shared
    #   (n_pages, page_size, H_kv, D) pool indexed through a per-row
    #   (B, max_len/page_size) block table instead of a dense
    #   (B, max_len, ...) slab; see _paged_decode_attention.  The pool is
    #   engine state (serving/kv_pool.py), never initialized here.
    quant: str = "none"  # "int8": WEIGHT-only quantization — every dense
    #   projection in the block (qkv/q_proj/kv_proj/proj/dense_0/dense_1)
    #   becomes an Int8Dense (models/quant.py): int8 kernel + per-output-
    #   channel f32 scale, dequant fused into the matmul.  Params must be
    #   transformed with quantize_params_int8 (the serving engine does
    #   this at upload/swap); norms, embeddings, and MoE experts stay full
    #   precision.  Orthogonal to kv_cache_dtype (weights vs cache).
    dtype: jnp.dtype = jnp.bfloat16

    def _dense(self, features: int, name: str):
        """The block's matmul layer: nn.Dense, or its int8-stored sibling
        under the SAME name (so param trees transfer by name and the
        Megatron TP rule's path matches are unchanged)."""
        if self.quant == "int8":
            from distributed_tensorflow_ibm_mnist_tpu.models.quant import Int8Dense

            return Int8Dense(features, dtype=self.dtype, name=name)
        if self.quant != "none":
            raise ValueError(
                f"quant must be 'none' or 'int8', got {self.quant!r}")
        return nn.Dense(features, dtype=self.dtype, name=name)

    @nn.compact
    def __call__(self, x, train: bool = False, decode: bool = False,
                 max_len: int = 0, ragged: bool = False):
        b, s, _ = x.shape
        head_dim = self.dim // self.heads

        h = nn.LayerNorm(dtype=self.dtype, name="norm_attn")(x)
        hkv = self.heads_kv or self.heads
        if hkv == self.heads:
            qkv = self._dense(3 * self.dim, "qkv")(h)
            qkv = qkv.reshape(b, s, 3, self.heads, head_dim)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            if self.heads % hkv:
                raise ValueError(
                    f"heads ({self.heads}) must be a multiple of heads_kv ({hkv})"
                )
            # GQA: separate projections — q at full width, k/v at the
            # grouped width (the param saving IS the feature).  Named
            # q_proj/kv_proj for the Megatron TP rule.
            q = self._dense(self.dim, "q_proj")(h)
            kv = self._dense(2 * hkv * head_dim, "kv_proj")(h)
            q = q.reshape(b, s, self.heads, head_dim)
            kv = kv.reshape(b, s, 2, hkv, head_dim)
            k, v = kv[:, :, 0], kv[:, :, 1]
        if decode:
            o = self._decode_attention(q, k, v, max_len, ragged)
        else:
            if self.rope:
                q, k = apply_rope(q), apply_rope(k)
            if self.sow_kv:
                # absolute-position-rotated K/V, exactly what the decode
                # cache stores — the flash-prefill capture point
                self.sow("intermediates", "kv_cache", (k, v))
            o = _resolve_attn(self.attn_fn, self.attn)(q, k, v)
        o = o.reshape(b, s, self.dim)
        o = self._dense(self.dim, "proj")(o)
        if self.dropout > 0.0:
            o = nn.Dropout(self.dropout, deterministic=not train)(o)
        x = x + o

        h = nn.LayerNorm(dtype=self.dtype, name="norm_mlp")(x)
        # MoE blocks decode too (round 4): routing is per-call — the decode
        # step routes its B current tokens with capacity sized for B, the
        # standard MoE serving semantics (equal to full-forward logits
        # whenever capacity drops nothing; under pressure the per-step
        # routing drops differently than a full-sequence pass would).
        # Aux-loss/stat sows are no-ops outside mutable collections.
        if self.use_moe:
            from distributed_tensorflow_ibm_mnist_tpu.parallel.expert_parallel import MoEBlock

            h = MoEBlock(
                dim=self.dim, n_experts=self.n_experts, hidden_mult=self.mlp_ratio,
                capacity_factor=self.moe_capacity_factor, top_k=self.moe_top_k,
                z_weight=self.moe_z_weight, ep_fn=self.moe_fn, name="moe",
            )(h, train=train)
        else:
            h = self._dense(self.mlp_ratio * self.dim, "dense_0")(h)
            h = nn.gelu(h)
            h = self._dense(self.dim, "dense_1")(h)
        if self.dropout > 0.0:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h

    def _decode_attention(self, q, k, v, max_len: int, ragged: bool = False):
        """Incremental (KV-cache) attention for autoregressive decoding.

        Appends this call's K/V at the running per-row ``cache_index`` (a
        (B,) int32 cursor in the flax ``cache`` collection, mutated via
        ``mutable=["cache"]``) and attends each query causally over its
        row's filled prefix.  Handles S >= 1, so one call prefills a whole
        prompt and subsequent S=1 calls decode — the core/generate.py
        contract.  The cursor being per-row is what makes RAGGED prompts
        work: after a right-padded prefill each row's cursor starts at its
        own prompt length, new K/V land at per-row positions (vmapped
        ``dynamic_update_slice``), RoPE rotates at per-row absolute
        offsets, and the causal mask ``k_pos <= cursor`` keeps every row
        from seeing the pad garbage beyond its own prefix.

        ``ragged`` is STATIC: the per-row machinery (scatter-shaped cache
        writes, (B, S, half) rotation angles, (B, S, max_len) mask)
        measures ~20% of batched decode throughput at B=8 (r4: 18%
        single-shot, r5: 22% median — docs/PERFORMANCE.md), so the
        uniform case — ``prompt_lens=None``,
        including EOS-stopped batches, whose cursors advance in lockstep
        — keeps the scalar-cursor path (one ``dynamic_update_slice``,
        shared angles, (S, max_len) mask).  The cursor variable stays
        (B,)-shaped in both modes so the cache pytree is
        layout-compatible.

        Dtype policy matches the flash kernel (ops/flash_attention.py):
        native-dtype MXU operands with f32 accumulation
        (``preferred_element_type``) — decode is cache-bandwidth-bound, so
        upcasting the whole (B, max_len, H_kv, D) cache to f32 per step
        (the round-3 form) doubled the bytes read of the dominant stream.
        Softmax stays f32.

        The sp/ring ``attn_fn`` islands and the flash kernel are
        training/prefill machinery; decode is bandwidth-bound
        gather-attend over the cache, which XLA handles directly (no
        custom kernel needed at this scale).  Windowed models gather
        only the live W-span of the cache per step — O(W) instead of
        O(max_len) (the r3 advisor's noted cost) — at a shared start on
        the uniform path and at per-row starts (vmapped slices) on the
        ragged path (round 5); full-attention decodes score the whole
        filled prefix.
        """
        if max_len <= 0:
            raise ValueError("decode=True needs max_len > 0 (the KV-cache size)")
        if self.kv_cache_dtype not in ("native", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'native' or 'int8', got "
                f"{self.kv_cache_dtype!r}"
            )
        if self.page_size > 0:
            return self._paged_decode_attention(q, k, v, max_len)
        b, s, h, d = q.shape
        hkv = k.shape[2]  # GQA: the cache is heads_kv-sized — the memory win
        quant = self.kv_cache_dtype == "int8"
        store = jnp.int8 if quant else self.dtype
        cache_k = self.variable(
            "cache", "k", lambda: jnp.zeros((b, max_len, hkv, d), store))
        cache_v = self.variable(
            "cache", "v", lambda: jnp.zeros((b, max_len, hkv, d), store))
        if quant:
            scale_k = self.variable(
                "cache", "k_scale",
                lambda: jnp.zeros((b, max_len, hkv), jnp.float32))
            scale_v = self.variable(
                "cache", "v_scale",
                lambda: jnp.zeros((b, max_len, hkv), jnp.float32))
        idx_var = self.variable(
            "cache", "index", lambda: jnp.zeros((b,), jnp.int32))
        idx = idx_var.value  # (B,) per-row decode cursor
        import jax

        if ragged:
            if self.rope:
                q = apply_rope(q, offset=idx)
                k = apply_rope(k, offset=idx)
            if s == 1:
                row_update = jax.vmap(
                    lambda c, u, i: jax.lax.dynamic_update_slice(
                        c, u, (i,) + (0,) * (c.ndim - 1)))
            else:
                # multi-token ragged chunks (speculative verify windows,
                # core/generate.py make_verify_window): per-POSITION
                # clamped scatter, NOT a dynamic_update_slice — DUS clamps
                # the chunk's START, so a row overrunning max_len (a
                # retiring row within k-1 of its budget in a tight cache)
                # would have its whole chunk SHIFTED back over real
                # history.  Clamping each position piles the overflow onto
                # max_len-1 instead, which never holds live data (the
                # admission contract prompt+max_new <= max_len puts the
                # last real position at max_len-2), so within-budget
                # positions stay exact — the same overrun contract the
                # paged write path already has.
                rows_ = jnp.arange(b)[:, None]
                pos_ = jnp.minimum(
                    idx[:, None] + jnp.arange(s), max_len - 1)

                def row_update(c, u, i):
                    del i  # positions are precomputed (and clamped) above
                    return c.at[rows_, pos_].set(u.astype(c.dtype))
            if quant:
                k_st, k_sc = quantize_kv_int8(k)
                v_st, v_sc = quantize_kv_int8(v)
                scale_k.value = row_update(scale_k.value, k_sc, idx)
                scale_v.value = row_update(scale_v.value, v_sc, idx)
            else:
                k_st, v_st = k.astype(store), v.astype(store)
            cache_k.value = row_update(cache_k.value, k_st, idx)
            cache_v.value = row_update(cache_v.value, v_st, idx)
            q_pos = idx[:, None] + jnp.arange(s)  # (B, S) absolute positions
        else:
            idx0 = idx[0]  # uniform rows: ONE cursor, one slice update
            if self.rope:
                q = apply_rope(q, offset=idx0)
                k = apply_rope(k, offset=idx0)
            if quant:
                k_st, k_sc = quantize_kv_int8(k)
                v_st, v_sc = quantize_kv_int8(v)
                scale_k.value = jax.lax.dynamic_update_slice(
                    scale_k.value, k_sc, (0, idx0, 0))
                scale_v.value = jax.lax.dynamic_update_slice(
                    scale_v.value, v_sc, (0, idx0, 0))
            else:
                k_st, v_st = k.astype(store), v.astype(store)
            cache_k.value = jax.lax.dynamic_update_slice(
                cache_k.value, k_st, (0, idx0, 0, 0))
            cache_v.value = jax.lax.dynamic_update_slice(
                cache_v.value, v_st, (0, idx0, 0, 0))
            q_pos = (idx0 + jnp.arange(s))[None]  # (1, S) broadcasts over B
        # saturate the cursor at max_len: decode-ahead windows (serving
        # engine decode_ahead=k) legitimately run a retiring row up to k-1
        # steps past its budget before the host sees the EOS/budget stop,
        # so a full-budget row (prompt + max_new == max_len) may decode
        # past the cache end.  dynamic_update_slice already clamps the
        # WRITE start; clamping the cursor too keeps RoPE offsets and mask
        # positions bounded for those garbage steps (the row is reset at
        # retirement — wasted FLOPs, never corruption).  A no-op for every
        # well-behaved row: prompt + max_new <= max_len is the admission
        # contract.
        idx_var.value = jnp.minimum(idx + s, max_len)

        kc, vc = cache_k.value, cache_v.value
        ksc = scale_k.value if quant else None
        vsc = scale_v.value if quant else None
        k_pos = jnp.arange(max_len)[None]  # (1, max_len) absolute positions
        if self.window and (self.window + s - 1) < max_len:
            # windowed decode gathers only the live span instead of
            # scoring the whole max_len cache (the O(max_len)-per-step
            # cost noted by the r3 advisor): queries [cursor, cursor+s)
            # attend at most positions (cursor+s-1-W, cursor+s) — a
            # static W+s-1 span starting at max(cursor-W+1, 0).  The
            # span's end never exceeds cursor+s <= max_len (the cache
            # contract), so the dynamic_slice start is exact, and masking
            # the gathered span with its true positions keeps the
            # full-cache softmax's exact support (numerically equivalent;
            # reduction trees over span vs max_len elements round ~1e-7
            # apart, so not bit-identical).  Ragged rows (round 5) gather
            # at PER-ROW starts — a vmapped dynamic_slice at each row's
            # own cursor — so window composes with prompt_lens instead of
            # falling back to the O(max_len) full-cache score.
            span = self.window + s - 1
            if ragged:
                start = jnp.maximum(idx - self.window + 1, 0)  # (B,)
                row_slice = jax.vmap(
                    lambda c, st: jax.lax.dynamic_slice(
                        c, (st,) + (0,) * (c.ndim - 1),
                        (span,) + c.shape[1:]))
                kc = row_slice(kc, start)
                vc = row_slice(vc, start)
                if quant:
                    ksc = row_slice(ksc, start)
                    vsc = row_slice(vsc, start)
                k_pos = start[:, None] + jnp.arange(span)  # (B, span)
            else:
                start = jnp.maximum(idx0 - self.window + 1, 0)
                kc = jax.lax.dynamic_slice(
                    kc, (0, start, 0, 0), (b, span, hkv, d))
                vc = jax.lax.dynamic_slice(
                    vc, (0, start, 0, 0), (b, span, hkv, d))
                if quant:
                    ksc = jax.lax.dynamic_slice(
                        ksc, (0, start, 0), (b, span, hkv))
                    vsc = jax.lax.dynamic_slice(
                        vsc, (0, start, 0), (b, span, hkv))
                k_pos = (start + jnp.arange(span))[None]  # (1, span)
        mask = k_pos[:, None, :] <= q_pos[:, :, None]  # (B|1, S, span|max_len)
        if self.window:
            mask &= k_pos[:, None, :] > q_pos[:, :, None] - self.window
        return _attend_cached(q, kc, vc, ksc, vsc, mask, self.dtype)

    def _paged_decode_attention(self, q, k, v, max_len: int):
        """Paged decode attention: K/V live in a POOLED
        ``(n_pages, page_size, H_kv, D)`` slab per layer, and each batch row
        owns a ``(max_len / page_size,)`` row of the ``block_table`` mapping
        its virtual positions to pool pages.  Memory then scales with LIVE
        tokens (pages allocated on admission, freed on retirement) instead
        of ``slots * max_len``, and read-only pages can be SHARED between
        rows (the radix prefix cache, serving/radix_cache.py) because this
        path writes only the current chunk's positions — never a whole row.

        Writes scatter each new K/V position to ``(block_table[pos // ps],
        pos % ps)``; reads gather the row's full virtual span
        ``pool[block_table]`` back to (B, max_len, H_kv, D) and reuse the
        dense tail (same mask, same reduction shapes), which is what makes
        paged greedy decoding token-identical to the dense layout.
        ``max_len`` must be a page multiple so the virtual span is exactly
        max_len.  Write positions clamp at max_len - 1 exactly like the
        dense path's ``dynamic_update_slice`` clamp (decode-ahead overrun
        rows); unallocated block-table entries point at the reserved trash
        page 0, whose garbage is never exposed: a row's mask only admits
        positions below its cursor, all of which lie in allocated pages.

        The pool, block table, and cursor are ENGINE state: the init fns
        raise, because pool size is serving configuration
        (serving/kv_pool.py builds it), not a model attribute.  Sliding
        windows are rejected — the windowed span slice assumes dense
        contiguity.
        """
        import jax

        ps = self.page_size
        if max_len % ps:
            raise ValueError(
                f"paged decode needs max_len ({max_len}) to be a multiple "
                f"of page_size ({ps})")
        if self.window:
            raise ValueError(
                "paged decode does not compose with sliding-window "
                "attention (window > 0) — the windowed span gather assumes "
                "a dense contiguous cache row")
        b, s, h, d = q.shape
        hkv = k.shape[2]
        quant = self.kv_cache_dtype == "int8"
        store = jnp.int8 if quant else self.dtype

        def _external(name):
            def init():
                raise ValueError(
                    f"paged decode cache variable {name!r} must be supplied "
                    "by the caller — the page pool is engine state; build "
                    "it with serving.kv_pool.init_paged_cache")
            return init

        pages_k = self.variable("cache", "pages_k", _external("pages_k"))
        pages_v = self.variable("cache", "pages_v", _external("pages_v"))
        if quant:
            scale_k = self.variable(
                "cache", "pages_k_scale", _external("pages_k_scale"))
            scale_v = self.variable(
                "cache", "pages_v_scale", _external("pages_v_scale"))
        bt_var = self.variable("cache", "block_table", _external("block_table"))
        idx_var = self.variable("cache", "index", _external("index"))
        idx = idx_var.value  # (B,) per-row decode cursor
        bt = bt_var.value  # (B, max_len // ps) page ids into the pool

        if self.rope:
            q = apply_rope(q, offset=idx)
            k = apply_rope(k, offset=idx)
        # write positions, clamped like the dense path's update-slice clamp
        pos = jnp.minimum(idx[:, None] + jnp.arange(s), max_len - 1)  # (B, S)
        page = jnp.take_along_axis(bt, pos // ps, axis=1)  # (B, S)
        off = pos % ps
        if quant:
            k_st, k_sc = quantize_kv_int8(k)
            v_st, v_sc = quantize_kv_int8(v)
            scale_k.value = scale_k.value.at[page, off].set(k_sc)
            scale_v.value = scale_v.value.at[page, off].set(v_sc)
        else:
            k_st, v_st = k.astype(store), v.astype(store)
        pages_k.value = pages_k.value.at[page, off].set(k_st)
        pages_v.value = pages_v.value.at[page, off].set(v_st)
        q_pos = idx[:, None] + jnp.arange(s)  # (B, S), unclamped (dense parity)
        idx_var.value = jnp.minimum(idx + s, max_len)

        # gather the virtual row: (n_pages, ps, ...)[bt] -> (B, n_row, ps, ...)
        kc = pages_k.value[bt].reshape(b, max_len, hkv, d)
        vc = pages_v.value[bt].reshape(b, max_len, hkv, d)
        ksc = scale_k.value[bt].reshape(b, max_len, hkv) if quant else None
        vsc = scale_v.value[bt].reshape(b, max_len, hkv) if quant else None
        k_pos = jnp.arange(max_len)[None]
        mask = k_pos[:, None, :] <= q_pos[:, :, None]  # (B, S, max_len)
        return _attend_cached(q, kc, vc, ksc, vsc, mask, self.dtype)


class StackedBlocks(nn.Module):
    """The ViT block stack with params stacked ``(n_stages, per_stage, ...)``.

    The pipeline-parallel form of the block stack (VERDICT.md round-1 item
    2): one pytree param ``stacked`` holds every block's weights with a
    leading stage axis, so the GPipe island (parallel/pipeline.py) can shard
    stages over the ``pipe`` mesh axis and each device materializes only its
    own stage.  ``pipeline_fn(stage_fn, stacked, x)`` is the trainer-supplied
    hook that wraps ``stage_fn`` (scan this stage's blocks) in the shard_map
    pipeline — or falls back to a local scan for island-incompatible shapes
    (init samples, eval remainders).  With no hook, the stack is a plain
    ``lax.scan`` over all stages: numerically the unstacked ViT with
    identically-distributed (but differently-keyed) initialization.

    Restrictions inherited from the equal-shape pipeline contract: no
    dropout, no MoE blocks in the stack (both vary per-block state).
    """

    dim: int
    heads: int
    n_stages: int
    per_stage: int
    heads_kv: int = 0
    mlp_ratio: int = 4
    attn_fn: Callable | None = None
    attn: str = "vanilla"
    pipeline_fn: Callable | None = None
    block_remat: bool = False  # jax.checkpoint each block inside the stage
    #   scan: the pipeline's backward keeps only block-boundary residuals
    rope: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        import jax
        from jax import lax

        block = TransformerBlock(
            dim=self.dim, heads=self.heads, heads_kv=self.heads_kv,
            mlp_ratio=self.mlp_ratio,
            dropout=0.0, attn_fn=self.attn_fn, attn=self.attn, rope=self.rope,
            dtype=self.dtype,
        )
        sample = jnp.zeros((1, x.shape[1], self.dim), x.dtype)

        def init_fn(rng):
            keys = jax.random.split(rng, self.n_stages * self.per_stage)
            per = [block.init({"params": k}, sample, train=False)["params"] for k in keys]
            stages = [
                jax.tree.map(
                    lambda *a: jnp.stack(a),
                    *per[s * self.per_stage:(s + 1) * self.per_stage],
                )
                for s in range(self.n_stages)
            ]
            return jax.tree.map(lambda *a: jnp.stack(a), *stages)

        stacked = self.param("stacked", init_fn)
        block_apply = lambda p, c: block.apply({"params": p}, c, train=False)
        if self.block_remat:
            block_apply = jax.checkpoint(block_apply)

        def stage_fn(stage_params, h):
            def body(c, p):
                return block_apply(p, c), None

            out, _ = lax.scan(body, h, stage_params)
            return out

        if self.pipeline_fn is not None:
            return self.pipeline_fn(stage_fn, stacked, x)

        def body(c, ps):
            return stage_fn(ps, c), None

        out, _ = lax.scan(body, x, stacked)
        return out


class VisionTransformer(nn.Module):
    """Patch ViT over (B, H, W, C) images in [0, 1]."""

    patch_size: int = 4
    dim: int = 128
    depth: int = 4
    heads: int = 4
    heads_kv: int = 0  # 0 = heads; <heads = grouped-query attention
    mlp_ratio: int = 4
    num_classes: int = 10
    dropout: float = 0.0
    attn_fn: Callable | None = None
    attn: str = "vanilla"
    moe_every: int = 0  # 0 = dense; k = every k-th block uses a MoE FFN
    n_experts: int = 8
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1
    moe_z_weight: float = 0.0  # router z-loss coefficient (0 = off)
    moe_fn: Callable | None = None
    pp_stages: int = 0  # >0: stack blocks (n_stages, per_stage, ...) for the
    #                     GPipe island — params shardable over 'pipe'
    pipeline_fn: Callable | None = None  # (stage_fn, stacked_params, x) -> y
    block_remat: bool = False  # jax.checkpoint each block (backward
    #                            recomputes within-block activations; the
    #                            O(depth) memory lever for deep/long-seq runs)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.patch_size
        b, h, w, c = x.shape
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch size {p}")
        x = x.astype(self.dtype)
        # patchify as a stride-p conv: one MXU-friendly matmul over pixels
        x = nn.Conv(
            self.dim, kernel_size=(p, p), strides=(p, p), padding="VALID",
            dtype=self.dtype, name="patch_embed",
        )(x)
        s = (h // p) * (w // p)
        x = x.reshape(b, s, self.dim)
        pos = self.param("pos_embed", nn.initializers.normal(0.02), (1, s, self.dim))
        x = x + pos.astype(self.dtype)
        if self.pp_stages > 0:
            if self.depth % self.pp_stages:
                raise ValueError(
                    f"depth {self.depth} not divisible by pp_stages {self.pp_stages}"
                )
            if self.dropout > 0.0 or self.moe_every > 0:
                raise ValueError(
                    "pipeline stages need identical per-block programs: "
                    "dropout and MoE blocks don't compose with pp_stages"
                )
            x = StackedBlocks(
                dim=self.dim, heads=self.heads, heads_kv=self.heads_kv,
                n_stages=self.pp_stages,
                per_stage=self.depth // self.pp_stages, mlp_ratio=self.mlp_ratio,
                attn_fn=self.attn_fn, attn=self.attn, pipeline_fn=self.pipeline_fn,
                block_remat=self.block_remat, dtype=self.dtype, name="pipe_blocks",
            )(x, train=train)
            x = nn.LayerNorm(dtype=self.dtype, name="norm_out")(x)
            x = x.mean(axis=1)
            x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
            return x.astype(jnp.float32)
        # static_argnums: (self, x, train) -> train must stay a Python bool
        # through the checkpoint (it selects dropout determinism)
        block_cls = (
            nn.remat(TransformerBlock, static_argnums=(2,))
            if self.block_remat
            else TransformerBlock
        )
        for i in range(self.depth):
            x = block_cls(
                dim=self.dim, heads=self.heads, heads_kv=self.heads_kv,
                mlp_ratio=self.mlp_ratio,
                dropout=self.dropout, attn_fn=self.attn_fn, attn=self.attn,
                use_moe=self.moe_every > 0 and (i + 1) % self.moe_every == 0,
                n_experts=self.n_experts, moe_capacity_factor=self.moe_capacity_factor,
                moe_top_k=self.moe_top_k, moe_z_weight=self.moe_z_weight,
                moe_fn=self.moe_fn, dtype=self.dtype, name=f"block_{i}",
            )(x, train)
        x = nn.LayerNorm(dtype=self.dtype, name="norm_out")(x)
        x = x.mean(axis=1)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)

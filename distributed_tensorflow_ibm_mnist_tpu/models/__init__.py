"""Model zoo (flax.linen modules).

Covers the reference's model layer (SURVEY.md §1 L3: the TF-tutorial
LeNet-style MNIST CNN) plus the scale-out configs from BASELINE.md
(MLP smoke model, ResNet-20, ResNet-50).

Every model follows one calling convention:
``model(x, train: bool = False)`` with optional ``dropout`` RNG and
``batch_stats`` collection, images NHWC float in [0, 1].
"""

from __future__ import annotations

from distributed_tensorflow_ibm_mnist_tpu.models.causal_lm import CausalLM
from distributed_tensorflow_ibm_mnist_tpu.models.lenet import LeNet5
from distributed_tensorflow_ibm_mnist_tpu.models.mlp import MLP
from distributed_tensorflow_ibm_mnist_tpu.models.resnet import ResNet, ResNet20, ResNet50
from distributed_tensorflow_ibm_mnist_tpu.models.transformer import VisionTransformer

_REGISTRY = {
    "mlp": MLP,
    "lenet5": LeNet5,
    "resnet20": ResNet20,
    "resnet50": ResNet50,
    "vit": VisionTransformer,
    "causal_lm": CausalLM,
}


def get_model(name: str, **kwargs):
    """Build a model from the registry by name."""
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_REGISTRY)}") from None
    return cls(**kwargs)


def available_models() -> list[str]:
    return sorted(_REGISTRY)


def model_accepts(name: str, param: str) -> bool:
    """Whether a registry builder takes the given keyword (e.g. axis_name)."""
    import inspect

    try:
        builder = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_REGISTRY)}") from None
    try:
        return param in inspect.signature(builder).parameters
    except (TypeError, ValueError):
        return False


def model_default(name: str, param: str, default=None):
    """The declared default of a registry builder's keyword — e.g. the
    ``causal`` flag a model family ships with (True for causal_lm), or its
    ``heads``/``patch_size`` when the user didn't override them.  Returns
    ``default`` when the builder has no such parameter.  This is how the
    Trainer derives family semantics instead of asking the user to restate
    them (VERDICT.md r2 item 3)."""
    import inspect

    try:
        builder = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; available: {sorted(_REGISTRY)}") from None
    try:
        p = inspect.signature(builder).parameters.get(param)
    except (TypeError, ValueError):
        return default
    if p is None or p.default is inspect.Parameter.empty:
        return default
    return p.default


__all__ = ["CausalLM", "MLP", "LeNet5", "ResNet", "ResNet20", "ResNet50", "VisionTransformer", "get_model", "available_models", "model_accepts", "model_default"]

"""Two-layer MLP — the CPU smoke-test model (BASELINE.md config 1)."""

from __future__ import annotations

from collections.abc import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Flatten -> Dense(hidden) x N -> Dense(num_classes).

    Compute runs in ``dtype`` (bfloat16 by default for the MXU); parameters
    are kept in float32 and logits are returned in float32 for a stable
    softmax/loss.
    """

    hidden: Sequence[int] = (256,)
    num_classes: int = 10
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for i, width in enumerate(self.hidden):
            x = nn.Dense(width, dtype=self.dtype, name=f"dense_{i}")(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)

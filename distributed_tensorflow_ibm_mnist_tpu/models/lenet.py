"""LeNet-class MNIST CNN — the reference's 99%-capable model.

The reference's net is the TF-tutorial LeNet-style graph
(SURVEY.md §2.1 "MNIST CNN model graph":
conv(5x5,32) -> maxpool -> conv(5x5,64) -> maxpool -> fc(1024)+dropout ->
fc(10) softmax, built with ``tf.nn.conv2d``/``max_pool`` [B:5][R-high]).
This is the same architecture expressed as a flax module with bfloat16
compute so the convs/matmuls land on the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class LeNet5(nn.Module):
    """conv32 -> pool -> conv64 -> pool -> fc1024 + dropout -> fc(num_classes)."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1024, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)

"""LeNet-class MNIST CNN — the reference's 99%-capable model.

The reference's net is the TF-tutorial LeNet-style graph
(SURVEY.md §2.1 "MNIST CNN model graph":
conv(5x5,32) -> maxpool -> conv(5x5,64) -> maxpool -> fc(1024)+dropout ->
fc(10) softmax, built with ``tf.nn.conv2d``/``max_pool`` [B:5][R-high]).
This is the same architecture expressed as a flax module with bfloat16
compute so the convs/matmuls land on the MXU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def _polyphase_maps(k: int = 5, f: int = 2):
    """Static index/validity maps turning a (k, k, 1, C) stride-1 SAME conv
    kernel into its stride-``f`` polyphase form: a (k2, k2, f*f, f*f*C)
    kernel over the space-to-depth input, where k2 = k//f + 1.

    Polyphase identity: writing an output row ``o = f*i + d`` and an input
    offset ``t = d + u - k//2 = f*p + s`` (s in [0, f)), the 5x5 C_in=1
    conv decomposes exactly into f*f phase kernels of spatial extent k2
    over the f*f space-to-depth channels.  Returned as numpy constants so
    the per-step work is ONE gather+mask of the stored (5,5,1,C) kernel —
    checkpoints and the parameter layout are untouched.
    """
    half, k2 = k // 2, k // f + 1
    U = np.zeros((k2, k2, f * f, f * f), np.int32)
    V = np.zeros_like(U)
    OK = np.zeros(U.shape, bool)
    for d_i in range(f):
        for d_j in range(f):
            for p in range(k2):
                for q in range(k2):
                    for s_u in range(f):
                        for s_v in range(f):
                            u = f * (p - 1) + s_u + half - d_i
                            v = f * (q - 1) + s_v + half - d_j
                            ci, co = s_u * f + s_v, d_i * f + d_j
                            if 0 <= u < k and 0 <= v < k:
                                U[p, q, ci, co] = u
                                V[p, q, ci, co] = v
                                OK[p, q, ci, co] = True
    return U, V, OK


class LeNet5(nn.Module):
    """conv32 -> pool -> conv64 -> pool -> fc1024 + dropout -> fc(num_classes)."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    conv1_s2d: bool = False  # exact polyphase space-to-depth form of conv1:
    #   the C_in=1 5x5 conv wastes the MXU's reduction AND output lanes
    #   (4.5% of FLOPs, ~39% of step time — docs/PERFORMANCE.md); this
    #   computes the SAME function as one 3x3 conv with C_in=4, C_out=128
    #   over the pixel-unshuffled image, from the SAME stored (5,5,1,32)
    #   parameters (a static gather re-expresses the kernel per step).
    #   MEASURED REJECTION on the v5e bench condition (round 5, in-session
    #   A/B): 601.5k -> 425.0k img/s — the pixel-shuffle relayouts of the
    #   (B, 28, 28, 32) activations cost more than the 4x lane occupancy
    #   buys at these shapes, the same lesson as the round-2 im2col
    #   rejection.  Kept off by default; exact-equivalence test pins it.
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.conv1_s2d:
            x = self._conv1_polyphase(x)
        else:
            x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.dtype,
                        name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1024, dtype=self.dtype, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype, name="logits")(x)
        return x.astype(jnp.float32)

    def _conv1_polyphase(self, x):
        """conv1 evaluated in its stride-2 polyphase form (see conv1_s2d):
        a submodule NAMED "conv1" with the identical (5,5,1,32)+(32,)
        parameter layout, so checkpoints interchange with the direct
        form; equivalence pinned by test_lenet_conv1_s2d_matches_direct.
        """
        return _PolyphaseConv1(dtype=self.dtype, name="conv1")(x)


class _PolyphaseConv1(nn.Module):
    """The LeNet conv1 (5x5, C_in=1, SAME) computed as one 3x3 conv with
    C_in=4, C_out=128 over the pixel-unshuffled image — the SAME function
    from the SAME stored parameters (a static gather re-expresses the
    kernel per step; the 14x14 SAME conv's zero padding corresponds
    exactly to the original padding rows).  C_in=1 fills 1/128 of the
    MXU's reduction lanes and C_out=32 a quarter of its output lanes;
    the polyphase form trades 1.44x the FLOPs for 4x both occupancies.
    """

    features: int = 32
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        import jax

        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (5, 5, 1, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        b, h, w_, _ = x.shape
        hh, ww = h // 2, w_ // 2
        U, V, OK = _polyphase_maps()
        # (3, 3, 4, 4, C): phase kernels gathered from the stored weights
        wsd = jnp.where(
            jnp.asarray(OK)[..., None],
            kernel[jnp.asarray(U), jnp.asarray(V), 0, :],
            0.0,
        ).astype(self.dtype)
        wsd = wsd.reshape(3, 3, 4, 4 * self.features)
        xs = x.reshape(b, hh, 2, ww, 2).transpose(0, 1, 3, 2, 4)
        xs = xs.reshape(b, hh, ww, 4).astype(self.dtype)
        # no preferred_element_type: XLA accumulates bf16 convs in f32 on
        # TPU anyway, and an f32 OUTPUT would hand the backward's conv
        # transpose mixed-dtype operands (f32 cotangent x bf16 kernel),
        # which lax.conv refuses; this matches flax Conv's own lowering
        y = jax.lax.conv_general_dilated(
            xs, wsd, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y.reshape(b, hh, ww, 2, 2, self.features)
        y = y.transpose(0, 1, 3, 2, 4, 5).reshape(b, h, w_, self.features)
        return (y + bias).astype(self.dtype)

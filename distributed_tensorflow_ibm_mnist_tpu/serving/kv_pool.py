"""Paged KV-cache pool: the serving-side half of the paged decode path.

The dense engine allocates ``slots * max_len`` cache positions up front, so
concurrency is capped by worst-case-length allocation even when every live
request is short.  This module re-blocks the cache into a fixed pool of
``page_size``-token pages per layer — ``(n_pages, page_size, H_kv, D)``
pytree leaves — plus a per-slot ``(max_len / page_size,)`` BLOCK TABLE
mapping each row's virtual positions to pool pages.  Memory then scales
with LIVE tokens: admission allocates ``ceil((len + max_new) / page_size)``
pages, retirement frees them, and the engine can run more slots than the
pool could hold at worst case (overcommit), stalling admission — never
corrupting — when the pool is momentarily full.

Layout contract (mirrors the dense cache per block name):

    dense   {"k": (B, max_len, hkv, d), "v": ..., ["k_scale"/"v_scale":
             (B, max_len, hkv)], "index": (B,)}
    paged   {"pages_k": (n_pages, ps, hkv, d), "pages_v": ...,
             ["pages_k_scale"/"pages_v_scale": (n_pages, ps, hkv)],
             "block_table": (B, max_len // ps) int32, "index": (B,)}

Page 0 is a reserved TRASH page: every unallocated block-table entry points
at it, so idle rows' decode writes land in garbage nobody reads (the model's
causal mask only exposes positions below a live row's cursor, all of which
lie in allocated pages).  ``KVPagePool`` is the host-side allocator over
pages ``1 .. n_pages-1``; page ids are shared across layers (page ``p``
means slab ``p`` in EVERY layer's pool), which is what lets the radix
prefix cache (serving/radix_cache.py) refcount a whole-model prefix block
as one integer.

Everything jitted here is donation-friendly: the engine wraps
``make_paged_insert``/``paged_reset``/``make_paged_extend`` in ``jax.jit``
with the cache donated, same as the dense path (the ~23% donation win from
PR 2 carries over — the pool is the dominant buffer either way).

Context parallelism (ISSUE 20) never touches this module's code: under a
``cp > 1`` serving mesh the engine shards every ``pages_*`` leaf along its
PAGE axis (``kv_cache_rule`` pins ``P("cp", None, head, None)``), so each
of the ``cp`` chip rows physically holds ``n_pages / cp`` page slabs —
1/cp of the live KV bytes — while the block table and ``KVPagePool``
keep addressing the same GLOBAL page ids.  The (chip, page) split is the
partitioner's business: inserts scatter to whichever chip row owns the
target slab, decode's per-row gather assembles the attended span across
rows, and the host-side allocator, radix refcounts, and trash-page
protocol are layout-invariant — the same integers mean the same pages at
any cp.  The only cp-visible constraint lives in the engine: ``n_pages``
must divide by ``cp`` so the page axis shards evenly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..core.generate import _zeros_like_shapes

TRASH_PAGE = 0


def pages_needed(n_tokens: int, page_size: int) -> int:
    """Pages to hold ``n_tokens`` cache positions (host-side ceil div)."""
    return -(-int(n_tokens) // int(page_size))


class KVPagePool:
    """Host-side page allocator over a pool of ``n_pages`` pages.

    Page 0 is the reserved trash page and is never handed out.  ``alloc``
    is all-or-nothing (a partially admitted request would deadlock the
    pool) and hands out the lowest free ids first — deterministic, so the
    paged engine's behaviour replays exactly under the fault-injection
    harness.  The allocator knows nothing about sharing: the radix cache
    owns refcounts and calls ``free`` only when a page's count reaches
    zero.
    """

    def __init__(self, n_pages: int, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (page 0 is the reserved trash page), "
                f"got {n_pages}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # pop() takes from the END: store descending so allocation walks
        # ascending page ids (determinism + readable block tables)
        self._free = list(range(self.n_pages - 1, 0, -1))

    @property
    def capacity(self) -> int:
        """Allocatable pages (the trash page excluded)."""
        return self.n_pages - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def allocated(self) -> int:
        return self.capacity - len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """Take ``n`` pages, or None (and take nothing) if fewer are free."""
        if n < 0:
            raise ValueError(f"alloc needs n >= 0, got {n}")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        return out

    def free(self, pages) -> None:
        """Return pages to the pool.  Accepts any iterable of page ids."""
        for p in pages:
            p = int(p)
            if not 0 < p < self.n_pages:
                raise ValueError(
                    f"free of invalid page id {p} (pool has pages 1.."
                    f"{self.n_pages - 1}; page 0 is reserved)")
            self._free.append(p)
        if len(self._free) > self.capacity:
            raise ValueError("double free: more pages freed than exist")


def init_paged_cache(model, params, slots: int, max_len: int,
                     page_size: int, n_pages: int, shardings=None):
    """A zeroed paged decode cache for ``model``: per-layer page pools
    sized ``n_pages`` plus per-slot block tables and cursors, derived from
    the DENSE decode layout via ``jax.eval_shape`` (no forward runs), so
    dtypes — including the int8 payload + f32 scale split — always match
    what the dense path would have stored.

    ``model`` may be the dense model or its paged clone; the dense layout
    is probed either way.  Every block table starts all-TRASH (page 0) and
    every cursor at 0 — the state ``paged_reset`` restores per slot.

    ``shardings`` (a pytree of shardings matching the returned cache
    structure) allocates each pool leaf directly in its sharded layout, so
    a pool bigger than one chip never materializes on a single device.
    """
    return _zeros_like_shapes(
        paged_cache_shapes(model, params, slots, max_len, page_size,
                           n_pages), shardings)


def paged_cache_shapes(model, params, slots: int, max_len: int,
                       page_size: int, n_pages: int):
    """ShapeDtypeStruct tree of the paged cache :func:`init_paged_cache`
    allocates — exposed (like ``core.generate.cache_shapes``) so the
    tensor-parallel engine can derive a congruent sharding tree before
    any pool memory exists."""
    if max_len % page_size:
        raise ValueError(
            f"max_len ({max_len}) must be a multiple of page_size "
            f"({page_size}) so each slot's virtual span is exactly max_len")
    if n_pages < 2:
        raise ValueError(f"n_pages must be >= 2, got {n_pages}")
    dense = model.clone(page_size=0) if getattr(model, "page_size", 0) else model
    shapes = jax.eval_shape(
        lambda p: dense.apply(
            {"params": p}, jnp.zeros((slots, 1), jnp.int32),
            decode=True, max_len=max_len, ragged=True, mutable=["cache"],
        )[1]["cache"],
        params,
    )
    n_row = max_len // page_size
    struct = jax.ShapeDtypeStruct
    paged_shapes = {}
    for name, entry in shapes.items():
        k = entry["k"]  # (slots, max_len, hkv, d)
        hkv, d = k.shape[2], k.shape[3]
        paged = {
            "pages_k": struct((n_pages, page_size, hkv, d), k.dtype),
            "pages_v": struct((n_pages, page_size, hkv, d),
                              entry["v"].dtype),
            "block_table": struct((slots, n_row), jnp.int32),
            "index": struct((slots,), jnp.int32),
        }
        if "k_scale" in entry:
            paged["pages_k_scale"] = struct(
                (n_pages, page_size, hkv), entry["k_scale"].dtype)
            paged["pages_v_scale"] = struct(
                (n_pages, page_size, hkv), entry["v_scale"].dtype)
        paged_shapes[name] = paged
    return paged_shapes


def pool_page_bytes(cache) -> int:
    """Bytes one page occupies across every layer's pool leaves — the
    ``kv_bytes_live = pages_live * pool_page_bytes`` accounting unit."""
    total = 0
    for entry in cache.values():
        for key, leaf in entry.items():
            if key.startswith("pages_"):
                total += leaf.nbytes // leaf.shape[0]
    return total


def make_paged_insert(page_size: int, max_len: int) -> Callable:
    """Build ``insert(cache, row_cache, bt_row, slot) -> cache``: scatter a
    dense prefilled B=1 row (make_prefill's layout) into the page pool
    through ``bt_row`` and install the row's block table + cursor at
    ``slot``.  The engine jits this with the cache donated.

    The full (max_len,) row is scattered — including garbage above the
    cursor — which is safe precisely because a dense-prefilled request owns
    ALL of its pages privately (pages become shared only by donation to the
    radix trie AFTER insert, and donated pages are read-only from then on:
    later tenants of the same prefix never write below their cursor).
    """
    n_row = max_len // page_size
    pos = jnp.arange(max_len)
    page_idx = pos // page_size
    off = pos % page_size

    def insert(cache, row_cache, bt_row, slot):
        page = bt_row[page_idx]  # (max_len,) destination pages
        out = {}
        for name, entry in cache.items():
            row = row_cache[name]
            e = dict(entry)
            e["pages_k"] = entry["pages_k"].at[page, off].set(
                row["k"][0].astype(entry["pages_k"].dtype))
            e["pages_v"] = entry["pages_v"].at[page, off].set(
                row["v"][0].astype(entry["pages_v"].dtype))
            if "pages_k_scale" in entry:
                e["pages_k_scale"] = entry["pages_k_scale"].at[page, off].set(
                    row["k_scale"][0].astype(entry["pages_k_scale"].dtype))
                e["pages_v_scale"] = entry["pages_v_scale"].at[page, off].set(
                    row["v_scale"][0].astype(entry["pages_v_scale"].dtype))
            e["block_table"] = jax.lax.dynamic_update_slice(
                entry["block_table"], bt_row[None].astype(jnp.int32),
                (slot, 0))
            e["index"] = jax.lax.dynamic_update_slice(
                entry["index"], row["index"].astype(entry["index"].dtype),
                (slot,))
            out[name] = e
        return out

    return insert


def paged_reset(cache, slot_mask):
    """Per-slot reset in the paged layout: point the masked slots' block
    tables back at the trash page and zero their cursors.  The POOL is
    untouched — a freed page's stale K/V is dead data (nothing maps to it)
    until the allocator hands the page to a new tenant, whose insert/extend
    scatter overwrites every position its mask will ever expose.  The
    paged sibling of models/transformer.py ``reset_cache_slots``; the
    engine jits it with the cache donated under the same compile site.
    """
    mask = jnp.asarray(slot_mask, bool)
    out = {}
    for name, entry in cache.items():
        e = dict(entry)
        e["block_table"] = jnp.where(
            mask[:, None], TRASH_PAGE, entry["block_table"])
        e["index"] = jnp.where(mask, 0, entry["index"])
        out[name] = e
    return out


def pool_page_leaves(cache):
    """The ``pages_*`` leaves of a paged cache as a congruent sub-tree —
    the payload layout one page occupies across every layer (the handoff
    transfer unit, serving/kv_handoff.py)."""
    return {name: {k: v for k, v in entry.items() if k.startswith("pages_")}
            for name, entry in cache.items()}


def gather_page(cache, page_id):
    """One page's cross-layer payload: ``{layer: {pages_k: (ps, hkv, d),
    ...}}`` sliced at ``page_id``.  Read-only (jit WITHOUT donation — the
    source pool stays live until the handoff commits); ``device_get`` of
    the result assembles shards host-side, which is what makes a tp=4
    prefill pool's head-sharded page land as one full host array for a
    tp=1 decode pool (the resharding seam of the disaggregated tier)."""
    return jax.tree.map(lambda leaf: leaf[page_id], pool_page_leaves(cache))


def page_write(cache, payload, page_id):
    """Scatter one page's cross-layer ``payload`` (the
    :func:`gather_page` tree, host- or device-resident) into page
    ``page_id`` of every layer's pool.  Fixed shape at ANY prompt length
    — the handoff installs N pages as N dispatches of this ONE program,
    so the per-role compile census never moves with traffic.  The engine
    jits this with the cache donated."""
    out = {}
    for name, entry in cache.items():
        e = dict(entry)
        for key in entry:
            if key.startswith("pages_"):
                e[key] = entry[key].at[page_id].set(
                    payload[name][key].astype(entry[key].dtype))
        out[name] = e
    return out


def bt_install(cache, bt_row, slot, cursor):
    """Install ``slot``'s block table row and cursor across every layer —
    the no-forward landing step of a handed-off request (its K/V pages
    are already in the pool; only the mapping and the cursor are new).
    The engine jits this with the cache donated."""
    out = {}
    for name, entry in cache.items():
        e = dict(entry)
        e["block_table"] = jax.lax.dynamic_update_slice(
            entry["block_table"], bt_row[None].astype(jnp.int32), (slot, 0))
        e["index"] = entry["index"].at[slot].set(
            jnp.asarray(cursor, jnp.int32))
        out[name] = e
    return out


def make_paged_extend(model, max_len: int, page_size: int) -> Callable:
    """Build the PARTIAL-PREFIX prefill program: ``extend(params, cache,
    slot, bt_row, suffix, start, suffix_len) -> (cache, last_logits)``.

    When the radix cache matches the first ``start`` tokens of a prompt
    (whole shared pages), only the unshared suffix needs computing.  The
    suffix runs as ONE decode-mode chunk over the slot's block table: its
    queries attend the shared pages (read-only) plus themselves, and its
    K/V scatter into the slot's PRIVATE pages — copy-on-write at the
    divergence page falls out of the layout, because the block table remaps
    the diverging virtual block to a private page and the shared page is
    never written.  ``suffix`` is (1, Sb) right-padded to a bucket length;
    positions above ``suffix_len`` write garbage above the cursor into
    private pages (masked, later overwritten by decode).  The cursor is set
    to ``start + suffix_len`` (the REAL length, not the padded one) and
    ``last_logits`` is (1, V) at the last real suffix position — pick the
    first generated token from it, exactly like a dense prefill.

    ``model`` must be the PAGED clone (``page_size > 0``).  The engine jits
    this with the cache donated.
    """
    if not getattr(model, "page_size", 0):
        raise ValueError(
            "make_paged_extend needs the paged model clone "
            "(model.page_size > 0) — it decodes through the page pool")
    n_row = max_len // page_size

    def extend(params, cache, slot, bt_row, suffix, start, suffix_len):
        # install the row's block table first: the chunk decodes through it
        cache = {
            name: {
                **e,
                "block_table": jax.lax.dynamic_update_slice(
                    e["block_table"], bt_row[None].astype(jnp.int32),
                    (slot, 0)),
            }
            for name, e in cache.items()
        }
        # B=1 sub-cache over the FULL pool: only the slot's table row and
        # cursor narrow to the row; the pool leaves are shared storage
        sub = {}
        for name, e in cache.items():
            se = {k: v for k, v in e.items() if k.startswith("pages_")}
            se["block_table"] = jax.lax.dynamic_slice(
                e["block_table"], (slot, 0), (1, n_row))
            se["index"] = jnp.zeros((1,), jnp.int32) + start
            sub[name] = se
        logits, vars_ = model.apply(
            {"params": params, "cache": sub}, suffix.astype(jnp.int32),
            decode=True, max_len=max_len, ragged=True, mutable=["cache"],
        )
        new = vars_["cache"]
        out = {}
        for name, e in cache.items():
            oe = dict(e)
            for key in e:
                if key.startswith("pages_"):
                    oe[key] = new[name][key]
            # real cursor, not the padded chunk's clamped one
            oe["index"] = e["index"].at[slot].set(
                (start + suffix_len).astype(jnp.int32))
            out[name] = oe
        last = jax.lax.dynamic_index_in_dim(
            logits[0], suffix_len - 1, axis=0, keepdims=False)  # (V,)
        return out, last[None]

    return extend

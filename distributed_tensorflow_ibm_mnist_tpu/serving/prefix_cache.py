"""Content-addressed prompt prefix cache for the serving engine.

ROADMAP-scale traffic repeats itself: system prompts, few-shot preambles,
retry storms — the same token prefix prefilled again and again.  Prefill
is the one per-request compile-shaped dispatch the engine cannot batch
away (a B=1 bucket program that stalls every resident slot while it
runs), so a repeated prefix is pure redundant work.  This module is the
memoization layer: the engine keys each admission by a blake2b digest of
its BUCKET-granular prompt (the padded shape is part of the identity —
the same tokens in a different bucket produce a different cache row
layout downstream) and, on a hit, reuses the stored prefill cache row and
last-position logits, skipping the prefill dispatch entirely.

Two honest scope notes, by construction:

* **Whole-prompt granularity** — an entry matches only a byte-identical
  (bucket, prompt) pair.  Partial-prefix reuse (split a prompt, reuse the
  shared head) would need per-position cache surgery; the dominant
  real-world case (identical system prompts / repeated requests) is
  whole-prefix anyway.
* **Sampling-safe because nothing sampled is ever stored** (ISSUE 13) —
  the cache holds only the DETERMINISTIC prefill products (the cache row
  and the last-position logits), never a picked token.  Every admission —
  hit or miss — picks its own first token from those logits with its own
  request's ``(temperature, top_p, seed)`` through the one shared pick
  program (serving/sampling.py ``first_pick``), so a greedy hit replays
  the argmax and a sampled hit draws its own seed-keyed sample,
  bit-identical to what the request would have picked on a miss.

Eviction is byte-bounded LRU (``max_bytes`` over the stored cache rows'
``nbytes``), not entry-counted — one long-bucket row can weigh hundreds
of short ones, and the budget the operator actually has is device memory.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

import jax
import numpy as np


def prefix_key(bucket: int, tokens) -> str:
    """Content address of a bucket-granular prompt prefix: blake2b over
    the bucket id + the raw int32 token bytes.  The bucket participates
    because it IS part of the prefill identity — the padded prefill shape
    determines the stored row's layout and pad positions."""
    h = hashlib.blake2b(digest_size=16)
    h.update(int(bucket).to_bytes(8, "little"))
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.hexdigest()


class PrefixCache:
    """Byte-bounded LRU of prefill results keyed by :func:`prefix_key`.

    Values are ``(row_cache, payload)``: the B=1 prefill cache pytree
    (device-resident, reused read-only — the engine's slot insert copies
    it into the slot cache without donating it) and an opaque
    deterministic payload the caller replays on a hit — the serving
    engine stores the (1, V) last-position logits and re-picks the first
    token per request, which is what keeps the cache sampling-safe.
    ``get`` counts hits/misses for the stats record.
    """

    def __init__(self, max_bytes: int):
        if max_bytes <= 0:
            raise ValueError(
                f"max_bytes must be > 0 (omit the cache to disable it), "
                f"got {max_bytes}")
        self.max_bytes = int(max_bytes)
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.oversized = 0  # put() refusals: single entry > max_bytes —
        #   a persistently nonzero count means the budget is sized below
        #   one long-bucket row and the cache can never help that bucket
        # key -> (row_cache, payload, entry_bytes); insertion order IS
        # recency order (move_to_end on hit)
        self._entries: OrderedDict[str, tuple] = OrderedDict()

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: str):
        """The (row_cache, payload) stored under ``key``, or None."""
        entry = self._entries.get(key)
        if entry is None:
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return entry[0], entry[1]

    def put(self, key: str, row_cache, payload) -> None:
        """Store one prefill result, evicting least-recently-used entries
        until the byte budget holds.  An entry larger than the whole
        budget is refused outright and counted (``oversized``) — storing
        it would drain the entire LRU only to miss again next time."""
        if key in self._entries:
            self._entries.move_to_end(key)
            return
        nbytes = int(sum(leaf.nbytes for leaf in jax.tree.leaves(row_cache)))
        nbytes += int(sum(getattr(leaf, "nbytes", 0)
                          for leaf in jax.tree.leaves(payload)))
        if nbytes > self.max_bytes:
            self.oversized += 1
            return
        self._entries[key] = (row_cache, payload, nbytes)
        self.bytes += nbytes
        while self.bytes > self.max_bytes:
            _, (_, _, nb) = self._entries.popitem(last=False)
            self.bytes -= nb

    def clear(self) -> None:
        """Drop every entry (weight hot-swap: rows prefilled under the old
        params are wrong under the new ones).  Hit/miss counters survive —
        they are the run's story, not the cache's contents."""
        self._entries.clear()
        self.bytes = 0

"""Telemetry-driven elastic capacity for the daemonized tier (ISSUE 17).

The tier already has every mechanism elasticity needs — ``Replica``
lifecycle with warm respawn through the persistent compile cache,
drain-before-close (the weight-swap quiesce), failover harvest, and a
telemetry stream of queue depth and occupancy.  What it lacks is the
POLICY loop that turns those signals into capacity decisions.  This
module is that loop, deliberately small and deliberately mechanism-free:

* **Scale up** when backlog pressure holds: admitted-but-unserved
  requests per slot above ``up_backlog_per_slot`` (or the admission
  policy shedding — sheds are goodput ALREADY lost, the strongest
  possible up signal) for ``hysteresis_up`` consecutive ticks.  Capacity
  comes from :meth:`ServingDaemon.restart_replica` when a retired
  replica exists (WARM: the compile cache makes respawn cache-reads, and
  the router re-stamps the tier's current weights so a late-spawned
  replica never serves stale parameters) else
  :meth:`ServingDaemon.add_replica`.
* **Scale down** when the tier idles: an empty admission queue (nothing
  WAITING — in-flight work shows up as occupancy, not as a reason to
  hold idle capacity) and slot occupancy below ``down_occupancy`` for
  ``hysteresis_down`` ticks, never below ``min_replicas`` — via :meth:`ServingDaemon.retire_replica`, which
  DRAINS first (the replica finishes its in-flight work undispatchable,
  then the watchdog closes it under the pump lock).  Scale-down drops
  nothing, ever; that is the router's ``begin_retire`` contract, and the
  bench gates it.

Hysteresis is the whole art here: both verdicts must hold for N
consecutive ticks, and any tick of contrary evidence resets the streak —
a burst ending mid-count does not strand capacity, and one noisy sample
does not flap the tier.  After every action the OTHER direction's streak
resets too (an up decision is evidence against down, and vice versa).

The controller runs either embedded (call :meth:`tick` from your own
loop — the deterministic path tests and the bench drive) or as its own
daemon thread (:meth:`start` / :meth:`stop`) ticking every
``interval_s``.  :meth:`chip_seconds` integrates healthy-engines x
seconds over the capacity log — the denominator that makes elastic and
fixed tiers comparable at equal hardware cost (goodput per chip-second,
the bench's gate currency).
"""

from __future__ import annotations

import threading
import time

from distributed_tensorflow_ibm_mnist_tpu.serving.replica import HEALTHY


class Autoscaler:
    """Capacity controller over one :class:`~.daemon.ServingDaemon`.

    ``min_replicas``/``max_replicas`` bound the healthy count the
    controller will steer toward.  ``up_backlog_per_slot`` is the
    backlog-pressure threshold (waiting + in-flight logical requests per
    healthy slot); ``down_occupancy`` the idle threshold (occupied
    slots / total slots).  ``hysteresis_up``/``hysteresis_down`` are the
    consecutive-tick streaks each verdict needs.  ``clock`` is
    injectable for tests.
    """

    def __init__(self, daemon, *, min_replicas: int = 1,
                 max_replicas: int | None = None,
                 up_backlog_per_slot: float = 1.0,
                 down_occupancy: float = 0.25,
                 hysteresis_up: int = 2, hysteresis_down: int = 4,
                 interval_s: float = 0.05, clock=time.monotonic):
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) < min_replicas "
                f"({min_replicas})")
        if hysteresis_up < 1 or hysteresis_down < 1:
            raise ValueError("hysteresis streaks must be >= 1")
        self.daemon = daemon
        self.min_replicas = int(min_replicas)
        self.max_replicas = (int(max_replicas)
                             if max_replicas is not None else None)
        self.up_backlog_per_slot = float(up_backlog_per_slot)
        self.down_occupancy = float(down_occupancy)
        self.hysteresis_up = int(hysteresis_up)
        self.hysteresis_down = int(hysteresis_down)
        self.interval_s = float(interval_s)
        self.clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_shed = self._policy_shed()
        self.events: list[dict] = []   # every action, timestamped
        self.ticks = 0
        # capacity log: (t, healthy_engines) at construction + after
        # every action — chip_seconds() integrates it
        self._capacity_log: list[tuple[float, int]] = [
            (self.clock(), self._healthy_count())]
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    # signals

    def _policy_shed(self) -> int:
        return int(getattr(self.daemon.policy, "shed", 0))

    def _healthy(self):
        router = self.daemon.router
        return [r for r in router.replicas
                if r.state == HEALTHY and r.alive]

    def _healthy_count(self) -> int:
        return len(self._healthy())

    def signals(self) -> dict:
        """One telemetry sample: backlog (admission depth + logical
        in-flight), healthy capacity in slots, slot occupancy, and the
        policy's shed delta since the previous sample."""
        healthy = self._healthy()
        slots = sum(r.engine.slots for r in healthy)
        occupied = sum(r.engine.occupied for r in healthy)
        with self.daemon._adm_cv:
            waiting = len(self.daemon._admission)
            backlog = waiting + len(self.daemon._inflight)
        shed_now = self._policy_shed()
        shed_delta, self._last_shed = shed_now - self._last_shed, shed_now
        return {
            "healthy": len(healthy),
            "retiring": len(self.daemon.router._retiring),
            "slots": slots,
            "waiting": waiting,
            "backlog": backlog,
            "backlog_per_slot": (backlog / slots) if slots else float("inf"),
            "occupancy": (occupied / slots) if slots else 0.0,
            "shed_delta": shed_delta,
        }

    # ------------------------------------------------------------------
    # the control loop

    def tick(self) -> str | None:
        """One control decision; returns ``"up"``/``"down"`` when an
        action fired, else None."""
        self.ticks += 1
        sig = self.signals()
        # a retire in flight is capacity already leaving — freeze
        # decisions until the drain settles rather than double-steer
        if sig["retiring"]:
            return None
        up_pressure = (sig["shed_delta"] > 0
                       or sig["backlog_per_slot"] > self.up_backlog_per_slot)
        down_pressure = (sig["waiting"] == 0
                         and sig["occupancy"] < self.down_occupancy)
        self._up_streak = self._up_streak + 1 if up_pressure else 0
        self._down_streak = self._down_streak + 1 if down_pressure else 0
        at_ceiling = (self.max_replicas is not None
                      and sig["healthy"] >= self.max_replicas)
        if self._up_streak >= self.hysteresis_up and not at_ceiling:
            return self._scale_up(sig)
        if (self._down_streak >= self.hysteresis_down
                and sig["healthy"] > self.min_replicas):
            return self._scale_down(sig)
        return None

    def _scale_up(self, sig: dict) -> str | None:
        router = self.daemon.router
        retired = [r for r in router.replicas if r.retired and not r.alive]
        try:
            if retired:
                index = retired[0].index
                spawn_s = self.daemon.restart_replica(index)
                warm = True
            else:
                rep = self.daemon.add_replica()
                index, spawn_s, warm = rep.index, rep.spawn_s, False
        except RuntimeError:
            return None       # tier closing under us — not an error
        self._record("up", index=index, spawn_s=spawn_s, warm=warm, sig=sig)
        return "up"

    def _scale_down(self, sig: dict) -> str | None:
        # least-loaded retires first; equal load breaks toward the higher
        # index, keeping replica 0 (the longest-lived lane) resident
        victims = sorted(self._healthy(), key=lambda r: (r.load, -r.index))
        for rep in victims:
            if self.daemon.retire_replica(rep.index):
                self._record("down", index=rep.index, spawn_s=None,
                             warm=None, sig=sig)
                return "down"
        return None   # role constraints vetoed every candidate

    def _record(self, action: str, *, index, spawn_s, warm, sig) -> None:
        self._up_streak = self._down_streak = 0
        now = self.clock()
        self.events.append({
            "t": now, "action": action, "replica": index,
            "spawn_s": spawn_s, "warm": warm, "signals": sig,
        })
        self._capacity_log.append((now, self._healthy_count()))
        tel = self.daemon._telemetry
        if tel is not None:
            tel.inc(f"autoscale_{action}")

    # ------------------------------------------------------------------
    # accounting

    def chip_seconds(self, until: float | None = None) -> float:
        """Integral of healthy engines over time since construction —
        the hardware-cost denominator for goodput-per-chip-second."""
        until = self.clock() if until is None else until
        total = 0.0
        log = self._capacity_log
        for (t0, n), (t1, _) in zip(log, log[1:] + [(until, 0)]):
            total += max(0.0, min(t1, until) - t0) * n
        return total

    def summary(self) -> dict:
        ups = [e for e in self.events if e["action"] == "up"]
        return {
            "ticks": self.ticks,
            "scale_ups": len(ups),
            "scale_downs": sum(1 for e in self.events
                               if e["action"] == "down"),
            "warm_ups": sum(1 for e in ups if e["warm"]),
            "spawn_s": [round(e["spawn_s"], 6) for e in ups
                        if e["spawn_s"] is not None],
            "chip_seconds": round(self.chip_seconds(), 3),
            "healthy": self._healthy_count(),
        }

    # ------------------------------------------------------------------
    # threaded runner

    def start(self) -> "Autoscaler":
        """Tick on a daemon thread every ``interval_s`` until stop()."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    # a failed decision must not kill the control loop;
                    # the next sample decides again
                    pass

        self._thread = threading.Thread(target=_loop, name="dtm-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=timeout)
        self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

"""Recorded arrival traces: workload shapes as DATA, not driver code
(ISSUE 17).

The open-loop SLO bench (scripts/bench_slo.py) hardcodes its arrival
process — a homogeneous Poisson generator inlined in the driver.  That
measures overload, but only ONE shape of it, and the shape is not a
thing you can save, diff, or replay against two tiers.  This module
makes the workload a first-class artifact:

* :class:`TraceEvent` / :class:`ArrivalTrace` — the schema: each event
  is an arrival offset from trace start plus the request's shape
  (``prompt_len``, ``max_new``), its CLASS (``interactive`` vs
  ``batch`` — the two-tier traffic mix every serving paper's goodput
  story turns on), priority, and optional per-request SLOs.  Traces
  round-trip through JSONL (:meth:`ArrivalTrace.save` /
  :meth:`ArrivalTrace.load`), so a shape generated once replays
  byte-identically against any tier configuration.
* Generators for the canonical shapes: :func:`poisson_trace`
  (homogeneous — the bench's existing process, now recordable),
  :func:`bursty_trace` (on/off modulated: quiet base load with periodic
  arrival bursts — the autoscaler's reason to exist),
  :func:`diurnal_trace` (sinusoidal rate via Lewis-Shedler thinning —
  the day/night curve, compressed to seconds), and
  :func:`heavy_tail_trace` (Pareto-shaped request LENGTHS over Poisson
  arrivals — a few giants among many mice, the shape that breaks
  FIFO-behind-a-giant tiers).
* :func:`replay_trace` — drive a :class:`~.daemon.ServingDaemon` with a
  trace on the arrival clock (open-loop, coordinated-omission-free:
  submit at each event's offset regardless of completions) and return
  per-class dispositions + goodput, the report
  :func:`per_class_report` computes from delivered streams.

SLOs live in seconds, so a recorded trace would bake one machine's
latency scale into a portable artifact.  :func:`with_slos` is the seam:
generators emit SHAPE only (offsets, lengths, classes), and the replay
harness stamps calibrated SLOs per class right before driving — the
same trace replays on any box against SLOs measured on that box.

Rates are offered-load knobs in requests/second; generators are seeded
(`numpy` Generator) and deterministic — same seed, same trace.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Callable, Iterable

import numpy as np

_SCHEMA = "dtm-arrival-trace/1"
INTERACTIVE = "interactive"
BATCH = "batch"
_CLASSES = (INTERACTIVE, BATCH)


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One recorded arrival.  ``t_offset`` is seconds from trace start;
    ``cls`` is the traffic class (``interactive``/``batch``); SLOs are
    optional per-request overrides (usually stamped by
    :func:`with_slos`, not recorded)."""

    t_offset: float
    prompt_len: int
    max_new: int
    cls: str = INTERACTIVE
    priority: int = 0
    ttft_slo_s: float | None = None
    tpot_slo_s: float | None = None

    def __post_init__(self):
        if self.t_offset < 0:
            raise ValueError(f"t_offset must be >= 0, got {self.t_offset}")
        if self.prompt_len < 1 or self.max_new < 1:
            raise ValueError(
                f"prompt_len/max_new must be >= 1, got "
                f"{self.prompt_len}/{self.max_new}")
        if self.cls not in _CLASSES:
            raise ValueError(f"cls must be one of {_CLASSES}, got {self.cls!r}")


class ArrivalTrace:
    """An ordered list of :class:`TraceEvent` with a name and JSONL
    round-trip.  Events are kept sorted by offset — replay is a single
    forward walk of the arrival clock."""

    def __init__(self, name: str, events: Iterable[TraceEvent]):
        self.name = str(name)
        self.events = sorted(events, key=lambda e: e.t_offset)

    def __len__(self) -> int:
        return len(self.events)

    def __iter__(self):
        return iter(self.events)

    @property
    def duration_s(self) -> float:
        return self.events[-1].t_offset if self.events else 0.0

    def class_counts(self) -> dict:
        out = {c: 0 for c in _CLASSES}
        for ev in self.events:
            out[ev.cls] += 1
        return out

    def save(self, path) -> Path:
        """JSONL: a schema header line, then one event per line."""
        path = Path(path)
        with path.open("w") as fh:
            fh.write(json.dumps({"schema": _SCHEMA, "name": self.name,
                                 "n_events": len(self.events)}) + "\n")
            for ev in self.events:
                fh.write(json.dumps(dataclasses.asdict(ev)) + "\n")
        return path

    @classmethod
    def load(cls, path) -> "ArrivalTrace":
        path = Path(path)
        with path.open() as fh:
            header = json.loads(fh.readline())
            if header.get("schema") != _SCHEMA:
                raise ValueError(
                    f"{path}: not an arrival trace "
                    f"(schema {header.get('schema')!r}, want {_SCHEMA!r})")
            events = [TraceEvent(**json.loads(line))
                      for line in fh if line.strip()]
        if len(events) != header.get("n_events", len(events)):
            raise ValueError(
                f"{path}: truncated trace — header says "
                f"{header['n_events']} events, file has {len(events)}")
        return cls(header.get("name", path.stem), events)


def with_slos(trace: ArrivalTrace, *,
              interactive_ttft_slo_s: float | None,
              batch_ttft_slo_s: float | None = None,
              interactive_tpot_slo_s: float | None = None,
              batch_tpot_slo_s: float | None = None) -> ArrivalTrace:
    """Stamp calibrated, per-class SLOs onto a shape-only trace (a new
    trace — the recorded artifact stays machine-independent)."""
    ttft = {INTERACTIVE: interactive_ttft_slo_s, BATCH: batch_ttft_slo_s}
    tpot = {INTERACTIVE: interactive_tpot_slo_s, BATCH: batch_tpot_slo_s}
    return ArrivalTrace(trace.name, (
        dataclasses.replace(ev, ttft_slo_s=ttft[ev.cls],
                            tpot_slo_s=tpot[ev.cls])
        for ev in trace.events))


# ----------------------------------------------------------------------
# shape generators (seeded, deterministic)


def _draw_shape(rng, *, prompt_len, max_new, interactive_frac: float):
    """Common per-event draws: class (interactive gets priority 1 —
    PriorityPolicy drains it first under backlog), prompt/output lengths
    uniform in their inclusive ranges."""
    cls = INTERACTIVE if rng.random() < interactive_frac else BATCH
    return {
        "prompt_len": int(rng.integers(prompt_len[0], prompt_len[1] + 1)),
        "max_new": int(rng.integers(max_new[0], max_new[1] + 1)),
        "cls": cls,
        "priority": 1 if cls == INTERACTIVE else 0,
    }


def poisson_trace(n: int, rate_rps: float, *, seed: int,
                  prompt_len=(2, 6), max_new=(2, 4),
                  interactive_frac: float = 0.5) -> ArrivalTrace:
    """Homogeneous Poisson arrivals — exponential gaps at ``rate_rps``."""
    rng = np.random.default_rng(seed)
    t, events = 0.0, []
    for _ in range(n):
        t += rng.exponential(1.0 / rate_rps)
        events.append(TraceEvent(
            t_offset=t, **_draw_shape(rng, prompt_len=prompt_len,
                                      max_new=max_new,
                                      interactive_frac=interactive_frac)))
    return ArrivalTrace(f"poisson-r{rate_rps:g}-s{seed}", events)


def bursty_trace(n: int, base_rps: float, burst_rps: float, *, seed: int,
                 burst_every_s: float, burst_len_s: float,
                 prompt_len=(2, 6), max_new=(2, 4),
                 interactive_frac: float = 0.5) -> ArrivalTrace:
    """On/off modulated Poisson: ``base_rps`` background with windows of
    ``burst_rps`` every ``burst_every_s`` lasting ``burst_len_s`` — the
    quiet-then-slammed shape elastic capacity is judged on."""
    if burst_rps <= base_rps:
        raise ValueError(
            f"burst_rps ({burst_rps}) must exceed base_rps ({base_rps})")
    rng = np.random.default_rng(seed)
    t, events = 0.0, []
    for _ in range(n):
        in_burst = (t % burst_every_s) < burst_len_s
        t += rng.exponential(1.0 / (burst_rps if in_burst else base_rps))
        events.append(TraceEvent(
            t_offset=t, **_draw_shape(rng, prompt_len=prompt_len,
                                      max_new=max_new,
                                      interactive_frac=interactive_frac)))
    return ArrivalTrace(f"bursty-b{base_rps:g}-p{burst_rps:g}-s{seed}", events)


def diurnal_trace(n: int, mean_rps: float, *, seed: int, period_s: float,
                  depth: float = 0.8, prompt_len=(2, 6), max_new=(2, 4),
                  interactive_frac: float = 0.5) -> ArrivalTrace:
    """Sinusoidal rate ``mean_rps * (1 + depth*sin)`` via Lewis-Shedler
    thinning of a Poisson process at the peak rate — the day/night curve
    compressed to a ``period_s``-second day."""
    if not 0.0 <= depth < 1.0:
        raise ValueError(f"depth must be in [0, 1), got {depth}")
    rng = np.random.default_rng(seed)
    peak = mean_rps * (1.0 + depth)
    t, events = 0.0, []
    while len(events) < n:
        t += rng.exponential(1.0 / peak)
        rate_t = mean_rps * (1.0 + depth * np.sin(2 * np.pi * t / period_s))
        if rng.random() * peak <= rate_t:     # thinning acceptance
            events.append(TraceEvent(
                t_offset=t, **_draw_shape(rng, prompt_len=prompt_len,
                                          max_new=max_new,
                                          interactive_frac=interactive_frac)))
    return ArrivalTrace(f"diurnal-m{mean_rps:g}-s{seed}", events)


def heavy_tail_trace(n: int, rate_rps: float, *, seed: int,
                     alpha: float = 1.5, prompt_len=(2, 8), max_new=(2, 8),
                     interactive_frac: float = 0.5) -> ArrivalTrace:
    """Poisson arrivals with Pareto(``alpha``)-shaped LENGTHS, clipped to
    the inclusive ranges: most requests are mice at the range floor, a
    heavy tail of giants pins the ceiling — the mix where per-class
    accounting matters, because giants behind-the-counter starve mice."""
    if alpha <= 1.0:
        raise ValueError(f"alpha must be > 1 (finite mean), got {alpha}")
    rng = np.random.default_rng(seed)

    def tail(lo: int, hi: int) -> int:
        return int(min(hi, lo + np.floor(lo * (rng.pareto(alpha)))))

    t, events = 0.0, []
    for _ in range(n):
        t += rng.exponential(1.0 / rate_rps)
        base = _draw_shape(rng, prompt_len=prompt_len, max_new=max_new,
                           interactive_frac=interactive_frac)
        base["prompt_len"] = tail(prompt_len[0], prompt_len[1])
        base["max_new"] = tail(max_new[0], max_new[1])
        events.append(TraceEvent(t_offset=t, **base))
    return ArrivalTrace(f"heavytail-a{alpha:g}-s{seed}", events)


# ----------------------------------------------------------------------
# replay


def replay_trace(daemon, trace: ArrivalTrace, *, vocab: int = 16,
                 seed: int = 0, speed: float = 1.0,
                 timeout_s: float = 120.0,
                 prompt_fn: Callable | None = None) -> dict:
    """Drive ``daemon`` with ``trace`` on the arrival clock and return
    :func:`per_class_report` over the outcomes.

    Open-loop: each event submits at ``t_offset / speed`` seconds after
    replay start whether or not earlier requests finished; rejections
    (:class:`~.scheduler.QueueFull`, including policy sheds) are counted
    per class, never retried — the trace IS the offered load.  Prompts
    are deterministic from ``seed`` (or ``prompt_fn(event, rng)``), so
    two replays of one trace offer identical requests.
    """
    from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
        QueueFull,
    )

    if speed <= 0:
        raise ValueError(f"speed must be > 0, got {speed}")
    rng = np.random.default_rng(seed)
    if prompt_fn is None:
        def prompt_fn(ev, rng):
            return rng.integers(1, vocab, size=(ev.prompt_len,)).astype(
                np.int32)

    outcomes = []      # (event, dr | None, stream)
    t0 = time.monotonic()
    for ev in trace.events:
        lag = t0 + ev.t_offset / speed - time.monotonic()
        if lag > 0:
            time.sleep(lag)
        prompt = prompt_fn(ev, rng)
        stream: list[int] = []
        try:
            dr = daemon.submit(
                prompt, ev.max_new, priority=ev.priority,
                ttft_slo_s=ev.ttft_slo_s, tpot_slo_s=ev.tpot_slo_s,
                callback=lambda dr, tok, s=stream: s.append(int(tok)))
        except QueueFull:
            outcomes.append((ev, None, stream))
            continue
        outcomes.append((ev, dr, stream))
    deadline = time.monotonic() + timeout_s
    for _ev, dr, _stream in outcomes:
        if dr is not None:
            dr.wait(timeout=max(0.0, deadline - time.monotonic()))
    wall_s = time.monotonic() - t0
    return per_class_report(outcomes, wall_s)


def per_class_report(outcomes, wall_s: float) -> dict:
    """Per-class dispositions + goodput from replay outcomes.

    A request counts toward GOODPUT only if it finished ``done``, its
    delivered stream matches its final tokens (exactly-once), and every
    SLO it carried held end-to-end: TTFT = submit→first delivered token,
    TPOT = mean inter-token time over the remaining tokens.  Classes are
    reported separately — one aggregate number hides exactly the
    interactive-starved-by-batch failure the class split exists to show.
    """
    per = {c: {"offered": 0, "accepted": 0, "rejected": 0, "done": 0,
               "cancelled": 0, "failed": 0, "unfinished": 0,
               "slo_met": 0, "exactly_once": True, "ttfts": []}
           for c in _CLASSES}
    for ev, dr, stream in outcomes:
        row = per[ev.cls]
        row["offered"] += 1
        if dr is None:
            row["rejected"] += 1
            continue
        row["accepted"] += 1
        if not dr.done:
            row["unfinished"] += 1
            continue
        if dr.status != "done":
            row["cancelled" if dr.status == "cancelled" else "failed"] += 1
            continue
        row["done"] += 1
        if stream != dr.tokens:
            row["exactly_once"] = False
        met = True
        if dr.first_token_t is not None:
            ttft = dr.first_token_t - dr.submit_t
            row["ttfts"].append(ttft)
            if ev.ttft_slo_s is not None and ttft > ev.ttft_slo_s:
                met = False
            if (ev.tpot_slo_s is not None and dr.rr is not None
                    and dr.rr.req is not None and len(dr.tokens) > 1):
                req = dr.rr.req
                if req.finish_t is not None and req.first_token_t is not None:
                    tpot = ((req.finish_t - req.first_token_t)
                            / (len(dr.tokens) - 1))
                    if tpot > ev.tpot_slo_s:
                        met = False
        elif ev.ttft_slo_s is not None:
            met = False
        if met:
            row["slo_met"] += 1
    out = {"wall_s": round(wall_s, 3), "per_class": {}}
    for c, row in per.items():
        ttfts = row.pop("ttfts")
        row["goodput_rps"] = (round(row["slo_met"] / wall_s, 3)
                              if wall_s > 0 else None)
        row["ttft_p50_s"] = (round(float(np.percentile(ttfts, 50)), 4)
                             if ttfts else None)
        row["ttft_p99_s"] = (round(float(np.percentile(ttfts, 99)), 4)
                             if ttfts else None)
        out["per_class"][c] = row
    totals = {k: sum(out["per_class"][c][k] for c in _CLASSES)
              for k in ("offered", "accepted", "rejected", "done",
                        "cancelled", "failed", "unfinished", "slo_met")}
    totals["goodput_rps"] = (round(totals["slo_met"] / wall_s, 3)
                             if wall_s > 0 else None)
    totals["exactly_once"] = all(out["per_class"][c]["exactly_once"]
                                 for c in _CLASSES)
    out["total"] = totals
    return out

"""Model-free n-gram / prompt-lookup drafting for speculative decoding.

The draft model problem, deleted: instead of a second (small) LM proposing
continuations — extra HBM, a second program family, a distillation
pipeline — the drafter exploits the observation that served text is full
of REPETITION (retrieved context quoted back, code identifiers, boilerplate,
chat templates): the most recent prior occurrence of the current suffix
n-gram is a strong predictor of what comes next.  Drafting is a pure
host-side numpy suffix match over the request's own prompt + generated
tokens, so it costs microseconds, needs no weights, and can never be
stale — the context IS the request.

A draft is only ever a PROPOSAL: the verify window
(core/generate.py ``make_verify_window``) runs the target model over the
drafted block and accepts exactly the prefix the model's own greedy argmax
reproduces, so a bad draft costs wasted verify lanes, never a wrong token.
Sampled rows (ISSUE 13) keep the same guarantee distributionally: the
verify core accepts each drafted token by rejection sampling against the
target's filtered distribution (accept with prob ``p_target(draft)``,
resample from the draft-masked residual on reject), so the emitted stream
is distributed exactly as plain sampling — the drafter itself never
changes; it stays a model-free proposal source either way.
On low-repetition (adversarial) text the match rate drops toward zero and
speculative decoding degrades to plain decode — one emitted token per
window — which is the honest floor documented in docs/PERFORMANCE.md.
"""

from __future__ import annotations

import numpy as np


class NgramDrafter:
    """Prompt-lookup drafter: propose the continuation of the most recent
    prior occurrence of the context's suffix n-gram.

    For ``n`` from ``max_ngram`` down to ``min_ngram``, take the last n
    tokens of the context as the pattern, find its most recent EARLIER
    occurrence, and propose the ``draft_len`` tokens that follow it.
    Longer patterns are tried first (a longer match is more predictive);
    the first hit wins.  A match ``p`` tokens before the suffix means the
    stream is locally ``p``-periodic, so the proposal extends PERIODICALLY
    past the end of the context (token ``j`` is predicted as token
    ``j - p``, self-referencing into the draft once ``j`` passes the
    context) — without this, a short-period stream could never fill a
    draft longer than its period, exactly the high-acceptance case
    drafting exists for.  No match at any n returns an empty draft — the
    verify window still emits its one guaranteed token, so an empty draft
    is a plain decode step, not a stall.

    ``max_context`` bounds the searched suffix (the match scan is O(context)
    per window on the host); 0 = unbounded.
    """

    def __init__(self, draft_len: int, max_ngram: int = 3,
                 min_ngram: int = 1, max_context: int = 4096):
        if draft_len < 1:
            raise ValueError(f"draft_len must be >= 1, got {draft_len}")
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}/{max_ngram}")
        if max_context < 0:
            raise ValueError(f"max_context must be >= 0, got {max_context}")
        self.draft_len = int(draft_len)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        self.max_context = int(max_context)

    def draft(self, context: np.ndarray) -> np.ndarray:
        """Up to ``draft_len`` proposed continuations of ``context`` (1-D
        int array: the request's prompt + every generated token, the last
        of which is the pending token the verify chunk leads with).
        Returns a possibly-empty int32 array, never longer than
        ``draft_len``."""
        ctx = np.asarray(context, np.int32).ravel()
        if self.max_context and ctx.size > self.max_context:
            ctx = ctx[-self.max_context:]
        n_ctx = ctx.size
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if n_ctx <= n:  # pattern must have at least one earlier slot
                continue
            pat = ctx[-n:]
            # candidate starts: every position whose n-gram equals the
            # suffix, EXCLUDING the suffix occurrence itself
            wins = np.lib.stride_tricks.sliding_window_view(
                ctx[:-1], n) if n_ctx - 1 >= n else None
            if wins is None:
                continue
            hits = np.nonzero((wins == pat[None, :]).all(axis=1))[0]
            if hits.size == 0:
                continue
            s = int(hits[-1])  # most recent prior occurrence
            period = (n_ctx - n) - s  # suffix start minus match start
            out = np.empty((self.draft_len,), np.int32)
            for i in range(self.draft_len):
                j = n_ctx - period + i
                out[i] = ctx[j] if j < n_ctx else out[i - period]
            return out
        return np.zeros((0,), np.int32)

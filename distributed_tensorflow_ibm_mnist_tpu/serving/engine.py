"""Continuous-batching inference engine over the compiled decode path.

``make_generator`` (core/generate.py) compiles an entire prefill+decode
episode into ONE program per (B, P) shape: ideal for offline batches,
wrong for a request STREAM — every row waits for the slowest row's
``max_new`` (head-of-line blocking) and each new shape recompiles.  This
engine is the TF-Replicator / Mesh-TensorFlow answer (PAPERS.md): keep the
DEVICE side a small set of fixed-shape compiled programs and move all the
variable-length multiplexing into a host-side driver loop.

Device side (compiled once each, resident for the engine's lifetime):

* ``len(buckets)`` prefill programs (core/generate.py ``make_prefill`` at
  B=1 per padded bucket length),
* ONE batched single-step decode across all ``slots`` rows
  (``make_decode_step``, ragged — every slot owns an independent cursor),
* a slot insert (``dynamic_update_slice`` of a prefilled row into the
  (slots, max_len) cache — the slot index is traced, so one compile) and a
  per-slot reset (models/transformer.py ``reset_cache_slots``).

Host loop (:meth:`InferenceEngine.step`): cancel overdue rows → admit
queued requests into free slots (prefill at the request's bucket, pick its
first token) → one batched decode step across ALL slots → retire rows on
EOS / budget, zeroing their cache rows — freed slots refill on the very
next iteration, so no request ever waits on another request's completion.
Idle slots decode garbage into their own rows in lockstep (cache writes
are per-row; the batch shape is fixed) — wasted FLOPs on an un-full
engine, never corruption.

Greedy decode through this loop is token-for-token identical to
``make_generator`` (both run the same ``_prefill_core``/
``_decode_step_core`` math; pinned in tests/test_serving.py).

Failure hardening (ISSUE 3): failures are isolated at the blast radius
they actually have.  A fault belonging to ONE request — its prefill
raising (poisoned prompt, injected ``serving-admit`` chaos) or its user
``callback`` raising — moves that request to the terminal ``FAILED``
state (``Request.error`` records why), resets its cache row, and the loop
keeps serving every other slot.  A fault in the BATCHED decode dispatch
belongs to all slots: with ``stall_timeout_s`` set, decode exceptions are
absorbed as no-progress iterations until the watchdog deadline, then the
engine fails the in-flight requests and raises :class:`EngineStalled`
cleanly (slots cleared, engine reusable); without a watchdog, the first
decode fault fails in-flight requests and re-raises immediately.
``drain()`` (serve everything already accepted, admit nothing new) and
``close()`` (cancel queued + in-flight, emit stats, refuse further use)
give supervisors graceful-shutdown semantics.  Chaos sites
``serving-admit`` / ``serving-step`` / ``serving-callback``
(utils/chaos.py) inject all three failure shapes on a seeded schedule.
"""

from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.core.generate import (
    _filter_logits,
    init_cache,
    make_decode_step,
    make_prefill,
)
from distributed_tensorflow_ibm_mnist_tpu.models.transformer import reset_cache_slots
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import FIFOScheduler, Request
from distributed_tensorflow_ibm_mnist_tpu.serving.stats import ServingStats
from distributed_tensorflow_ibm_mnist_tpu.utils.metrics import MetricWriter


class EngineStalled(RuntimeError):
    """The watchdog verdict: no token progress across ALL slots within
    ``stall_timeout_s``.  In-flight requests were already moved to FAILED
    and their slots reset before this raised — the engine object remains
    usable (or closeable) by the caller that catches it."""


class InferenceEngine:
    """Slot-multiplexed continuous-batching decoder for a causal LM.

    ``slots`` is the resident decode batch (B); ``max_len`` the per-slot
    KV-cache length.  ``scheduler`` defaults to a :class:`FIFOScheduler`
    whose buckets must fit ``max_len``.  Sampling knobs mirror
    ``make_generator`` (greedy at ``temperature=0``; ``rng`` required
    otherwise — per-step keys are split from it).

    Usage::

        eng = InferenceEngine(model, params, slots=4, max_len=128)
        eng.submit([1, 2, 3], max_new=16)
        eng.submit([4, 5], max_new=64, deadline_s=2.0)
        done = eng.run()          # drive until every request retired
        done[0].generated         # real tokens (EOS kept), no pad fill

    The engine is NOT thread-safe: submit and run from one thread (the
    host loop is the single writer of all device state).
    """

    def __init__(self, model, params, *, slots: int, max_len: int,
                 scheduler: FIFOScheduler | None = None,
                 eos_id: int | None = None, pad_id: int = 0,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
                 rng=None, writer: MetricWriter | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: float | None = None,
                 chaos=None):
        if stall_timeout_s is not None and stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0 (None disables the watchdog), "
                f"got {stall_timeout_s}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_len < 2:
            raise ValueError(
                f"max_len must be >= 2 (one prompt token + one generated), "
                f"got {max_len}")
        if eos_id is not None and eos_id == pad_id:
            raise ValueError(
                f"eos_id and pad_id must differ (both {eos_id}): idle slots "
                "are fed pad_id, which must never read as a stop")
        if temperature == 0.0 and (top_k or top_p):
            raise ValueError(
                "top_k/top_p filter a SAMPLING distribution; set temperature > 0")
        if temperature != 0.0 and rng is None:
            raise ValueError(
                "temperature > 0 samples from the model — pass rng=")
        self.model = model
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        self.clock = clock
        # `is None`, NOT `or`: FIFOScheduler defines __len__, so an EMPTY
        # custom scheduler is falsy and `scheduler or default` would
        # silently discard it (with its buckets/bounds/clock)
        self.scheduler = scheduler if scheduler is not None else FIFOScheduler(
            max_len=max_len,
            buckets=tuple(b for b in (16, 32, 64, 128) if b <= max_len) or (max_len,),
            clock=clock)
        if self.scheduler.max_len != max_len:
            raise ValueError(
                f"scheduler.max_len ({self.scheduler.max_len}) != engine "
                f"max_len ({max_len}) — admission would pass requests the "
                "cache cannot hold")
        self.writer = writer
        self.stats = ServingStats(slots)

        # --- compiled device programs (all resident, all fixed-shape) ---
        # The engine's slot cache is DONATED through every program that
        # threads it (step/insert/reset): without donation XLA must copy
        # the whole (slots, max_len) cache per call to keep the input
        # buffer alive — measured ~23% of the dim-320 step on CPU.  Safe
        # because the engine immediately reassigns self.cache and never
        # touches the donated buffer again; the PUBLIC make_decode_step
        # stays undonated (callers own their caches).
        self._prefill = make_prefill(model, max_len)     # per-bucket shapes
        self._decode = make_decode_step(model, max_len, ragged=True)
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._reset = jax.jit(reset_cache_slots, donate_argnums=(0,))

        def _pick(logits, rng):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = _filter_logits(logits / temperature, top_k, top_p)
            return jax.random.categorical(rng, logits).astype(jnp.int32)

        def _step_and_pick(params, cache, tok, rng):
            # decode + token pick fused into ONE dispatch: the host loop
            # pays per-iteration dispatch latency on every decode step, so
            # halving the calls matters exactly where the engine competes
            # with the fused one-shot episode (jit-of-jit traces through)
            cache, logits = self._decode(params, cache, tok)
            return cache, _pick(logits, rng)

        self._step_and_pick = jax.jit(_step_and_pick, donate_argnums=(1,))

        def _prefill_and_pick(params, prompt, lens, rng):
            cache, last = self._prefill(params, prompt, lens)
            return cache, _pick(last, rng)

        self._prefill_and_pick = jax.jit(_prefill_and_pick)
        self._greedy = temperature == 0.0
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

        # --- mutable engine state ---
        self.cache = init_cache(model, params, slots, max_len)
        self._slot_req: list[Request | None] = [None] * slots
        self._slot_tok = np.full((slots,), self.pad_id, np.int32)
        self._tok_dev = None  # device copy of _slot_tok; None = stale
        self.completed: list[Request] = []
        # --- failure isolation / shutdown state ---
        self.stall_timeout_s = stall_timeout_s
        self._chaos = chaos  # utils/chaos.FaultInjector | None (see module doc)
        self._last_progress_t: float | None = None  # watchdog anchor
        self._draining = False  # drain(): serve what's accepted, admit no more
        self._closed = False

    @staticmethod
    def _insert_impl(cache, row_cache, slot):
        """Write row 0 of a B=1 prefill cache into ``slot`` of the engine
        cache (every leaf is (B, ...)-leading, so one dynamic_update_slice
        per leaf; ``slot`` is traced — one compile covers every slot)."""
        return jax.tree.map(
            lambda full, row: jax.lax.dynamic_update_slice(
                full, row.astype(full.dtype),
                (slot,) + (0,) * (full.ndim - 1)),
            cache, row_cache)

    @classmethod
    def from_trainer(cls, trainer, *, slots: int, max_len: int, **kw
                     ) -> "InferenceEngine":
        """Build an engine from a trained :class:`~...core.trainer.Trainer`
        run: the same clean single-device decode model + device-resident
        cast params ``Trainer.generate`` uses (training islands dropped,
        pp-stacked params unstacked)."""
        from distributed_tensorflow_ibm_mnist_tpu.models import get_model, model_accepts

        if not model_accepts(trainer.config.model, "pos") or not trainer.causal:
            raise ValueError(
                "InferenceEngine needs a causally-trained causal-LM-family "
                f"run; got {trainer.config.model!r}")
        clean_kwargs = {
            k: v for k, v in trainer.config.model_kwargs.items()
            if k not in ("attn_fn", "moe_fn", "pipeline_fn", "pp_stages")
        }
        model = get_model(trainer.config.model,
                          num_classes=trainer.num_classes, **clean_kwargs)
        kw.setdefault("writer", trainer.writer)
        return cls(model, trainer._decode_params(), slots=slots,
                   max_len=max_len, **kw)

    # ------------------------------------------------------------------
    # request lifecycle

    def submit(self, prompt, max_new: int, deadline_s: float | None = None,
               callback: Callable | None = None) -> Request:
        """Enqueue a request (see :meth:`FIFOScheduler.submit` for the
        admission rules; raises ``QueueFull`` under backpressure).
        ``callback(request, token)`` streams every generated token; if it
        raises, THIS request fails (terminal ``failed`` state) and the
        engine keeps serving the rest.  Refused after :meth:`drain` /
        :meth:`close`."""
        if self._closed or self._draining:
            raise RuntimeError(
                "engine is " + ("closed" if self._closed else "draining")
                + " — no new requests")
        return self.scheduler.submit(prompt, max_new, deadline_s=deadline_s,
                                     callback=callback)

    @property
    def occupied(self) -> int:
        return sum(r is not None for r in self._slot_req)

    @property
    def has_work(self) -> bool:
        return self.occupied > 0 or len(self.scheduler) > 0

    def _next_rng(self):
        # greedy decode never reads the key — skip the split's dispatch
        # (one per decode step; real latency on the host loop's hot path)
        if self._greedy:
            return self._rng
        self._rng, key = jax.random.split(self._rng)
        return key

    def _retire(self, slot: int, status: str, now: float) -> None:
        # the freed slot's stale token keeps being fed to the decode step
        # (its output is ignored and its cache row is reset), so _slot_tok
        # needs no write here — which keeps _tok_dev valid across retires
        req = self._slot_req[slot]
        req.status = status
        req.finish_t = now
        self._slot_req[slot] = None
        self.completed.append(req)
        self.stats.add(req)

    def _fail(self, req: Request, exc: BaseException, now: float) -> None:
        """Move ``req`` to the terminal FAILED state (isolated casualty)."""
        req.status = "failed"
        req.error = f"{type(exc).__name__}: {exc}"
        req.finish_t = now
        self.completed.append(req)
        self.stats.add(req)

    def _notify(self, req: Request, tok: int) -> None:
        """Deliver one token to the request's streaming callback.  Raises
        propagate to the caller, which fails THIS request only."""
        if self._chaos is not None:
            from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import ChaosFault

            self._chaos.raise_if_fired("serving-callback", ChaosFault)
        if req.callback is not None:
            req.callback(req, tok)

    def _admit(self, req: Request, slot: int, now: float) -> bool:
        """Prefill ``req`` at its bucket shape and land it in ``slot``.

        Failure-isolated: any exception from the request's OWN processing
        (prefill, first-token callback, injected ``serving-admit`` poison)
        fails the request and leaves the slot free.  Returns True when the
        failure happened AFTER the cache insert — the caller must reset
        the half-claimed row unless a later admit overwrites it.
        """
        inserted = False
        try:
            if self._chaos is not None:
                from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import ChaosFault

                self._chaos.raise_if_fired("serving-admit", ChaosFault)
            padded = np.full((1, req.bucket), self.pad_id, np.int32)
            padded[0, : req.tokens.size] = req.tokens
            row_cache, first_tok = self._prefill_and_pick(
                self.params, jnp.asarray(padded),
                jnp.asarray([req.tokens.size], jnp.int32), self._next_rng())
            self.cache = self._insert(
                self.cache, row_cache, jnp.asarray(slot, jnp.int32))
            inserted = True
            first = int(first_tok[0])
            req.admit_t = now
            req.generated.append(first)
            req.first_token_t = self.clock()  # TTFT: first token ON THE HOST
            req.status = "running"
            self._notify(req, first)
        except Exception as e:
            self._fail(req, e, self.clock())
            return inserted
        self._slot_req[slot] = req
        self._slot_tok[slot] = first
        self._tok_dev = None  # host mirror changed; re-upload before decode
        if self._done_reason(req) is not None:
            self._retire(slot, self._done_reason(req), self.clock())
        return False

    def _done_reason(self, req: Request) -> str | None:
        if self.eos_id is not None and req.generated and req.generated[-1] == self.eos_id:
            return "done"
        if len(req.generated) >= req.max_new:
            return "done"
        return None

    def step(self) -> int:
        """One host-loop iteration: cancel → admit → decode → retire.
        Returns the number of REAL tokens produced this iteration."""
        if self._closed:
            raise RuntimeError("engine is closed")
        t0 = self.clock()
        reset_mask = np.zeros((self.slots,), bool)
        admitted = False

        # 1) deadline sweep over RUNNING rows (queued rows are swept by the
        #    scheduler at pop time)
        for slot, req in enumerate(self._slot_req):
            if req is not None and t0 > req.overdue_at:
                self._retire(slot, "cancelled", t0)
                reset_mask[slot] = True

        # 2) admit into free slots — freed capacity refills immediately,
        #    which is the whole point of continuous batching.  A failed
        #    admission (poisoned request) frees the slot for the NEXT
        #    queued request in the same iteration — one casualty must not
        #    idle a slot for a whole loop turn.
        drained = False
        for slot in range(self.slots):
            while not drained and self._slot_req[slot] is None:
                req = self.scheduler.pop(self.clock())
                if req is None:
                    drained = True
                    break
                needs_reset = self._admit(req, slot, self.clock())
                if self._slot_req[slot] is not None:
                    admitted = True
                    reset_mask[slot] = False  # insert fully overwrote the row
                elif needs_reset:
                    # the casualty half-claimed the row (insert landed, then
                    # its callback raised); zero it unless a later admit in
                    # this same while-loop overwrites it
                    reset_mask[slot] = True
            if drained:
                break

        # 3) one batched decode step across ALL slots (fixed shape; idle
        #    rows decode garbage into their own rows).  A decode-dispatch
        #    fault belongs to ALL slots: with a watchdog it is absorbed as
        #    a no-progress iteration until stall_timeout_s, then in-flight
        #    requests fail and EngineStalled raises; without one it fails
        #    in-flight and re-raises immediately.
        produced = 0
        decoded = False
        if self.occupied > 0:
            try:
                if self._chaos is not None:
                    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
                        ChaosFault,
                    )

                    self._chaos.raise_if_fired("serving-step", ChaosFault)
                if self._tok_dev is None:
                    self._tok_dev = jnp.asarray(self._slot_tok)
                self.cache, nxt_dev = self._step_and_pick(
                    self.params, self.cache, self._tok_dev, self._next_rng())
            except Exception as e:
                now = self.clock()
                anchor = self._last_progress_t if self._last_progress_t is not None else t0
                if self._last_progress_t is None:
                    self._last_progress_t = t0
                if self.stall_timeout_s is None:
                    self._fail_in_flight(e, now)
                    raise
                if now - anchor > self.stall_timeout_s:
                    self._fail_in_flight(e, now)
                    raise EngineStalled(
                        f"no token progress across {self.slots} slots within "
                        f"{self.stall_timeout_s}s (last decode error: "
                        f"{type(e).__name__}: {e})") from e
                # transient: no tokens this iteration, watchdog keeps counting
            else:
                decoded = True
                # one sync serves both the host inspection below and the next
                # step's feed (the device array is reused as-is — no re-upload
                # unless an admission rewrites the host mirror)
                nxt = np.asarray(nxt_dev)
                self._tok_dev = nxt_dev
                self._slot_tok = nxt.copy()
                now = self.clock()
                for slot, req in enumerate(self._slot_req):
                    if req is None:
                        continue
                    tok = int(nxt[slot])
                    req.generated.append(tok)
                    produced += 1
                    try:
                        self._notify(req, tok)
                    except Exception as e:
                        # the callback's failure is THIS request's failure
                        self._slot_req[slot] = None
                        self._fail(req, e, now)
                        reset_mask[slot] = True
                        continue
                    reason = self._done_reason(req)
                    if reason is not None:
                        self._retire(slot, reason, now)
                        reset_mask[slot] = True

        # 4) zero retired rows so idle cursors restart from 0 (bounded) and
        #    the next admission starts from a clean row
        if reset_mask.any():
            self.cache = self._reset(self.cache, jnp.asarray(reset_mask))

        if produced > 0 or admitted or self.occupied == 0:
            self._last_progress_t = self.clock()
        self.stats.tick(self.occupied, max(self.clock() - t0, 0.0),
                        decoded=decoded)
        return produced

    def _fail_in_flight(self, exc: BaseException, now: float) -> None:
        """Fail every running request and reset their rows — the clean-exit
        half of the watchdog contract (the engine stays consistent for a
        caller that catches EngineStalled)."""
        mask = np.zeros((self.slots,), bool)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._slot_req[slot] = None
            self._fail(req, exc, now)
            mask[slot] = True
        if mask.any():
            self.cache = self._reset(self.cache, jnp.asarray(mask))
        self._last_progress_t = None

    def run(self, max_steps: int | None = None) -> list[Request]:
        """Drive :meth:`step` until every submitted request has retired
        (or ``max_steps`` host iterations elapse), then return the
        completed requests in retirement order.  Emits the stats summary
        through ``writer`` (when one was given) on drain."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        # overdue-before-admission cancellations belong to this run's book
        for req in self.scheduler.cancelled:
            self.completed.append(req)
            self.stats.add(req)
        self.scheduler.cancelled.clear()
        if self.writer is not None and not self.has_work:
            self.stats.emit(self.writer)
        return self.completed

    # ------------------------------------------------------------------
    # graceful shutdown

    def drain(self, max_steps: int | None = None) -> list[Request]:
        """Graceful shutdown, phase 1: serve every request already accepted
        (queued + in-flight) to retirement, admitting NOTHING new —
        :meth:`submit` raises from the moment drain starts.  Returns the
        completed list; call :meth:`close` afterwards to release the
        engine."""
        if self._closed:
            raise RuntimeError("engine is closed")
        self._draining = True
        return self.run(max_steps=max_steps)

    def close(self) -> None:
        """Graceful shutdown, phase 2 (or an immediate one): cancel every
        queued and in-flight request (terminal ``cancelled``, partial
        output kept), emit the stats record, and refuse all further
        submit/step/run/drain calls.  Idempotent."""
        if self._closed:
            return
        self._draining = True
        now = self.clock()
        mask = np.zeros((self.slots,), bool)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._retire(slot, "cancelled", now)
            mask[slot] = True
        if mask.any():
            self.cache = self._reset(self.cache, jnp.asarray(mask))
        while (req := self.scheduler.pop(now)) is not None:
            req.status = "cancelled"
            req.finish_t = now
            self.completed.append(req)
            self.stats.add(req)
        for req in self.scheduler.cancelled:  # overdue-at-pop sweepings
            self.completed.append(req)
            self.stats.add(req)
        self.scheduler.cancelled.clear()
        if self.writer is not None:
            self.stats.emit(self.writer)
        self._closed = True

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

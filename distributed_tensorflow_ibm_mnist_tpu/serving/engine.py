"""Continuous-batching inference engine over the compiled decode path.

``make_generator`` (core/generate.py) compiles an entire prefill+decode
episode into ONE program per (B, P) shape: ideal for offline batches,
wrong for a request STREAM — every row waits for the slowest row's
``max_new`` (head-of-line blocking) and each new shape recompiles.  This
engine is the TF-Replicator / Mesh-TensorFlow answer (PAPERS.md): keep the
DEVICE side a small set of fixed-shape compiled programs and move all the
variable-length multiplexing into a host-side driver loop.

Device side (compiled once each, resident for the engine's lifetime):

* ``len(buckets)`` prefill programs (core/generate.py ``make_prefill`` at
  B=1 per padded bucket length) — the bucket set comes from the scheduler
  (one source of truth; an engine-level ``buckets=`` that disagrees with a
  caller-supplied scheduler is rejected at construction),
* ONE batched decode-ahead WINDOW across all ``slots`` rows
  (``_sample_window_core``: a ``lax.scan`` of ``decode_ahead`` fused
  decode+pick steps, ragged — every slot owns an independent cursor),
* a slot insert (``dynamic_update_slice`` of a prefilled row into the
  (slots, max_len) cache — the slot index is traced, so one compile) and a
  per-slot reset (models/transformer.py ``reset_cache_slots``).

Host loop (:meth:`InferenceEngine.step`): cancel overdue rows → admit
queued requests into free slots (prefill at the request's bucket, pick its
first token) → ONE windowed decode dispatch across ALL slots → retire rows
on EOS / budget, zeroing their cache rows — freed slots refill on the very
next iteration, so no request ever waits on another request's completion.
Idle slots decode garbage into their own rows in lockstep (cache writes
are per-row; the batch shape is fixed) — wasted FLOPs on an un-full
engine, never corruption.

Decode-ahead (ISSUE 5, ``decode_ahead=k``): each dispatch runs k fused
decode+pick steps in-graph against a per-slot active mask FROZEN for the
window, emitting a (slots, k) token block the host reads back ONCE — the
per-token host sync and dispatch tax docs/PERFORMANCE.md §Serving measured
drop ~k×.  Retirement conditions (EOS, budget) are still judged on the
host, so a row that stops mid-window decodes up to k−1 garbage steps past
its stop before the host sees it; those tokens are masked off the output
(never appended, never delivered) and the row's ≤k−1 overrun writes land
only in its own row (models/transformer.py clamps the cursor at max_len) —
the same wasted-FLOPs-never-corruption contract idle slots already have.
Windows are token-identical for every k — greedy because a slot's tokens
depend only on its own cache row and previous token, sampled because the
PRNG key for the token at generated index n is ``fold_in(base_key, n)``
(serving/sampling.py): the index, not the window phase, owns the key, so
decode-ahead width never changes a request's stream.

Per-request sampling (ISSUE 13, top-k ISSUE 14): a request may carry
``SamplingParams(temperature, top_p, top_k, seed)`` (serving/sampling.py);
the engine keeps per-slot (slots,) temperature/top-p/top-k planes and a
(slots, 2) base-key plane as runtime DATA into ONE compiled window program
(core/generate.py ``_sample_window_core``) — greedy and sampled rows ride
the same program, so the compile census is invariant across sampling
mixes.  Each generated token's raw-logits logprob comes back with the
token block (``Request.logprobs``), and a request's stream is a pure
function of its seed — restarts and failover replays are
token-identical.

Two more host-loop latencies hide behind the window (ISSUE 5):

* **Prefix cache** (``prefix_cache_bytes=``, serving/prefix_cache.py) — a
  byte-bounded LRU keyed by blake2b over the (bucket, prompt) pair; a hit
  reuses the stored prefill row + last-position logits and skips the
  prefill dispatch entirely.  Sampling-safe: the cache stores only the
  DETERMINISTIC prefill products, and every admission (hit or miss) picks
  its own first token from the logits with its own request's params
  through the shared ``first_pick`` program (serving/sampling.py).
* **Prefill overlap** — after dispatching a window and BEFORE blocking on
  its readback, the engine pops the next queued request and dispatches its
  bucketed B=1 prefill, so prefill compute overlaps the in-flight window
  instead of stalling every slot.  The prefilled request parks in a
  pending queue (bounded by ``slots``) and lands in the next free slot;
  a pending request whose deadline lapses before landing is cancelled at
  landing time (the prefill was the overlap gamble's stake).

Speculative decoding (ISSUE 9, ``speculative="ngram"``): the decode-ahead
window still emits ONE token per model step — k tokens cost k sequential
forwards.  Speculative mode replaces the window with its verify sibling
(core/generate.py ``make_verify_window``): between dispatches the host
drafts up to ``draft_len`` continuation tokens per slot with a model-free
prompt-lookup drafter (serving/drafter.py — suffix n-gram match over the
request's own prompt + generated stream), and ONE (slots, draft_len+1)-
position target forward verifies the whole chunk.  Greedy rows accept
the longest drafted prefix the model's own argmax reproduces plus one
free correction token — output is token-identical to plain greedy decode
by construction (the emitted tokens ARE the argmax chain), pinned across
dense/paged/int8 layouts in tests/test_speculative.py.  Sampled rows use
speculative REJECTION sampling (core/generate.py ``_verify_sample_core``,
ISSUE 13): draft token i is accepted with probability
min(1, p_target(i)/q_draft(i)) and the first rejection resamples from
the residual distribution, so the emitted marginal equals sampling the
target directly (chi-squared gated in tests/test_sampling.py) and the
stream stays a pure function of the request's seed at fixed engine
config (replays are token-identical; the spec and plain sample PATHS
differ — only their distributions and the greedy limit coincide).
Every accepted lane is a sequential forward the
engine didn't run; a rejected lane costs a wasted verify position, never
a wrong token.  The KV cursor is rewound in-graph to the acceptance
point, so rejected positions are garbage the next window overwrites —
the same wasted-FLOPs-never-corruption contract as decode-ahead overrun,
on both layouts (paged allocation already budgets len+max_new; ISSUE 7).
Incompatible with sliding-window attention (rejected at construction).
The chaos contract is unchanged: one
``serving-step`` event per window dispatch, whether that window decodes
or verifies.  ``ServingStats`` gains drafted/accepted/corrected counters,
``accept_rate``, and ``useful_tokens_per_window``; each request's trace
track gains per-window draft/verify/accept spans.

Chunked prefill (ISSUE 14, ``prefill_chunk=C``): whole-prompt prefill —
bucketed OR radix-suffix — freezes every co-resident request's decode for
the full prompt duration, and long prompts need a matching bucket.  With
``prefill_chunk=C`` (paged KV required) admission allocates the request's
pages up front but dispatches NO prefill; the prompt then advances in
fixed (1, C)-token chunks through the paged suffix-extend program — ONE
``extend[b{C}]`` program for every chunk of every prompt, so the census
stays pinned and prompts up to ``max_len - max_new`` need no bucket.  One
chunk dispatches per engine iteration at the prefill-overlap seam
(between the window dispatch and its blocking readback), so the decode
latency any admission adds is bounded by one chunk, not one prompt.  The
partially-prefilled slot holds a transient PREFILLING state: occupied
(its pages are real) but inactive in every window — its decode writes
are garbage the chunk cursor overwrites — and invisible to drafting and
the token loop.  A radix partial hit lands chunking AT the divergence
page (``done`` starts at the matched-page boundary); the finished prompt
donates its pages back to the trie exactly like whole-prompt admission.
Chaos contract unchanged: one ``serving-admit`` event per admission
attempt (a pool-stall retry does not re-fire), chunk dispatches ride the
window's ``serving-step`` with NO events of their own.  The prefix cache
(whole-row store) is refused under chunking — the radix trie is the
prefix-sharing mechanism.

Launch-path prewarm (ROADMAP item 5a, :meth:`InferenceEngine.prewarm`):
every program above compiles lazily at first use, so the first requests
eat the whole compile bill as TTFT.  ``prewarm()`` runs the engine's full
program family once with dummy inputs before traffic — paired with
``compile_cache_dir=`` the compiles also persist across processes, and
``Router.prewarm()`` fans the warmup across replicas.

Greedy decode through this loop is token-for-token identical to
``make_generator`` for every ``decode_ahead`` (both run the same
``_prefill_core``/``_decode_step_core`` math; pinned in
tests/test_serving.py and tests/test_decode_ahead.py).

Failure hardening (ISSUE 3): failures are isolated at the blast radius
they actually have.  A fault belonging to ONE request — its prefill
raising (poisoned prompt, injected ``serving-admit`` chaos) or its user
``callback`` raising — moves that request to the terminal ``FAILED``
state (``Request.error`` records why), resets its cache row, and the loop
keeps serving every other slot.  A fault in the BATCHED decode dispatch
belongs to all slots: with ``stall_timeout_s`` set, decode exceptions are
absorbed as no-progress iterations until the watchdog deadline, then the
engine fails the in-flight requests and raises :class:`EngineStalled`
cleanly (slots cleared, engine reusable); without a watchdog, the first
decode fault fails in-flight requests and re-raises immediately.
``drain()`` (serve everything already accepted, admit nothing new) and
``close()`` (cancel queued + in-flight, emit stats, refuse further use)
give supervisors graceful-shutdown semantics.  Chaos sites
``serving-admit`` / ``serving-step`` / ``serving-callback``
(utils/chaos.py) inject all three failure shapes on a seeded schedule;
per-site event indices are unchanged by decode-ahead and overlap (one
``serving-admit`` event per admission attempt in FIFO order, one
``serving-step`` event per window dispatch).

Thread model: the engine itself is single-threaded — ONE thread (the
caller's loop, or one daemon pump thread per replica in
serving/daemon.py) drives ``step()``/``step_chunk()`` and owns every
slot/cache mutation.  Cross-thread ``submit()`` is the daemon's job: it
serializes admissions under its tier lock and the scheduler's deque
append/popleft are atomic under CPython, so the pump can pop while a
producer appends.  The only engine state other threads read directly is
:attr:`heartbeat_t` (a single float write, torn-read-free) — the
external liveness probe for a wedged pump.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.core.generate import (
    _sample_window_core,
    _verify_sample_core,
    _zeros_like_shapes,
    cache_shapes,
    make_prefill,
)
from distributed_tensorflow_ibm_mnist_tpu.models.quant import quantize_params_int8
from distributed_tensorflow_ibm_mnist_tpu.models.transformer import reset_cache_slots
from distributed_tensorflow_ibm_mnist_tpu.parallel.ring_attention import (
    make_ring_attention,
)
from distributed_tensorflow_ibm_mnist_tpu.parallel.tensor_parallel import (
    kv_cache_rule,
    make_param_specs,
    megatron_rule,
    mesh_shardings,
    per_chip_bytes,
    serving_mesh,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.drafter import NgramDrafter
from distributed_tensorflow_ibm_mnist_tpu.serving.kv_pool import (
    KVPagePool,
    bt_install,
    gather_page,
    make_paged_extend,
    make_paged_insert,
    page_write,
    paged_cache_shapes,
    paged_reset,
    pages_needed,
    pool_page_bytes,
    pool_page_leaves,
)
from distributed_tensorflow_ibm_mnist_tpu.serving import kv_handoff
from distributed_tensorflow_ibm_mnist_tpu.serving.prefix_cache import PrefixCache
from distributed_tensorflow_ibm_mnist_tpu.serving.radix_cache import RadixCache
from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import (
    SamplingParams,
    base_key,
    first_pick,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import FIFOScheduler, Request
from distributed_tensorflow_ibm_mnist_tpu.serving.stats import ServingStats
from distributed_tensorflow_ibm_mnist_tpu.utils.metrics import MetricWriter
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import CompileTracker

# sentinel "row cache" _prefill_request returns for a radix partial-prefix
# hit: nothing was dispatched — the real work (the suffix-extend program)
# runs at LANDING, against the live trie/pool state at that moment
_RADIX_PREFILL = object()

# sentinel "prefilled" payload a chunked admission parks with when the
# page pool is momentarily dry (ISSUE 14): nothing was prefilled — the
# retry re-runs _chunk_admit from the allocation, skipping the already-
# fired serving-admit chaos event (one event per admission attempt)
_CHUNK_STALL = object()

# sentinel "first token" _paged_land returns on a prefill-role engine
# (ISSUE 16): no token was picked — the landing was packaged into the
# handoff outbox and the slot is already free (its pages moved to the
# packet's hold; the block table gets the caller's reset)
_HANDOFF = object()


class EngineStalled(RuntimeError):
    """The watchdog verdict: no token progress across ALL slots within
    ``stall_timeout_s``.  In-flight requests were already moved to FAILED
    and their slots reset before this raised — the engine object remains
    usable (or closeable) by the caller that catches it."""


class InferenceEngine:
    """Slot-multiplexed continuous-batching decoder for a causal LM.

    ``slots`` is the resident decode batch (B); ``max_len`` the per-slot
    KV-cache length.  ``scheduler`` defaults to a :class:`FIFOScheduler`
    built from ``buckets=`` (or the stock bucket ladder); pass both a
    scheduler AND ``buckets=`` and they must agree — the scheduler's
    buckets are the compiled prefill shapes.  ``decode_ahead=k`` runs k
    fused decode steps per dispatch/readback (greedy output is
    k-invariant; see the module docs for the waste trade).
    ``speculative="ngram"`` swaps the decode window for the speculative
    verify window: a host-side prompt-lookup drafter proposes up to
    ``draft_len`` tokens per slot per window and one target forward
    accepts greedy rows by argmax match and sampled rows by rejection
    sampling — greedy output stays token-identical to plain decode,
    sampled output stays seed-deterministic and unbiased; exclusive with
    sliding-window attention (see module docs).  ``prefix_cache_bytes``
    arms the prompt prefix cache (sampling-safe — it stores prefill
    logits, never a picked token).

    ``kv_page_size=ps`` switches the decode cache to the PAGED layout
    (serving/kv_pool.py): a fixed pool of ``kv_pages`` pages per layer plus
    per-slot block tables, so HBM scales with LIVE tokens instead of
    ``slots * max_len``.  ``kv_pages`` defaults to dense-equivalent
    capacity; set it LOWER to overcommit (more slots than worst-case
    memory) — a request the pool momentarily cannot hold parks and retries
    (admission stall, never corruption or failure).  ``radix_cache``
    (default on when paged) shares whole prompt-prefix pages between
    requests through a radix trie (serving/radix_cache.py): a matched
    prefix skips its prefill compute (only the suffix runs, via the extend
    program) and occupies ZERO extra pages.  Greedy paged output is
    token-identical to the dense engine for every ``decode_ahead``.
    ``prefill_chunk=C`` (paged only) replaces whole-prompt prefill with
    interleaved C-token chunks through the one ``extend[b{C}]`` program —
    bounded decode stalls, prompts up to ``max_len - max_new`` with no
    matching bucket, a transient PREFILLING slot state (see module docs);
    exclusive with ``prefix_cache_bytes`` (the radix trie is the sharing
    mechanism under chunking).

    ``tp=N`` shards the WHOLE program family over an N-chip ``("tp",)``
    mesh (parallel/tensor_parallel.py ``serving_mesh``): weights
    column/row-split by the same Megatron rule the training mesh uses
    (q/kv/up column, proj/down row — one psum per attention block and one
    per MLP per layer), the KV cache split over the HEAD axis in both
    layouts, per-chip weight and KV bytes 1/tp — a model whose bf16
    weights + pool exceed one chip serves anyway.  ``tp_devices=`` picks
    the chips (default: the first N visible; a router passes each replica
    its own disjoint group).  ``tp`` must divide ``heads`` AND
    ``heads_kv``.  Everything host-side — scheduler, page pool, radix
    trie, prefix keys, the n-gram drafter — never sees the mesh, so
    allocation/admission decisions and greedy output are tp-invariant
    (pinned in tests/test_tp_serving.py), and ``swap_params`` re-shards a
    full host tree onto the engine's own mesh.

    Engine-level sampling knobs (``temperature``/``top_k``/``top_p``/
    ``rng``) set the DEFAULT for requests that carry no
    ``SamplingParams`` (greedy at ``temperature=0``; ``rng`` required
    otherwise — its key data seeds the default base key).  A request's
    own ``submit(..., sampling=SamplingParams(...))`` overrides the
    default per slot — temperature/top_p/top_k/seed are all per-slot
    runtime data planes into the one compiled window (ISSUE 14 made
    top-k a data plane like the rest).
    ``tracer=`` (utils/tracing.Tracer) records a span tree per request and
    per decode window (nil-guarded — zero tracing instructions when None);
    construct it with the same ``clock`` as the engine so span durations
    agree with reported latencies.  Compile accounting is always on:
    ``stats`` reports this engine's ``n_compiled_programs`` /
    ``compile_time_s`` by site (docs/OBSERVABILITY.md).

    Usage::

        eng = InferenceEngine(model, params, slots=4, max_len=128)
        eng.submit([1, 2, 3], max_new=16)
        eng.submit([4, 5], max_new=64, deadline_s=2.0)
        done = eng.run()          # drive until every request retired
        done[0].generated         # real tokens (EOS kept), no pad fill

    The engine is NOT thread-safe: submit and run from one thread (the
    host loop is the single writer of all device state).
    """

    def __init__(self, model, params, *, slots: int, max_len: int,
                 scheduler: FIFOScheduler | None = None,
                 buckets: tuple[int, ...] | None = None,
                 decode_ahead: int = 1,
                 speculative: str | None = None, draft_len: int = 3,
                 prefix_cache_bytes: int = 0,
                 kv_page_size: int = 0, kv_pages: int = 0,
                 radix_cache: bool | None = None,
                 prefill_chunk: int = 0,
                 tp: int = 1, tp_devices=None,
                 cp: int = 1, cp_devices=None,
                 quant: str | None = None,
                 eos_id: int | None = None, pad_id: int = 0,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
                 min_p: float = 0.0, role: str = "both",
                 rng=None, writer: MetricWriter | None = None,
                 clock: Callable[[], float] = time.monotonic,
                 stall_timeout_s: float | None = None,
                 compile_cache_dir: str | None = None,
                 chaos=None, tracer=None, trace_tid: int = 0,
                 telemetry=None):
        if stall_timeout_s is not None and stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0 (None disables the watchdog), "
                f"got {stall_timeout_s}")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if max_len < 2:
            raise ValueError(
                f"max_len must be >= 2 (one prompt token + one generated), "
                f"got {max_len}")
        if decode_ahead < 1:
            raise ValueError(
                f"decode_ahead must be >= 1 (1 = one decode step per host "
                f"sync, the classic loop), got {decode_ahead}")
        if speculative not in (None, "ngram"):
            raise ValueError(
                f"speculative must be None or 'ngram' (model-free prompt-"
                f"lookup drafting), got {speculative!r}")
        if speculative is not None:
            if draft_len < 1:
                raise ValueError(
                    f"draft_len must be >= 1 (tokens drafted per verify "
                    f"window), got {draft_len}")
            if getattr(model, "window", 0):
                raise ValueError(
                    "speculative decoding does not compose with sliding-"
                    "window attention (model.window > 0): an overrunning "
                    "verify chunk would mislabel the windowed span gather")
        if eos_id is not None and eos_id == pad_id:
            raise ValueError(
                f"eos_id and pad_id must differ (both {eos_id}): idle slots "
                "are fed pad_id, which must never read as a stop")
        if temperature == 0.0 and (top_k or top_p or min_p):
            raise ValueError(
                "top_k/top_p/min_p filter a SAMPLING distribution; set "
                "temperature > 0")
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill', or 'decode', got {role!r}")
        if role != "both" and not kv_page_size:
            raise ValueError(
                "disaggregated roles hand KV off as PAGES — role="
                f"{role!r} needs the paged cache (kv_page_size > 0)")
        if role != "both" and speculative is not None:
            raise ValueError(
                "speculative decoding does not compose with disaggregated "
                "roles yet — the verify family would have to compile on "
                "both sides, voiding the per-role census")
        if temperature != 0.0 and rng is None:
            raise ValueError(
                "temperature > 0 samples from the model — pass rng=")
        if prefix_cache_bytes < 0:
            raise ValueError(
                f"prefix_cache_bytes must be >= 0 (0 disables the cache), "
                f"got {prefix_cache_bytes}")
        if kv_page_size < 0 or kv_pages < 0:
            raise ValueError(
                f"kv_page_size/kv_pages must be >= 0 (0 = dense layout), "
                f"got {kv_page_size}/{kv_pages}")
        if kv_pages and not kv_page_size:
            raise ValueError(
                "kv_pages sizes the PAGED pool — it needs kv_page_size > 0")
        if radix_cache and not kv_page_size:
            raise ValueError(
                "radix_cache shares whole KV PAGES between requests — it "
                "needs the paged cache (kv_page_size > 0)")
        if prefill_chunk < 0:
            raise ValueError(
                f"prefill_chunk must be >= 0 (0 = whole-prompt bucketed "
                f"prefill), got {prefill_chunk}")
        if prefill_chunk:
            if not kv_page_size:
                raise ValueError(
                    "prefill_chunk runs prompts through the paged suffix-"
                    "extend program — it needs the paged cache "
                    "(kv_page_size > 0)")
            if prefill_chunk > max_len:
                raise ValueError(
                    f"prefill_chunk ({prefill_chunk}) cannot exceed max_len "
                    f"({max_len}) — a chunk is at most one slot's span")
            if prefix_cache_bytes > 0:
                raise ValueError(
                    "prefill_chunk does not compose with the dense prefix "
                    "cache (prefix_cache_bytes > 0): chunked admission "
                    "never produces the bucketed row the cache stores — "
                    "the radix trie is the prefix-sharing mechanism under "
                    "chunking (radix_cache, on by default when paged)")
        if kv_page_size:
            if max_len % kv_page_size:
                raise ValueError(
                    f"max_len ({max_len}) must be a multiple of kv_page_size "
                    f"({kv_page_size}) so every slot's virtual span is "
                    "exactly max_len (the paged==dense parity contract)")
            if getattr(model, "window", 0):
                raise ValueError(
                    "the paged cache does not compose with sliding-window "
                    "attention (model.window > 0) — the windowed decode "
                    "gathers a contiguous dense span")
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if tp > 1:
            heads = getattr(model, "heads", 0)
            heads_kv = getattr(model, "heads_kv", None) or heads
            if not heads or heads % tp or heads_kv % tp:
                raise ValueError(
                    f"tp={tp} must divide heads ({heads}) and heads_kv "
                    f"({heads_kv}): the Megatron column/row split and the "
                    "KV head-axis shard both partition WHOLE heads — a "
                    "silent replicated degrade would void the 1/tp "
                    "per-chip memory claim")
        # --- context parallelism (ISSUE 20): sequence-sharded paged KV
        # over the cp axis of a 2-D cp×tp mesh, ring-attention prefill ---
        if cp < 1:
            raise ValueError(f"cp must be >= 1, got {cp}")
        if cp > 1:
            if not kv_page_size:
                raise ValueError(
                    "cp > 1 shards the PAGED KV pool along its page axis — "
                    "context-parallel serving needs the paged cache "
                    "(kv_page_size > 0); the dense per-slot layout has no "
                    "sequence axis a chip row could own")
            if max_len % cp:
                raise ValueError(
                    f"max_len ({max_len}) must be a multiple of cp ({cp}) "
                    "so every slot's virtual span splits into equal "
                    "per-chip-row sequence shards")
            if getattr(model, "attn_fn", None) is not None:
                raise ValueError(
                    "cp > 1 installs ring attention as the model's "
                    "attn_fn for prefill — a model that already carries a "
                    "custom attn_fn would be silently clobbered; pass the "
                    "base model and let the engine compose the ring")
        # persistent XLA compilation cache (opt-in): warm processes skip
        # recompiling the engine's program family — the r04→r05 cold-start
        # regression lever.  Semantics per core/trainer.resolve_compile_
        # cache_dir ("default" = env/repo-local dir on accelerator
        # backends, an explicit path always opts in, None = off).
        if compile_cache_dir is not None:
            from distributed_tensorflow_ibm_mnist_tpu.core.trainer import (
                _enable_compile_cache,
            )

            _enable_compile_cache(compile_cache_dir)
        # --- weight-only int8 quantization (ISSUE 12) --- the model
        # clones to its Int8Dense form and the HOST param tree quantizes
        # ONCE here (per-output-channel symmetric scales, models/quant.py)
        # — BEFORE the tp mesh block below, so under tp=N the sharding
        # specs are computed over the QUANTIZED tree and the scale leaves
        # shard alongside the Megatron column/row splits (megatron_rule's
        # "scale" rule).  swap_params re-runs the same transform, so a
        # router hot-swap handing full-precision host checkpoints just
        # works.  The whole downstream program family (per-bucket prefill,
        # decode/verify windows, insert/reset, paged extend, prewarm) is
        # quant-blind: quant lives in the model fields + the param tree,
        # so the family stays one program per (site, shape-key).
        if quant not in (None, "none", "int8"):
            raise ValueError(
                f"quant must be None/'none' or 'int8' (weight-only int8 "
                f"matmuls with fused dequant), got {quant!r}")
        self.quant = "int8" if quant == "int8" else "none"
        if self.quant == "int8":
            try:
                model = model.clone(quant="int8")
            except TypeError:
                raise ValueError(
                    f"quant='int8' needs a model with a quant= field "
                    f"(the causal-LM family); {type(model).__name__} has "
                    "none") from None
            params = quantize_params_int8(params)
        # --- tensor/context-parallel mesh (tp=cp=1: every attribute None,
        # the whole path byte-identical to the single-chip engine) --- the
        # serving half of ROADMAP item 5b: weights column/row-sharded by
        # the SAME Megatron rule the training mesh uses, KV cache sharded
        # over the head axis, one psum per attention block and one per MLP
        # inserted by the partitioner at the column->row boundaries.  With
        # cp > 1 (ROADMAP item 2, ISSUE 20) the mesh grows a leading
        # ``cp`` axis: params REPLICATE over it (megatron_rule names only
        # "tp"), the paged pool shards its page axis over it
        # (kv_cache_rule cp=), and prefill runs ring attention along it.
        # Everything host-side (scheduler, pool, radix trie, drafter)
        # never sees the mesh — allocation decisions are identical at any
        # (cp, tp).
        self.tp = int(tp)
        self.cp = int(cp)
        if tp > 1 or cp > 1:
            mesh_devices = cp_devices if cp_devices is not None else tp_devices
            self._mesh = serving_mesh(tp, mesh_devices, cp=cp)
            self._kv_rule = kv_cache_rule(tp, axis="tp", cp=cp)
            self._param_shardings = mesh_shardings(
                self._mesh,
                make_param_specs(params, megatron_rule(tp, axis="tp")))
            # accepts a host or single-chip tree and re-shards wholesale —
            # the same seam swap_params reuses for hot-swap under tp
            params = jax.device_put(params, self._param_shardings)
            self._rep = jax.sharding.NamedSharding(
                self._mesh, jax.sharding.PartitionSpec())
        else:
            self._mesh = None
            self._kv_rule = None
            self._param_shardings = None
            self._rep = None
        self.model = model
        self.params = params
        self.slots = slots
        self.max_len = max_len
        self.decode_ahead = int(decode_ahead)
        self.speculative = speculative
        self.draft_len = int(draft_len) if speculative is not None else 0
        # host-side prompt-lookup drafter (serving/drafter.py): pure numpy
        # suffix match over each request's prompt + generated tokens
        self._drafter = (
            NgramDrafter(self.draft_len) if speculative == "ngram" else None)
        self.eos_id = eos_id
        self.pad_id = int(pad_id)
        self.clock = clock
        # `is None`, NOT `or`: FIFOScheduler defines __len__, so an EMPTY
        # custom scheduler is falsy and `scheduler or default` would
        # silently discard it (with its buckets/bounds/clock)
        self._prefill_chunk = int(prefill_chunk)
        if scheduler is None:
            scheduler = FIFOScheduler(
                max_len=max_len,
                buckets=buckets if buckets is not None else
                tuple(b for b in (16, 32, 64, 128) if b <= max_len) or (max_len,),
                clock=clock, tracer=tracer,
                chunked_prefill=bool(prefill_chunk))
        elif buckets is not None:
            # the compiled prefill shapes are derived from the SCHEDULER's
            # buckets (one source of truth) — an engine-level buckets= that
            # disagrees is the drift bug this check exists to catch, not a
            # preference to silently resolve
            want = tuple(sorted(set(int(b) for b in buckets)))
            if want != scheduler.buckets:
                raise ValueError(
                    f"engine buckets= {want} != scheduler buckets "
                    f"{scheduler.buckets} — the prefill programs compile at "
                    "the scheduler's shapes, so a mismatch would admit "
                    "prompts the engine never compiled for")
        # chunking lifts the bucket bound at SUBMIT (scheduler) and honors
        # it at ADMISSION (engine) — the two sides must agree, like buckets
        if getattr(scheduler, "chunked_prefill", False) and not prefill_chunk:
            raise ValueError(
                "scheduler.chunked_prefill is set but the engine has no "
                "prefill_chunk= — the scheduler would admit prompts past "
                "the largest bucket that the engine cannot prefill")
        if prefill_chunk:
            scheduler.chunked_prefill = True
        self.scheduler = scheduler
        # ONE tracer serves a request's whole span tree: the scheduler
        # opens it (submit/queue), the engine continues it (admit/decode/
        # retire) — two different tracers would strand half-open trees in
        # each, so adopt whichever side has one and reject a conflict.
        sched_tracer = getattr(self.scheduler, "tracer", None)
        if tracer is None:
            tracer = sched_tracer
        elif sched_tracer is None:
            self.scheduler.tracer = tracer
        elif sched_tracer is not tracer:
            raise ValueError(
                "engine tracer= and scheduler.tracer are different Tracer "
                "objects — a request's span tree would be split across two "
                "buffers; wire ONE tracer (either side) and both will use it")
        self._tracer = tracer  # nil-guarded at every touch, like chaos
        # the engine's host-loop track.  0 (the default "host" track) for a
        # standalone engine; a Router gives each replica its own track
        # (tracer.track("replica <i>")) so N engine loops sharing ONE
        # tracer render as N lanes instead of interleaving on lane 0.
        self._trace_tid = int(trace_tid)
        # Compile accounting is always on (the listener is process-global
        # and costs nothing between compiles): the delta between this
        # baseline and shutdown is the engine's own program family, folded
        # into ServingStats as n_compiled_programs / compile_time_s.
        self._compile = CompileTracker.install()
        self._compile0 = self._compile.snapshot()
        if tracer is not None:
            self._compile.bind(tracer)
        if self.scheduler.max_len != max_len:
            raise ValueError(
                f"scheduler.max_len ({self.scheduler.max_len}) != engine "
                f"max_len ({max_len}) — admission would pass requests the "
                "cache cannot hold")
        self.buckets = self.scheduler.buckets
        self.writer = writer
        # disaggregated serving (ISSUE 16): "both" is the monolithic
        # engine, byte-identical to every prior PR.  "prefill" runs the
        # prefill/extend program family only and diverts finished
        # landings to a handoff outbox (serving/kv_handoff.py) instead of
        # decoding; "decode" accepts handed-off pages via
        # admit_prefilled() and never compiles a prefill bucket.
        self.role = role
        self.stats = ServingStats(slots, decode_ahead=self.decode_ahead,
                                  role=role)
        # prefill-role outbox: HandoffPacket per finished prefill, drained
        # by the router's handoff pump (or the owner directly in tests)
        self._outbox: deque = deque()
        self.handoffs_out = 0   # packets packaged (prefill side)
        self.handoffs_in = 0    # packets landed (decode side)

        # --- compiled device programs (all resident, all fixed-shape) ---
        # The engine's slot cache is DONATED through every program that
        # threads it (step/insert/reset): without donation XLA must copy
        # the whole (slots, max_len) cache per call to keep the input
        # buffer alive — measured ~23% of the dim-320 step on CPU.  Safe
        # because the engine immediately reassigns self.cache and never
        # touches the donated buffer again; the PUBLIC make_decode_step
        # stays undonated (callers own their caches).
        # paged mode decodes through the page pool: the DECODE-side
        # programs (window, insert, reset, extend) switch to the paged
        # layout while the prefill program family stays byte-identical
        # (prefill never touches the cache — core/generate.make_prefill)
        self._page_size = int(kv_page_size)
        if kv_page_size:
            n_row = max_len // kv_page_size
            if not kv_pages:
                # default: dense-equivalent capacity (+ the trash page) —
                # overcommit is opt-in via an explicit smaller kv_pages.
                # Under cp the pool's page axis shards cp ways, so the
                # default rounds UP to the next multiple of cp (a few
                # bonus pages, never fewer than dense-equivalent).
                kv_pages = slots * n_row + 1
                if self.cp > 1 and kv_pages % self.cp:
                    kv_pages += self.cp - kv_pages % self.cp
            elif self.cp > 1 and kv_pages % self.cp:
                raise ValueError(
                    f"kv_pages ({kv_pages}) must be a multiple of cp "
                    f"({self.cp}): the pool's page axis shards evenly "
                    "across the cp rows, or the 1/cp per-chip memory "
                    "claim silently degrades to replicated")
            if kv_pages < n_row + 1:
                raise ValueError(
                    f"kv_pages ({kv_pages}) cannot hold one full-length "
                    f"request: need >= max_len/kv_page_size + 1 "
                    f"({n_row + 1}; page 0 is the reserved trash page)")
            decode_model = model.clone(page_size=kv_page_size)
        else:
            decode_model = model
        self._kv_pages = int(kv_pages)

        # every jitted program that RETURNS a cache pins the KV layout at
        # its output (identity at tp=1): GSPMD propagation from the
        # committed sharded inputs would usually land there anyway, but the
        # pin makes the head-axis layout an explicit program invariant —
        # every program's cache OUTPUT is layout-identical to every
        # program's cache INPUT, which is what keeps donation legal and
        # the compile census at ONE program per (site, shape-key) under tp
        if self._mesh is not None:
            def _pin(tree):
                return jax.lax.with_sharding_constraint(
                    tree, mesh_shardings(
                        self._mesh, make_param_specs(tree, self._kv_rule)))
        else:
            def _pin(tree):
                return tree
        self._pin_kv = _pin

        # cp > 1 promotes ring attention from the training path into the
        # prefill program family (ISSUE 20): the prefill model's forward
        # runs attention as a shard_map island over the mesh's cp axis
        # (sequence-sharded K/V rotating via ppermute, GQA kept grouped at
        # H_kv width) with heads still sharded over tp.  Decode-mode
        # programs never consult attn_fn (the paged gather-based decode
        # attention reads the SEQUENCE-sharded pool and the partitioner
        # derives the cross-row collectives), so only the prefill family
        # changes.  Buckets that don't divide cp fall back to the
        # numerically-equivalent unsharded path inside the returned
        # callable — still one program per (site, shape-key).
        if self.cp > 1:
            ring = make_ring_attention(
                self._mesh, batch_axis=None, seq_axis="cp",
                head_axis="tp" if tp > 1 else None,
                causal=bool(getattr(model, "causal", True)))
            try:
                prefill_model = model.clone(attn_fn=ring)
            except TypeError:
                raise ValueError(
                    f"cp={cp} needs a model with an attn_fn= field (the "
                    f"causal-LM family); {type(model).__name__} has none"
                ) from None
        else:
            prefill_model = model
        self._prefill = make_prefill(prefill_model, max_len)  # per-bucket shapes
        if kv_page_size:
            _insert_fn = make_paged_insert(kv_page_size, max_len)
            _reset_fn = paged_reset
        else:
            _insert_fn = self._insert_impl
            _reset_fn = reset_cache_slots
        self._insert = jax.jit(
            lambda cache, *a: _pin(_insert_fn(cache, *a)),
            donate_argnums=(0,))
        self._reset = jax.jit(
            lambda cache, mask: _pin(_reset_fn(cache, mask)),
            donate_argnums=(0,))

        pad_id_ = self.pad_id
        top_k_ = int(top_k)
        window_ = self.decode_ahead

        def _window_impl(params, cache, tok, active, temps, topps, topks,
                         minps, keys, pos):
            # decode_ahead fused decode+pick steps as ONE dispatch
            # (core/generate.py _sample_window_core): the host loop pays
            # per-iteration dispatch latency and ONE blocking readback per
            # WINDOW instead of per token.  temperature/top_p/top_k/min_p/
            # base-key/position ride as per-slot DATA planes, so every
            # sampling mix (greedy included) is this ONE program — the
            # census never moves across distinct (temperature, top_p,
            # top_k, min_p, seed) configs.
            cache, blk, logps, last, pos = _sample_window_core(
                decode_model, params, cache, tok, active, temps, topps,
                topks, minps, keys, pos, window_, max_len, True, pad_id_)
            return _pin(cache), blk, logps, last, pos

        self._window = jax.jit(_window_impl, donate_argnums=(1,))

        if speculative is not None:
            # the speculative sibling: ONE (slots, draft_len+1)-position
            # target forward that verifies a host-drafted chunk, computes
            # per-slot acceptance in-graph (argmax match for greedy rows,
            # rejection sampling for sampled rows), and rewinds the KV
            # cursor to the acceptance point (core/generate.py
            # _verify_sample_core).  In spec mode this REPLACES the
            # decode-ahead scan as the per-window dispatch: drafting
            # happens on the host between windows, which a fused k-step
            # scan could never pause for.
            def _verify_impl(params, cache, chunk, draft_lens, active,
                             temps, topps, topks, minps, keys, pos):
                cache, *rest = _verify_sample_core(
                    decode_model, params, cache, chunk, draft_lens, active,
                    temps, topps, topks, minps, keys, pos, max_len, pad_id_)
                return (_pin(cache), *rest)

            self._verify = jax.jit(_verify_impl, donate_argnums=(1,))
        else:
            self._verify = None

        if kv_page_size:
            # partial-prefix prefill: compute only the unshared suffix of a
            # radix-matched prompt as one decode-mode chunk over the slot's
            # block table; the first-token pick runs separately through the
            # shared first_pick program (one pick program for every
            # landing path — miss, prefix hit, radix extend)
            _extend_impl = make_paged_extend(decode_model, max_len,
                                             kv_page_size)

            def _extend_row(params, cache, slot, bt_row, suffix,
                            start, suffix_len):
                cache, last = _extend_impl(params, cache, slot, bt_row,
                                           suffix, start, suffix_len)
                return _pin(cache), last

            self._extend = jax.jit(_extend_row, donate_argnums=(1,))

            # disaggregated handoff programs (serving/kv_handoff.py): one
            # fixed-shape page gather (read-only — the source pool stays
            # live until the transfer commits) and the destination-side
            # per-page scatter + no-forward block-table install, both with
            # the cache donated like every other cache-threading program
            self._page_gather = jax.jit(gather_page)
            self._page_write = jax.jit(
                lambda cache, payload, pid: _pin(
                    page_write(cache, payload, pid)),
                donate_argnums=(0,))
            self._bt_install = jax.jit(
                lambda cache, bt_row, slot, cur: _pin(
                    bt_install(cache, bt_row, slot, cur)),
                donate_argnums=(0,))

        def _prefill_row(params, prompt, lens):
            # the B=1 row cache is pinned head-sharded too: the insert
            # program's row input then always arrives in ONE layout,
            # whether it came from a fresh prefill, the prefix cache, or
            # prewarm's zero row.  Returns the (1, V) last-position logits
            # UNPICKED — the prefix cache stores them (never a sampled
            # token) and every admission picks through first_pick.
            cache, last = self._prefill(params, prompt, lens)
            return _pin(cache), last

        self._prefill_row = jax.jit(_prefill_row)
        # per-request sampling defaults: the engine-level knobs cover every
        # request submitted without SamplingParams.  The default base key
        # comes from the rng= knob's key data (host bytes — greedy engines
        # never touch it).
        self._default_temp = float(temperature)
        self._default_topp = float(top_p)
        self._top_k = top_k_
        self._default_minp = float(min_p)
        if rng is None:
            self._default_key = base_key(0)
        else:
            try:
                kd = jax.random.key_data(rng)
            except TypeError:
                kd = rng
            self._default_key = np.asarray(kd, np.uint32).reshape(-1)[-2:]

        # --- mutable engine state ---
        # cache zeros materialize DIRECTLY in their final layout: under tp
        # the shape probe runs first, the head-axis sharding tree is built
        # from it, and allocation jits with out_shardings — a pool bigger
        # than one chip's memory never transits a single device
        _shapes = (
            paged_cache_shapes(model, params, slots, max_len, kv_page_size,
                               kv_pages) if kv_page_size
            else cache_shapes(model, params, slots, max_len))
        self._cache_shardings = (
            None if self._mesh is None else mesh_shardings(
                self._mesh, make_param_specs(_shapes, self._kv_rule)))
        if kv_page_size:
            self.cache = _zeros_like_shapes(_shapes, self._cache_shardings)
            self._pool = KVPagePool(kv_pages, kv_page_size)
            self._page_bytes = pool_page_bytes(self.cache)
            self._radix = (
                RadixCache(kv_page_size)
                if (radix_cache is None or radix_cache) else None)
            # per-slot allocation record: [private page ids, held radix
            # nodes] — released at retirement, DEFERRED until the slot's
            # reset dispatch (its stale block table references the pages
            # until then; see _release_slot_alloc)
            self._slot_alloc: list[list | None] = [None] * slots
            self._deferred_free: list[list] = []
        else:
            self.cache = _zeros_like_shapes(_shapes, self._cache_shardings)
            self._pool = None
            self._radix = None
            self._slot_alloc = [None] * slots
            self._deferred_free = []
        self._slot_req: list[Request | None] = [None] * slots
        # chunked-prefill progress per slot (ISSUE 14): None for slots in
        # normal decode; a dict {"done", "total", "bt", "bt_dev", "last",
        # "t0"} while the slot is PREFILLING — occupied (its pages are
        # allocated, its request is resident) but EXCLUDED from the decode
        # window's active mask until the last chunk lands and the first
        # token is picked
        self._slot_prefill: list[dict | None] = [None] * slots
        self._slot_tok = np.full((slots,), self.pad_id, np.int32)
        self._tok_dev = None  # device copy of _slot_tok; None = stale
        self._active_dev = None  # device (slots,) bool mask; None = stale
        # per-slot sampling planes (host mirrors): temperature/top-p as
        # (slots,) float32, the Threefry base key as (slots, 2) uint32.
        # Uploaded once per occupancy change (_planes_dev, invalidated at
        # admission like _tok_dev/_active_dev — a retired slot's stale
        # plane rows are masked by `active`, so no invalidation there).
        self._slot_temp = np.full((slots,), self._default_temp, np.float32)
        self._slot_topp = np.full((slots,), self._default_topp, np.float32)
        self._slot_topk = np.full((slots,), self._top_k, np.int32)
        self._slot_minp = np.full((slots,), self._default_minp, np.float32)
        self._slot_key = np.tile(self._default_key, (slots, 1))
        # (temps, topps, topks, minps, keys) on device; None = stale
        self._planes_dev = None
        # device (slots,) int32 count of already-generated tokens per slot
        # — the PRNG position plane.  Plain windows return the advanced
        # plane (carried like _tok_dev); spec windows re-upload fresh each
        # dispatch (acceptance makes the advance data-dependent).
        self._pos_dev = None
        # prefill-overlap parking lot: (req, (row_cache, logits, hit))
        # tuples prefilled against an in-flight window, awaiting a slot
        self._pending: deque[tuple] = deque()
        # ids of parked requests whose landing STALLED on a dry page pool
        # (overcommit): close() must FAIL these terminally (engine_fault —
        # the engine gave up on work it had accepted) instead of the
        # plain "cancelled" an overlap-prefilled pending gets
        self._stalled_ids: set[int] = set()
        self._prefix = (
            PrefixCache(prefix_cache_bytes) if prefix_cache_bytes > 0
            else None)
        self.completed: list[Request] = []
        # --- failure isolation / shutdown state ---
        self.stall_timeout_s = stall_timeout_s
        self._chaos = chaos  # utils/chaos.FaultInjector | None (see module doc)
        self._last_progress_t: float | None = None  # watchdog anchor
        # the anchor above resets on a fatal fault (retry-after-fatal must
        # restart the stall countdown); this stamp never does — it is the
        # "when did this engine last make progress" heartbeat the health
        # sampler reports, frozen at its final value after a kill
        self._last_progress_ever: float | None = None
        # utils/telemetry.Telemetry | None — same nil-guard zero-cost-off
        # contract as _chaos/_tracer.  The engine registers a vitals
        # source under its trace track id (a Router's replicas get unique
        # tids, so a respawn REPLACES its predecessor's source) and calls
        # maybe_sample once per step — a clock read between samples.
        self._telemetry = telemetry
        if telemetry is not None:
            telemetry.register_source(f"engine{trace_tid}",
                                      self._telemetry_vitals)
        self._draining = False  # drain(): serve what's accepted, admit no more
        self._closed = False
        # per-chip footprint stamped up front: even a run that serves zero
        # requests reports what the config costs one chip (ISSUE 10)
        self._stamp_memory()

    def _telemetry_vitals(self) -> dict:
        """Health-sampler vitals (utils/telemetry): queue/slot/pool state
        plus the stats counters, all O(1) reads — safe every interval."""
        v = self.stats.vitals()
        v.update(
            queue_depth=len(self.scheduler),
            parked=len(self._pending),
            overcommit_stalled=len(self._stalled_ids),
            occupied_slots=self.occupied,
            slots=self.slots,
            draining=self._draining,
            closed=self._closed,
            last_progress_t=self._last_progress_t,
        )
        return v

    def _stamp_memory(self) -> None:
        """(Re-)stamp the per-chip memory figures into ``self.stats`` —
        at construction, and again at every drain/close emit point so a
        caller that swapped in a fresh ServingStats still reports them."""
        self.stats.memory(
            tp=self.tp, kv_bytes_per_chip=self.kv_bytes_per_chip(),
            weight_bytes_per_chip=self.weight_bytes_per_chip(),
            quant=self.quant, cp=self.cp)

    def _site(self, name: str) -> str:
        """Path-qualified compile-site name (ISSUE 20 satellite): cp=1
        engines keep every historical site name byte-identical; cp>1
        qualifies each site with the layout — ``prefill[b16]`` becomes
        ``prefill[b16,cp2]``, ``first_pick`` becomes ``first_pick[cp2]``
        — so a census diff between layouts attributes every compile to
        its (site, shape-key, LAYOUT) and prewarm/serving keys always
        agree (both come through this helper)."""
        if self.cp == 1:
            return name
        if name.endswith("]"):
            return f"{name[:-1]},cp{self.cp}]"
        return f"{name}[cp{self.cp}]"

    def _dev(self, x):
        """Host upload for per-window device inputs.  Single-chip: a plain
        uncommitted transfer (byte-identical to the pre-tp engine).  Under
        tp: COMMITTED replicated-on-mesh, so the first dispatch (prewarm)
        and every serving dispatch present jit the SAME input shardings —
        one program per site, never a layout-keyed recompile."""
        x = jnp.asarray(x)
        return x if self._rep is None else jax.device_put(x, self._rep)

    @property
    def _chip0(self):
        """The accounting chip: per-chip byte figures are measured on one
        fixed mesh device (they are equal across the mesh by symmetry)."""
        return None if self._mesh is None else self._mesh.devices.flat[0]

    def kv_bytes_per_chip(self) -> int:
        """KV-cache bytes resident on ONE chip — the whole cache at
        tp=cp=1; the head-axis shard under tp (1/tp of the slab bytes,
        the ISSUE 10 memory claim) and additionally the page-axis shard
        under cp (1/(tp*cp) of the slab — the ISSUE 20 claim), plus the
        replicated block tables/cursors (the documented tax)."""
        return per_chip_bytes(self.cache, self._chip0)

    def weight_bytes_per_chip(self) -> int:
        """Decode-weight bytes resident on ONE chip (Megatron column/row
        shards under tp; replicated leaves count whole)."""
        return per_chip_bytes(self.params, self._chip0)

    @staticmethod
    def _insert_impl(cache, row_cache, slot):
        """Write row 0 of a B=1 prefill cache into ``slot`` of the engine
        cache (every leaf is (B, ...)-leading, so one dynamic_update_slice
        per leaf; ``slot`` is traced — one compile covers every slot)."""
        return jax.tree.map(
            lambda full, row: jax.lax.dynamic_update_slice(
                full, row.astype(full.dtype),
                (slot,) + (0,) * (full.ndim - 1)),
            cache, row_cache)

    @classmethod
    def from_trainer(cls, trainer, *, slots: int, max_len: int, **kw
                     ) -> "InferenceEngine":
        """Build an engine from a trained :class:`~...core.trainer.Trainer`
        run: the same clean single-device decode model + device-resident
        cast params ``Trainer.generate`` uses (training islands dropped,
        pp-stacked params unstacked)."""
        from distributed_tensorflow_ibm_mnist_tpu.models import get_model, model_accepts

        if not model_accepts(trainer.config.model, "pos") or not trainer.causal:
            raise ValueError(
                "InferenceEngine needs a causally-trained causal-LM-family "
                f"run; got {trainer.config.model!r}")
        clean_kwargs = {
            k: v for k, v in trainer.config.model_kwargs.items()
            if k not in ("attn_fn", "moe_fn", "pipeline_fn", "pp_stages")
        }
        model = get_model(trainer.config.model,
                          num_classes=trainer.num_classes, **clean_kwargs)
        kw.setdefault("writer", trainer.writer)
        # inherit the run's persistent-compile-cache choice: the serving
        # program family is exactly what a warm cache saves (satellite of
        # ISSUE 7 — the r04→r05 cold-compile regression)
        kw.setdefault("compile_cache_dir", trainer.config.compile_cache_dir)
        return cls(model, trainer._decode_params(), slots=slots,
                   max_len=max_len, **kw)

    # ------------------------------------------------------------------
    # request lifecycle

    def submit(self, prompt, max_new: int, deadline_s: float | None = None,
               callback: Callable | None = None,
               ttft_slo_s: float | None = None,
               tpot_slo_s: float | None = None,
               sampling: SamplingParams | None = None) -> Request:
        """Enqueue a request (see :meth:`FIFOScheduler.submit` for the
        admission rules; raises ``QueueFull`` under backpressure).
        ``callback(request, token)`` streams every generated token; if it
        raises, THIS request fails (terminal ``failed`` state) and the
        engine keeps serving the rest.  ``ttft_slo_s``/``tpot_slo_s``
        declare latency SLO targets the engine judges at first token and
        retirement (never cancels — accounting only; serving/stats.py).
        ``sampling`` is the per-request :class:`SamplingParams`
        (temperature/top_p/seed; None = the engine's construction
        defaults) — the request's token stream is a pure function of its
        seed.  Refused after :meth:`drain` / :meth:`close`."""
        if self._closed or self._draining:
            raise RuntimeError(
                "engine is " + ("closed" if self._closed else "draining")
                + " — no new requests")
        if self.role == "decode":
            raise RuntimeError(
                "decode-role engine takes no direct submissions — its work "
                "arrives prefilled via admit_prefilled (route admissions "
                "to a prefill/both replica; serving/router.py does)")
        return self.scheduler.submit(prompt, max_new, deadline_s=deadline_s,
                                     callback=callback,
                                     ttft_slo_s=ttft_slo_s,
                                     tpot_slo_s=tpot_slo_s,
                                     sampling=sampling)

    @property
    def occupied(self) -> int:
        return sum(r is not None for r in self._slot_req)

    @property
    def _decoding(self) -> int:
        """Slots holding a request that is past prefill — PREFILLING
        slots are occupied (pages held, request resident) but excluded
        from the decode window until their last chunk lands."""
        return sum(r is not None and p is None
                   for r, p in zip(self._slot_req, self._slot_prefill))

    @property
    def has_work(self) -> bool:
        return (self.occupied > 0 or len(self.scheduler) > 0
                or len(self._pending) > 0)

    @property
    def heartbeat_t(self) -> float | None:
        """Monotonic timestamp of the engine's last real progress (a token
        produced), or None before the first.  The EXTERNAL liveness signal:
        ``stall_timeout_s`` is judged inside :meth:`step`, so a pump thread
        wedged mid-step can never trip it — the daemon's watchdog thread
        reads this instead and declares the replica dead when it freezes
        while work is in flight (serving/daemon.py, serving/replica.py)."""
        return self._last_progress_ever

    def _req_sampling(self, req: Request):
        """``(temperature, top_p, top_k, min_p, base_key)`` resolved for
        ``req`` — its own :class:`SamplingParams`, or the engine's
        construction-time defaults for requests submitted without one."""
        s = req.sampling
        if s is None:
            return (self._default_temp, self._default_topp, self._top_k,
                    self._default_minp, self._default_key)
        return (float(s.temperature), float(s.top_p), int(s.top_k),
                float(s.min_p), s.key())

    def _first_pick(self, req: Request, logits):
        """Pick ``req``'s FIRST token (generated index 0) from the
        prefill's (1, V) last-position logits through the module-level
        shared ``first_pick`` program (serving/sampling.py) — the same
        program for a fresh prefill, a prefix-cache hit, and a paged
        radix-extend landing, so hit/miss first tokens are bit-identical.
        Returns ``(token, logprob)`` as host scalars."""
        temp, topp, topk, minp, key = self._req_sampling(req)
        with self._compile.site(self._site("first_pick")):
            tok, logp = first_pick(
                logits, self._dev(np.array([temp], np.float32)),
                self._dev(np.array([topp], np.float32)),
                self._dev(np.array([topk], np.int32)),
                self._dev(np.array([minp], np.float32)),
                self._dev(key[None, :].astype(np.uint32)),
                self._dev(np.zeros((1,), np.int32)))
        return int(tok[0]), float(logp[0])

    # ------------------------------------------------------------------
    # tracing bookkeeping (every helper is a no-op without a tracer —
    # the same zero-cost-when-unwired contract as the chaos hooks)

    def _tr_phase(self, req: Request, name: str, **args) -> None:
        """Advance ``req`` to its next lifecycle phase: close the open
        phase span (queue/admit/decode) and open ``name`` in its place,
        parented under the request's root span."""
        if self._tracer is None or req.trace is None:
            return
        t = req.trace
        if t.get("phase") is not None:
            self._tracer.end(t["phase"])
        t["phase"] = self._tracer.begin(name, cat="serving", parent=t["id"],
                                        tid=t["tid"], **args)

    def _tr_instant(self, req: Request, name: str, **args) -> None:
        """A correlated event ON this request's tree (fault injections,
        cache hits, first token)."""
        if self._tracer is None or req.trace is None:
            return
        self._tracer.instant(name, cat="serving", parent=req.trace["id"],
                             tid=req.trace["tid"], **args)

    def _tr_close(self, req: Request, **args) -> None:
        """Terminal: close the open phase (if any) and the request root."""
        if self._tracer is None or req.trace is None:
            return
        t = req.trace
        if t.get("phase") is not None:
            self._tracer.end(t["phase"])
        self._tracer.end(t["id"], **args)
        req.trace = None

    def _retire(self, slot: int, status: str, now: float,
                waste: int = 0) -> None:
        # the freed slot's stale token keeps being fed to the decode step
        # (its output is ignored and its cache row is reset), so _slot_tok
        # needs no write here — which keeps _tok_dev valid across retires
        req = self._slot_req[slot]
        req.status = status
        req.finish_t = now
        # TPOT SLO verdict at retirement: mean seconds per output token
        # AFTER the first (the decode steady-state the SLO names).  A
        # single-token request has no inter-token interval — trivially ok.
        if req.tpot_slo_s is not None and status == "done":
            n = len(req.generated)
            if req.first_token_t is not None and n > 1:
                req.slo_tpot_ok = (
                    (now - req.first_token_t) / (n - 1) <= req.tpot_slo_s)
            else:
                req.slo_tpot_ok = True
        if self._telemetry is not None and status == "done":
            ex = (req.trace_ctx.trace_id
                  if req.trace_ctx is not None else None)
            self._telemetry.observe("latency_s", now - req.submit_t,
                                    exemplar=ex)
            n = len(req.generated)
            if req.first_token_t is not None and n > 1:
                self._telemetry.observe(
                    "tpot_s", (now - req.first_token_t) / (n - 1),
                    exemplar=ex)
        self._slot_req[slot] = None
        self._slot_prefill[slot] = None  # a PREFILLING slot can be swept
        self._release_slot_alloc(slot)  # paged: queue its pages for release
        self._active_dev = None  # occupancy changed; next window re-freezes
        self._tr_close(req, status=status, slot=slot, waste_steps=waste,
                       n_generated=len(req.generated))
        self.completed.append(req)
        self.stats.add(req)

    def _fail(self, req: Request, exc: BaseException, now: float) -> None:
        """Move ``req`` to the terminal FAILED state (isolated casualty)."""
        req.status = "failed"
        req.error = f"{type(exc).__name__}: {exc}"
        req.finish_t = now
        if self._tracer is not None and req.trace is not None:
            from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import ChaosFault

            if isinstance(exc, ChaosFault):
                # the injected fault lands ON the request it hit — the
                # site's event index correlates it back to the FaultPlan
                self._tr_instant(req, "chaos_fault", site=exc.site,
                                 fault_kind=exc.kind, event=exc.event)
            self._tr_close(req, status="failed", error=req.error)
        self.completed.append(req)
        self.stats.add(req)

    def _notify(self, req: Request, tok: int) -> None:
        """Deliver one token to the request's streaming callback.  Raises
        propagate to the caller, which fails THIS request only."""
        if self._chaos is not None:
            from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import ChaosFault

            self._chaos.raise_if_fired("serving-callback", ChaosFault)
        if req.callback is not None:
            req.callback(req, tok)

    def _prefill_request(self, req: Request):
        """The per-request half of admission: one ``serving-admit`` chaos
        event, a prefix-cache lookup, and (on a miss) the bucketed B=1
        prefill dispatch.  Returns ``(row_cache, logits, cache_hit)``;
        exceptions are the REQUEST's failure and propagate to the caller
        (inline admit or overlap dispatch), which fails it in isolation.
        The chaos event fires once per admission attempt, hit or miss, so
        per-site event indices are independent of the prefix cache and of
        WHEN (inline vs overlapped) the prefill ran."""
        if self._chaos is not None:
            from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import ChaosFault

            self._chaos.raise_if_fired("serving-admit", ChaosFault)
        if self._prefix is not None:
            hit = self._prefix.get(req.prefix_key)
            self.stats.prefix(hit is not None)
            if hit is not None:
                self._tr_instant(req, "prefix_cache_hit", bucket=req.bucket)
                return hit[0], hit[1], True
        if self._radix is not None and self._usable_radix_tokens(req) > 0:
            # partial-prefix hit: skip the prefill dispatch NOW; the
            # suffix-extend program runs at landing against the trie/pool
            # state of that moment (the match is re-taken there — eviction
            # may shrink it while the request is parked)
            return _RADIX_PREFILL, None, False
        return (*self._dense_prefill(req), False)

    def _dense_prefill(self, req: Request):
        """The bucketed B=1 prefill dispatch — the dense tail of
        :meth:`_prefill_request`, also the paged landing's fallback when a
        parked radix match was evicted before landing.  Returns
        ``(row_cache, logits)``: the first-token pick happens at LANDING
        through the shared ``first_pick`` program, never here — the
        logits are the deterministic product the prefix cache may store."""
        padded = np.full((1, req.bucket), self.pad_id, np.int32)
        padded[0, : req.tokens.size] = req.tokens
        span = (self._tracer.begin("prefill", cat="serving",
                                   parent=req.trace["phase"] or req.trace["id"],
                                   tid=req.trace["tid"], bucket=req.bucket)
                if self._tracer is not None and req.trace is not None else None)
        t0 = self.clock()
        try:
            with self._compile.site(self._site(f"prefill[b{req.bucket}]")):
                row_cache, logits = self._prefill_row(
                    self.params, jnp.asarray(padded),
                    jnp.asarray([req.tokens.size], jnp.int32))
        finally:
            if span is not None:
                self._tracer.end(span)  # a poisoned prefill still closes it
                if self.cp > 1 and req.bucket % self.cp == 0:
                    self._emit_ring_hops(req, span, t0, self.clock())
        return row_cache, logits

    def _emit_ring_hops(self, req: Request, parent_span, t0: float,
                        t1: float) -> None:
        """Per-hop ``ring_hop`` child spans under a cp>1 prefill span
        (ISSUE 20 satellite).  The XLA dispatch is one fused program — the
        cp-1 ppermute hops have no host-visible boundaries — so each hop
        is rendered as a uniform slice of the measured dispatch window,
        annotated with the ANALYTIC per-hop comm bytes (utils/flops.
        ring_hop_bytes at the grouped H_kv width): honest structure +
        honest byte accounting, no fake per-hop timings claimed beyond
        the uniform-slice convention the span args spell out."""
        if self._tracer is None or req.trace is None:
            return
        from distributed_tensorflow_ibm_mnist_tpu.utils.flops import (
            ring_hop_bytes,
        )

        m = self.model
        heads_kv = getattr(m, "heads_kv", None) or getattr(m, "heads", 1)
        head_dim = getattr(m, "dim", 0) // max(getattr(m, "heads", 1), 1)
        hop_bytes = ring_hop_bytes(
            req.bucket // self.cp, heads_kv, head_dim,
            dtype_bytes=jnp.dtype(getattr(m, "dtype", jnp.float32)).itemsize,
            depth=getattr(m, "depth", 1))
        n_hops = self.cp - 1
        dt = max(t1 - t0, 0.0) / max(n_hops, 1)
        for h in range(n_hops):
            self._tracer.complete(
                "ring_hop", t0 + h * dt, t0 + (h + 1) * dt, cat="serving",
                parent=parent_span, tid=req.trace["tid"], hop=h,
                comm_bytes=hop_bytes, timing="uniform-slice")

    def _usable_radix_tokens(self, req: Request, matched: int | None = None
                             ) -> int:
        """Whole-page radix match length usable for ``req``, capped so at
        least ONE prompt token remains for the suffix (the extend program
        needs a real position to pick the first token from)."""
        if matched is None:
            _, matched = self._radix.match(req.tokens)
        ps = self._page_size
        return min(matched, ((int(req.tokens.size) - 1) // ps) * ps)

    def _alloc_pages(self, n: int) -> list[int] | None:
        """``n`` pool pages, evicting unreferenced radix leaves to cover a
        shortfall; None = genuinely dry (every page is held by a live slot
        or a referenced prefix) — an admission STALL, never a failure."""
        pages = self._pool.alloc(n)
        if pages is None and self._radix is not None:
            self._radix.evict(n - self._pool.free_count,
                              lambda p: self._pool.free([p]))
            pages = self._pool.alloc(n)
        return pages

    def _release_slot_alloc(self, slot: int) -> None:
        """Queue ``slot``'s page allocation for release.  DEFERRED, not
        immediate: the slot's stale block table still references the pages
        until its reset dispatch lands, so the free (and any radix release
        that makes nodes evictable) only happens at _flush_freed_pages,
        called after the step's reset went out."""
        alloc = self._slot_alloc[slot]
        if alloc is not None:
            self._slot_alloc[slot] = None
            self._deferred_free.append(alloc)

    def _flush_freed_pages(self) -> None:
        """Apply deferred page frees / radix releases (see above)."""
        if self._pool is None or not self._deferred_free:
            return
        for pages, nodes in self._deferred_free:
            if pages:
                self._pool.free(pages)
            if nodes:
                self._radix.release(nodes)
        self._deferred_free.clear()

    def _paged_land(self, req: Request, slot: int, prefilled: tuple):
        """Land ``req`` in ``slot`` on the PAGED layout: allocate its page
        span, install the block table, and either scatter the dense prefill
        row (full prefill / prefix-cache hit) or run the suffix-extend
        program over the radix-shared prefix.  Returns ``(first_token,
        first_logprob, cache_hit)`` or None when the pool cannot cover the
        request right now (the caller re-parks it — admission stall, not
        failure)."""
        row_cache, logits, cache_hit = prefilled
        ps = self._page_size
        n_tok = int(req.tokens.size)
        path: list = []
        m_tok = 0
        if row_cache is _RADIX_PREFILL:
            # re-match at landing: the parked match may have been evicted
            # (or grown) while the request waited for a slot
            path, matched = self._radix.match(req.tokens)
            m_tok = self._usable_radix_tokens(req, matched)
            path = path[: m_tok // ps]
            if not path:
                # evaporated: plain dense prefill, WITHOUT re-firing the
                # serving-admit chaos event (it fired at _prefill_request —
                # one event per admission attempt, paging-invariant)
                row_cache, logits = self._dense_prefill(req)
                m_tok = 0
        m_blocks = len(path)
        if m_blocks:
            # pin the matched pages before any allocation could evict them
            self._radix.acquire(path)
        total = pages_needed(n_tok + req.max_new, ps)
        private = self._alloc_pages(total - m_blocks)
        if private is None:
            if m_blocks:
                self._radix.release(path)
            return None
        # record the allocation BEFORE any dispatch: if the extend/insert
        # (or the first-token callback downstream) raises, the failure
        # path's _release_slot_alloc reclaims these pages
        self._slot_alloc[slot] = [list(private), list(path)]
        bt_row = np.zeros((self.max_len // ps,), np.int32)  # rest = TRASH
        for j, node in enumerate(path):
            bt_row[j] = node.page
        for j, page in enumerate(private):
            bt_row[m_blocks + j] = page
        bt_dev = self._dev(bt_row)
        if m_blocks:
            suffix = req.tokens[m_tok:]
            sb = self.scheduler.bucket_for(suffix.size)
            padded = np.full((1, sb), self.pad_id, np.int32)
            padded[0, : suffix.size] = suffix
            with self._compile.site(self._site(f"extend[b{sb}]")):
                self.cache, ext_logits = self._extend(
                    self.params, self.cache, jnp.asarray(slot, jnp.int32),
                    bt_dev, jnp.asarray(padded),
                    jnp.asarray(m_tok, jnp.int32),
                    jnp.asarray(suffix.size, jnp.int32))
            if self.role == "prefill":
                # disaggregated (ISSUE 16): stop where the pick would
                # run — the logits row travels in the packet and the
                # DECODE side picks through the same shared program
                first, first_logp, land_logits = _HANDOFF, None, ext_logits
            else:
                first, first_logp = self._first_pick(req, ext_logits)
            self.stats.radix(True, tokens=m_tok)
            self._radix.record(True, tokens=m_tok)
            req.radix_tokens = m_tok
            self._tr_instant(req, "radix_hit", blocks=m_blocks, tokens=m_tok)
        else:
            with self._compile.site(self._site("slot_insert")):
                self.cache = self._insert(self.cache, row_cache, bt_dev,
                                          jnp.asarray(slot, jnp.int32))
            if self.role == "prefill":
                first, first_logp, land_logits = _HANDOFF, None, logits
            else:
                first, first_logp = self._first_pick(req, logits)
            if self._radix is not None:
                self.stats.radix(False)
                self._radix.record(False)
            if self._prefix is not None and not cache_hit:
                # store the DETERMINISTIC prefill products only (row +
                # logits), never the picked token — sampling safety
                self._prefix.put(req.prefix_key, row_cache, logits)
        req.pages = total
        if self._radix is not None:
            # donate the freshly computed FULL prompt blocks below the
            # match: they move from this request's private allocation into
            # the trie (held — ref stays up until this slot retires)
            donate = {j: int(bt_row[j])
                      for j in range(m_blocks, n_tok // ps)}
            if donate:
                priv, nodes = self._slot_alloc[slot]
                held, _kept = self._radix.insert(
                    req.tokens, m_blocks, donate, path)
                for node in held:
                    priv.remove(node.page)
                    nodes.append(node)
        if first is _HANDOFF:
            # package AFTER the donation, so the source trie shares this
            # prompt's blocks with later prefills (and with the re-prefill
            # a dead transfer falls back to); exceptions propagate to
            # _admit's failure path, which reclaims the still-slot-held
            # allocation
            self._handoff_package(req, slot, land_logits, bt_row)
        return first, first_logp, cache_hit

    def _admit(self, req: Request, slot: int, now: float,
               prefilled: tuple | None = None) -> bool:
        """Prefill ``req`` at its bucket shape and land it in ``slot``
        (``prefilled`` carries an overlap-dispatched prefill to land
        instead of prefilling inline).

        Failure-isolated: any exception from the request's OWN processing
        (prefill, first-token callback, injected ``serving-admit`` poison)
        fails the request and leaves the slot free.  Returns True when the
        slot's cache row needs a reset the caller must perform unless a
        later admit overwrites it: a failure AFTER the insert landed, or a
        request that retired at admission (its prefilled row would
        otherwise linger under an idle slot).
        """
        if self._prefill_chunk:
            # chunked admission (ISSUE 14): allocate the page span and
            # park the slot in the PREFILLING state — chunks run one per
            # engine iteration, never a whole-prompt prefill here
            return self._chunk_admit(req, slot, now,
                                     retry=prefilled is not None)
        inserted = False
        # inline admissions open their "admit" phase here; overlap-prefilled
        # requests opened it back at pop (in _overlap_prefill), so their
        # phase also covers the prefill and the parked wait for a slot
        if req.trace is not None and req.trace.get("phase") is None:
            self._tr_phase(req, "admit", slot=slot)
        try:
            if prefilled is None:
                prefilled = self._prefill_request(req)
            if self._pool is not None:
                landed = self._paged_land(req, slot, prefilled)
                if landed is None:
                    # pool momentarily full — NOT a failure: the caller
                    # re-parks the (already chaos'd, maybe prefilled)
                    # request and retries once decode frees pages
                    return ("stall", prefilled)
                first, first_logp, cache_hit = landed
                inserted = True
                if first is _HANDOFF:
                    # prefill role: the landing went to the outbox, the
                    # slot is free again (pages moved to the packet's
                    # hold) — True asks the caller to reset the row's
                    # block table unless a later admit overwrites it
                    return True
            else:
                row_cache, logits, cache_hit = prefilled
                with self._compile.site(self._site("slot_insert")):
                    self.cache = self._insert(
                        self.cache, row_cache, jnp.asarray(slot, jnp.int32))
                inserted = True
                # hit or miss, the pick runs HERE, per request, through the
                # one shared first_pick program — what makes the prefix
                # cache sampling-safe (it stores logits, never a token)
                first, first_logp = self._first_pick(req, logits)
                if self._prefix is not None and not cache_hit:
                    # insert does not donate row_cache, so the row stays
                    # valid to replay for every later identical prompt
                    self._prefix.put(req.prefix_key, row_cache, logits)
            req.admit_t = now
            req.generated.append(first)
            req.logprobs.append(first_logp)
            req.first_token_t = self.clock()  # TTFT: first token ON THE HOST
            # first token = progress: stamp the heartbeat here too, so an
            # engine killed later in this same step (before the end-of-step
            # stamp) still freezes at a real progress time, not None
            self._last_progress_ever = req.first_token_t
            # TTFT SLO verdict lands HERE, at the judgment point itself —
            # queue wait is inside TTFT by construction (stats docstring)
            if req.ttft_slo_s is not None:
                req.slo_ttft_ok = (
                    req.first_token_t - req.submit_t <= req.ttft_slo_s)
            if self._telemetry is not None:
                self._telemetry.observe(
                    "ttft_s", req.first_token_t - req.submit_t,
                    exemplar=(req.trace_ctx.trace_id
                              if req.trace_ctx is not None else None))
                # step()'s `produced` counts decode-window tokens only;
                # the admit-time first token lands here so the registry
                # counter matches stats' tokens_generated
                self._telemetry.inc("tokens_generated")
            req.status = "running"
            self._tr_instant(req, "first_token", slot=slot,
                             cache_hit=cache_hit)
            self._notify(req, first)
        except Exception as e:
            # a paged landing that allocated before raising gives its
            # pages back (deferred past the caller's reset dispatch)
            self._release_slot_alloc(slot)
            self._fail(req, e, self.clock())
            return inserted
        self._slot_req[slot] = req
        self._slot_tok[slot] = first
        temp, topp, topk, minp, key = self._req_sampling(req)
        self._slot_temp[slot] = temp
        self._slot_topp[slot] = topp
        self._slot_topk[slot] = topk
        self._slot_minp[slot] = minp
        self._slot_key[slot] = key
        self._tok_dev = None  # host mirror changed; re-upload before decode
        self._active_dev = None
        self._planes_dev = None  # sampling planes changed with the slot
        self._pos_dev = None  # rebuilt from host generated counts
        self._tr_phase(req, "decode", slot=slot)
        if self._done_reason(req) is not None:
            self._retire(slot, self._done_reason(req), self.clock())
            return True  # the landed row belongs to no live request now
        return False

    def _done_reason(self, req: Request) -> str | None:
        if self.eos_id is not None and req.generated and req.generated[-1] == self.eos_id:
            return "done"
        if len(req.generated) >= req.max_new:
            return "done"
        return None

    # ------------------------------------------------------------------
    # chunked prefill (ISSUE 14): admission holds a slot in the
    # PREFILLING state while fixed-size prompt chunks run one per engine
    # iteration through the paged suffix-extend program — the decode
    # latency cost of admitting ANY prompt is bounded by one chunk

    def _chunk_admit(self, req: Request, slot: int, now: float,
                     retry: bool = False):
        """Admit ``req`` into ``slot`` in the PREFILLING state: fire the
        one ``serving-admit`` chaos event (skipped on a stall ``retry`` —
        one event per admission ATTEMPT, exactly like the whole-prompt
        path), take the radix match (a partial hit resumes chunking at
        the divergence page), allocate the full page span, and build the
        host-side chunk record.  No chunk is dispatched here — the first
        runs at the next :meth:`_chunk_tick`.  Returns the same protocol
        as :meth:`_admit`: ``("stall", _CHUNK_STALL)`` when the pool is
        momentarily dry (caller re-parks), True/False for
        needs-reset-without-occupancy, with ``self._slot_req[slot]`` set
        on success.

        The slot's block table is NOT installed here: a reset pending
        from the previous tenant stays pending (garbage decode writes
        land in the trash page), and every chunk's extend call installs
        the real block table itself before writing."""
        if req.trace is not None and req.trace.get("phase") is None:
            self._tr_phase(req, "admit", slot=slot, chunked=True)
        try:
            if not retry and self._chaos is not None:
                from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
                    ChaosFault,
                )

                self._chaos.raise_if_fired("serving-admit", ChaosFault)
        except Exception as e:
            self._fail(req, e, self.clock())
            return False
        ps = self._page_size
        n_tok = int(req.tokens.size)
        path: list = []
        m_tok = 0
        if self._radix is not None:
            path, matched = self._radix.match(req.tokens)
            m_tok = self._usable_radix_tokens(req, matched)
            path = path[: m_tok // ps]
            m_tok = len(path) * ps
        m_blocks = len(path)
        if m_blocks:
            # pin the matched pages before any allocation could evict them
            self._radix.acquire(path)
        total = pages_needed(n_tok + req.max_new, ps)
        private = self._alloc_pages(total - m_blocks)
        if private is None:
            if m_blocks:
                self._radix.release(path)
            return ("stall", _CHUNK_STALL)
        self._slot_alloc[slot] = [list(private), list(path)]
        bt_row = np.zeros((self.max_len // ps,), np.int32)  # rest = TRASH
        for j, node in enumerate(path):
            bt_row[j] = node.page
        for j, page in enumerate(private):
            bt_row[m_blocks + j] = page
        req.pages = total
        req.admit_t = now
        req.status = "prefilling"
        self._slot_req[slot] = req
        self._slot_prefill[slot] = {
            "done": m_tok, "m_blocks": m_blocks, "path": path,
            "bt": bt_row, "bt_dev": self._dev(bt_row), "last": None,
            "t0": now,
        }
        self._active_dev = None  # occupancy changed; the slot joins the
        # window INACTIVE until its last chunk lands and first_pick runs
        if m_blocks:
            self.stats.radix(True, tokens=m_tok)
            self._radix.record(True, tokens=m_tok)
            req.radix_tokens = m_tok
            self._tr_instant(req, "radix_hit", blocks=m_blocks,
                             tokens=m_tok)
        elif self._radix is not None:
            self.stats.radix(False)
            self._radix.record(False)
        self.stats.prompt_admitted(n_tok)
        return False

    def _chunk_tick(self, reset_mask) -> bool:
        """Dispatch ONE prefill chunk — the chunked-prefill sibling of
        :meth:`_overlap_prefill`, called at the same seam (between the
        window dispatch and its blocking readback) so the chunk's compute
        hides behind the in-flight window; also called when no window
        dispatched (nothing decoding) so prefill still progresses.  One
        chunk per engine iteration TOTAL bounds every co-resident
        request's added decode latency at one chunk.  Picks the oldest
        PREFILLING slot (FIFO by request id).  Returns True when a chunk
        was dispatched (watchdog progress)."""
        pick = None
        for slot, rec in enumerate(self._slot_prefill):
            if rec is None:
                continue
            if pick is None or self._slot_req[slot].id < self._slot_req[pick].id:
                pick = slot
        if pick is None:
            return False
        slot, rec = pick, self._slot_prefill[pick]
        req = self._slot_req[slot]
        c = self._prefill_chunk
        done = rec["done"]
        suffix = req.tokens[done:done + c]
        t_c0 = self.clock()
        try:
            padded = np.full((1, c), self.pad_id, np.int32)
            padded[0, : suffix.size] = suffix
            # ONE program per chunk SIZE, not per prompt length: every
            # chunk of every prompt is this same (1, C) extend — the
            # census stays pinned and long prompts need no bucket
            with self._compile.site(self._site(f"extend[b{c}]")):
                self.cache, ext_logits = self._extend(
                    self.params, self.cache, jnp.asarray(slot, jnp.int32),
                    rec["bt_dev"], jnp.asarray(padded),
                    jnp.asarray(done, jnp.int32),
                    jnp.asarray(int(suffix.size), jnp.int32))
            rec["done"] = done + int(suffix.size)
            rec["last"] = ext_logits
            # the extend installed the slot's real block table — a reset
            # pending from the previous tenant must not zero it back
            reset_mask[slot] = False
            t_c1 = self.clock()
            self.stats.chunk(t_c1 - t_c0)
            if self._tracer is not None and req.trace is not None:
                # per-chunk child span under the request's admit phase
                self._tracer.complete(
                    "prefill_chunk", t_c0, t_c1, cat="serving",
                    parent=req.trace.get("phase") or req.trace["id"],
                    tid=req.trace["tid"], start=done,
                    tokens=int(suffix.size))
            return True
        except Exception as e:
            # the chunk's failure is THIS request's failure (isolated) —
            # the slot frees and its pages queue for release
            self._slot_req[slot] = None
            self._slot_prefill[slot] = None
            self._release_slot_alloc(slot)
            self._active_dev = None
            self._fail(req, e, self.clock())
            reset_mask[slot] = True
            return False

    def _chunk_finish(self, slot: int, rec: dict, req: Request,
                      reset_mask) -> None:
        """The last chunk landed: pick the first token from its final-
        position logits (the shared ``first_pick`` program — same as
        every other landing path), donate the freshly-prefilled whole
        prompt pages into the radix trie, and run the standard admission
        tail (TTFT/SLO/telemetry, streaming callback, planes, decode
        phase).  Failure here is the request's own, exactly like the
        whole-prompt admission tail."""
        now = self.clock()
        if self.role == "prefill":
            # disaggregated (ISSUE 16): donate the freshly-chunked prompt
            # blocks into the source trie, then package instead of
            # picking — chunked prefill composes with handoff exactly as
            # with local decode
            try:
                if self._radix is not None:
                    n_tok = int(req.tokens.size)
                    bt_row, m_blocks = rec["bt"], rec["m_blocks"]
                    donate = {j: int(bt_row[j])
                              for j in range(m_blocks,
                                             n_tok // self._page_size)}
                    if donate:
                        priv, nodes = self._slot_alloc[slot]
                        held, _kept = self._radix.insert(
                            req.tokens, m_blocks, donate, rec["path"])
                        for node in held:
                            priv.remove(node.page)
                            nodes.append(node)
                self._handoff_package(req, slot, rec["last"], rec["bt"])
            except Exception as e:
                self._slot_req[slot] = None
                self._slot_prefill[slot] = None
                self._release_slot_alloc(slot)
                self._active_dev = None
                self._fail(req, e, self.clock())
                reset_mask[slot] = True
                return
            self._slot_req[slot] = None
            self._slot_prefill[slot] = None
            self._active_dev = None
            reset_mask[slot] = True
            return
        try:
            first, first_logp = self._first_pick(req, rec["last"])
            if self._radix is not None:
                n_tok = int(req.tokens.size)
                bt_row, m_blocks = rec["bt"], rec["m_blocks"]
                donate = {j: int(bt_row[j])
                          for j in range(m_blocks, n_tok // self._page_size)}
                if donate:
                    priv, nodes = self._slot_alloc[slot]
                    held, _kept = self._radix.insert(
                        req.tokens, m_blocks, donate, rec["path"])
                    for node in held:
                        priv.remove(node.page)
                        nodes.append(node)
            req.generated.append(first)
            req.logprobs.append(first_logp)
            req.first_token_t = self.clock()  # TTFT: first token ON THE HOST
            self._last_progress_ever = req.first_token_t
            if req.ttft_slo_s is not None:
                req.slo_ttft_ok = (
                    req.first_token_t - req.submit_t <= req.ttft_slo_s)
            if self._telemetry is not None:
                self._telemetry.observe(
                    "ttft_s", req.first_token_t - req.submit_t,
                    exemplar=(req.trace_ctx.trace_id
                              if req.trace_ctx is not None else None))
                self._telemetry.inc("tokens_generated")
            req.status = "running"
            self._tr_instant(req, "first_token", slot=slot,
                             cache_hit=False)
            self._notify(req, first)
        except Exception as e:
            self._slot_req[slot] = None
            self._slot_prefill[slot] = None
            self._release_slot_alloc(slot)
            self._active_dev = None
            self._fail(req, e, self.clock())
            reset_mask[slot] = True
            return
        self._slot_prefill[slot] = None
        self._slot_tok[slot] = first
        temp, topp, topk, minp, key = self._req_sampling(req)
        self._slot_temp[slot] = temp
        self._slot_topp[slot] = topp
        self._slot_topk[slot] = topk
        self._slot_minp[slot] = minp
        self._slot_key[slot] = key
        self._tok_dev = None  # host mirrors changed; re-upload
        self._active_dev = None
        self._planes_dev = None
        self._pos_dev = None
        self._tr_phase(req, "decode", slot=slot)
        if self._done_reason(req) is not None:
            self._retire(slot, self._done_reason(req), self.clock())
            reset_mask[slot] = True

    def _chunk_land(self, reset_mask) -> None:
        """Land any slot whose LAST chunk has been dispatched.  Runs
        AFTER the window readback (not at the dispatch seam) so the
        landing's host-mirror writes — ``_slot_tok[slot]``, the sampling
        planes, the mirror invalidations — are not clobbered by the
        readback's wholesale ``blk[:, -1]`` copy."""
        for slot, rec in enumerate(self._slot_prefill):
            if rec is None or rec["last"] is None:
                continue
            req = self._slot_req[slot]
            if rec["done"] >= int(req.tokens.size):
                self._chunk_finish(slot, rec, req, reset_mask)

    def _admit_free_slots(self, reset_mask) -> bool:
        """Fill free slots: overlap-prefilled pendings first (they were
        popped earlier, so FIFO order is preserved), then fresh scheduler
        pops.  A failed admission (poisoned request) frees the slot for
        the NEXT request in the same iteration — one casualty must not
        idle a slot for a whole loop turn.  Returns True when anything
        landed (watchdog progress)."""
        admitted = False
        for slot in range(self.slots):
            while self._slot_req[slot] is None:
                if self._pending:
                    req, prefilled = self._pending.popleft()
                    self._stalled_ids.discard(req.id)
                    now = self.clock()
                    if now > req.overdue_at:
                        # the overlap gamble lost: prefilled, then the
                        # deadline lapsed before a slot freed — cancel
                        # without landing (the prefill is sunk cost)
                        req.status = "cancelled"
                        req.finish_t = now
                        self._tr_close(req, status="cancelled")
                        self.completed.append(req)
                        self.stats.add(req)
                        continue
                    needs_reset = self._admit(req, slot, now,
                                              prefilled=prefilled)
                else:
                    req = self.scheduler.pop(self.clock())
                    if req is None:
                        return admitted
                    needs_reset = self._admit(req, slot, self.clock())
                if isinstance(needs_reset, tuple):
                    # paged pool momentarily dry ("stall", prefilled): park
                    # the request at the FRONT (FIFO preserved — it was
                    # popped first) and stop admitting; this step's retires
                    # flush pages and the next iteration retries
                    self._pending.appendleft((req, needs_reset[1]))
                    self._stalled_ids.add(req.id)
                    return admitted
                if self._slot_req[slot] is not None:
                    admitted = True
                    if self._slot_prefill[slot] is None:
                        reset_mask[slot] = False  # insert overwrote the row
                    # else PREFILLING: keep any pending reset — the block
                    # table must stay TRASH until a chunk installs it
                elif needs_reset:
                    # the row was claimed but belongs to no live request
                    # (post-insert failure, or retired at admission); zero
                    # it unless a later admit in this loop overwrites it
                    reset_mask[slot] = True
        return admitted

    def _overlap_prefill(self) -> None:
        """Dispatch the NEXT queued request's bucketed prefill while a
        decode window is still in flight — the prefill's compute hides
        behind the window instead of stalling every resident slot at the
        next admission.  At most one dispatch per window (matching the
        at-most-slots admission rate) and at most ``slots`` parked
        pendings; a failure here is the request's own (isolated), exactly
        as if it had failed at inline admission."""
        if len(self._pending) >= self.slots:
            return
        req = self.scheduler.pop(self.clock())
        if req is None:
            return
        # the "admit" phase opens HERE — for an overlapped request it spans
        # prefill + the parked wait for a slot, mirroring what the request
        # actually experiences between queue exit and its first token
        self._tr_phase(req, "admit", overlapped=True)
        try:
            self._pending.append((req, self._prefill_request(req)))
        except Exception as e:
            self._fail(req, e, self.clock())

    # ------------------------------------------------------------------
    # disaggregated prefill/decode handoff (ISSUE 16; serving/kv_handoff)

    def _handoff_package(self, req: Request, slot: int, logits_dev,
                         bt_row) -> None:
        """Prefill role: gather the landed prompt's pages host-side and
        park the request in the outbox (kv_handoff.package) — the slot's
        page hold transfers to the packet, nothing frees until the router
        confirms delivery."""
        packet = kv_handoff.package(self, req, slot, logits_dev, bt_row)
        self._outbox.append(packet)
        self.handoffs_out += 1

    def admit_prefilled(self, packet) -> bool:
        """Decode side: land a handed-off prefill (kv_handoff.deliver).
        True = packet consumed (decoding, or terminally failed on its own
        admission tail); False = re-park and retry later (no free slot,
        or the all-or-nothing destination allocation found the pool dry —
        zero writes were issued).  Refused on prefill-role and dense
        engines, and after close."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if self.role == "prefill":
            raise RuntimeError(
                "prefill-role engine cannot accept a handoff — deliver to "
                "a decode/both replica")
        if self._pool is None:
            raise RuntimeError(
                "handoff needs the paged KV layout (kv_page_size > 0)")
        return kv_handoff.deliver(self, packet)

    def _reset_slot_now(self, slot: int) -> None:
        """Immediate one-slot block-table reset + deferred-free flush,
        for landing paths that run OUTSIDE step() (admit_prefilled): the
        reset dispatch precedes any later tenant of the reclaimed pages
        on the single device stream, same as step()'s batched reset."""
        mask = np.zeros((self.slots,), bool)
        mask[slot] = True
        with self._compile.site(self._site("slot_reset")):
            self.cache = self._reset(self.cache, self._dev(mask))
        self._flush_freed_pages()

    def step(self) -> int:
        """One host-loop iteration: cancel → admit → decode window →
        retire.  Returns the number of REAL tokens produced this
        iteration (window tokens past a row's EOS/budget are discarded,
        never counted)."""
        if self._closed:
            raise RuntimeError("engine is closed")
        t0 = self.clock()
        reset_mask = np.zeros((self.slots,), bool)

        # 1) deadline sweep over RUNNING rows (queued rows are swept by the
        #    scheduler at pop time; overlap-prefilled pendings at landing)
        for slot, req in enumerate(self._slot_req):
            if req is not None and t0 > req.overdue_at:
                self._retire(slot, "cancelled", t0)
                reset_mask[slot] = True

        # 2) admit into free slots — freed capacity refills immediately,
        #    which is the whole point of continuous batching
        admitted = self._admit_free_slots(reset_mask)

        # 3) ONE windowed decode dispatch across ALL slots (fixed shape;
        #    idle rows decode garbage into their own rows).  The active
        #    mask is FROZEN for the window: rows retiring mid-window keep
        #    decoding up to decode_ahead-1 garbage steps the host masks
        #    off below.  A decode-dispatch fault belongs to ALL slots:
        #    with a watchdog it is absorbed as a no-progress iteration
        #    until stall_timeout_s, then in-flight requests fail and
        #    EngineStalled raises; without one it fails in-flight and
        #    re-raises immediately.
        produced = 0
        decoded = False
        chunked = False
        occupied_at_dispatch = self.occupied
        # PREFILLING slots are occupied but not decoding: a window with
        # zero decoding rows would be pure waste (and a spurious
        # serving-step chaos event), so the dispatch gates on decoding
        decoding_at_dispatch = (self._decoding if self._prefill_chunk
                                else occupied_at_dispatch)
        if decoding_at_dispatch > 0:
            spec = self._verify is not None
            # speculative mode replaces the decode-ahead scan with ONE
            # (slots, draft_len+1)-position verify forward per window —
            # host drafting must run between windows, which a fused k-step
            # scan could never pause for — so the window length k is the
            # verify chunk size, not decode_ahead
            k = self.draft_len + 1 if spec else self.decode_ahead
            # the engine-track (tid 0) view of this window; request-track
            # spans tell each request's story, this tells the loop's.
            # Emitted as already-closed `complete` spans from the stats
            # timestamps the loop takes anyway — the windowed hot path
            # pays 3 ring pushes per window, no open-span churn and no
            # tracer-only clock reads.
            t_w0 = self.clock() if self._tracer is not None else 0.0
            t_disp = None
            try:
                if self._chaos is not None:
                    from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import (
                        ChaosFault,
                    )

                    # one chaos event per WINDOW dispatch (not per fused
                    # step): the event index is the dispatch count, which
                    # keeps seeded plans stable across decode_ahead
                    self._chaos.raise_if_fired("serving-step", ChaosFault)
                if spec:
                    # ---- host drafting: build the (slots, k) chunk ----
                    # column 0 = each slot's pending last token (the same
                    # contract the decode window's tok carry uses), then
                    # up to draft_len prompt-lookup proposals per slot
                    t_d0 = self.clock()
                    chunk = np.full((self.slots, k), self.pad_id, np.int32)
                    chunk[:, 0] = self._slot_tok
                    dls = np.zeros((self.slots,), np.int32)
                    for slot, req in enumerate(self._slot_req):
                        if req is None or self._slot_prefill[slot] is not None:
                            continue
                        d = self._drafter.draft(np.concatenate(
                            [req.tokens,
                             np.asarray(req.generated, np.int32)]))
                        if d.size:
                            chunk[slot, 1:1 + d.size] = d
                            dls[slot] = d.size
                    with self._compile.site(self._site("slot_draft")):
                        chunk_dev = self._dev(chunk)
                        dls_dev = self._dev(dls)
                        # acceptance makes the PRNG position advance
                        # data-dependent: spec windows re-upload the plane
                        # fresh from the host generated counts each window
                        pos_dev = self._dev(np.array(
                            [0 if r is None else len(r.generated)
                             for r in self._slot_req], np.int32))
                    t_d1 = self.clock()
                else:
                    if self._tok_dev is None:
                        self._tok_dev = self._dev(self._slot_tok)
                    if self._pos_dev is None:
                        # PRNG positions = tokens generated so far; the
                        # window returns the advanced plane (carried like
                        # _tok_dev, rebuilt here after any admission)
                        self._pos_dev = self._dev(np.array(
                            [0 if r is None else len(r.generated)
                             for r in self._slot_req], np.int32))
                if self._active_dev is None:
                    # PREFILLING slots stay INACTIVE: their pages hold a
                    # partial prompt — garbage decode writes above the
                    # chunk cursor are overwritten by the next chunk
                    self._active_dev = self._dev(np.array(
                        [r is not None and p is None
                         for r, p in zip(self._slot_req,
                                         self._slot_prefill)]))
                if self._planes_dev is None:
                    self._planes_dev = (self._dev(self._slot_temp),
                                        self._dev(self._slot_topp),
                                        self._dev(self._slot_topk),
                                        self._dev(self._slot_minp),
                                        self._dev(self._slot_key))
                (temps_dev, topps_dev, topks_dev, minps_dev,
                 keys_dev) = self._planes_dev
                t_disp = self.clock()
                if spec:
                    with self._compile.site(self._site(f"verify_window[k{k}]")):
                        self.cache, blk_dev, logp_dev, acc_dev, _ = \
                            self._verify(
                                self.params, self.cache, chunk_dev, dls_dev,
                                self._active_dev, temps_dev, topps_dev,
                                topks_dev, minps_dev, keys_dev, pos_dev)
                else:
                    with self._compile.site(self._site(f"decode_window[k{k}]")):
                        self.cache, blk_dev, logp_dev, last_dev, pos_out = \
                            self._window(
                                self.params, self.cache, self._tok_dev,
                                self._active_dev, temps_dev, topps_dev,
                                topks_dev, minps_dev, keys_dev,
                                self._pos_dev)
                dispatch_s = self.clock() - t_disp
            except Exception as e:
                now = self.clock()
                if self._tracer is not None:
                    # a decode-dispatch fault belongs to ALL slots — the
                    # engine-track instant records it once; requests it
                    # kills get their own chaos_fault/close via _fail
                    self._tracer.instant(
                        "decode_fault", cat="serving", tid=self._trace_tid,
                        error=f"{type(e).__name__}: {e}")
                    wid = self._tracer.complete(
                        "window", t_w0, now, cat="serving", k=k,
                        tid=self._trace_tid, occupied=occupied_at_dispatch,
                        error=type(e).__name__)
                    if t_disp is not None:
                        self._tracer.complete(
                            "dispatch", t_disp, now, cat="serving",
                            tid=self._trace_tid, parent=wid,
                            error=type(e).__name__)
                anchor = self._last_progress_t if self._last_progress_t is not None else t0
                if self._last_progress_t is None:
                    self._last_progress_t = t0
                if self.stall_timeout_s is None:
                    self._fail_in_flight(e, now)
                    raise
                if now - anchor > self.stall_timeout_s:
                    self._fail_in_flight(e, now)
                    raise EngineStalled(
                        f"no token progress across {self.slots} slots within "
                        f"{self.stall_timeout_s}s (last decode error: "
                        f"{type(e).__name__}: {e})") from e
                # transient: no tokens this iteration, watchdog keeps counting
            else:
                decoded = True
                # the window is in flight (async dispatch): spend the wait
                # prefilling instead of blocking — one chunk of the oldest
                # PREFILLING slot in chunked mode, else the next queued
                # request's bucketed prefill
                if self._prefill_chunk:
                    chunked = self._chunk_tick(reset_mask)
                else:
                    self._overlap_prefill()
                # ONE blocking host sync per window: the (slots, k) block
                # serves the host inspection below, and `last` (the final
                # carry token) feeds the next window without a host slice
                t_rb = self.clock()
                blk = np.asarray(blk_dev)
                logps = np.asarray(logp_dev)
                acc = np.asarray(acc_dev) if spec else None
                readback_s = self.clock() - t_rb
                if spec:
                    # each slot's pending token is acceptance-dependent —
                    # set per slot below; the device token mirror is never
                    # read in spec mode (the chunk re-uploads fresh)
                    self._tok_dev = None
                else:
                    self._tok_dev = last_dev
                    self._pos_dev = pos_out  # advanced in-graph, carried
                    self._slot_tok = blk[:, -1].copy()
                now = self.clock()
                t_acc0 = t_rb + readback_s
                waste = 0
                for slot, req in enumerate(self._slot_req):
                    if req is None or self._slot_prefill[slot] is not None:
                        continue  # PREFILLING rows were inactive: no tokens
                    n_emit = k
                    if spec:
                        # accepted drafts + the model's one free correction
                        # token: emitted tokens are exactly blk[:, :acc+1]
                        n_emit = int(acc[slot]) + 1
                        self._slot_tok[slot] = blk[slot, n_emit - 1]
                        self.stats.spec(int(dls[slot]), int(acc[slot]))
                        if self._tracer is not None and req.trace is not None:
                            # draft/verify/accept land on the REQUEST's
                            # track BEFORE the token loop, so a mid-
                            # acceptance retirement (which closes the
                            # request's trace tree) cannot lose them
                            par = req.trace.get("phase") or req.trace["id"]
                            rtid = req.trace["tid"]
                            self._tracer.complete(
                                "draft", t_d0, t_d1, cat="speculative",
                                parent=par, tid=rtid, drafted=int(dls[slot]))
                            self._tracer.complete(
                                "verify", t_disp, t_acc0, cat="speculative",
                                parent=par, tid=rtid)
                            self._tracer.complete(
                                "accept", t_acc0, now, cat="speculative",
                                parent=par, tid=rtid,
                                accepted=int(acc[slot]),
                                drafted=int(dls[slot]))
                    appended = 0
                    for j in range(n_emit):
                        tok = int(blk[slot, j])
                        req.generated.append(tok)
                        req.logprobs.append(float(logps[slot, j]))
                        produced += 1
                        appended += 1
                        try:
                            self._notify(req, tok)
                        except Exception as e:
                            # the callback's failure is THIS request's
                            # failure; its remaining window tokens die with it
                            self._slot_req[slot] = None
                            self._release_slot_alloc(slot)
                            self._active_dev = None
                            self._fail(req, e, now)
                            reset_mask[slot] = True
                            break
                        reason = self._done_reason(req)
                        if reason is not None:
                            # EOS/budget mid-window: keep tokens up to and
                            # including the stop, discard the ≤k-1 overrun
                            self._retire(slot, reason, now,
                                         waste=k - appended)
                            reset_mask[slot] = True
                            break
                    # this slot dispatched k device steps (scan steps in
                    # plain mode, verify lanes in spec mode) and delivered
                    # `appended` tokens — the remainder (post-stop overrun
                    # / rejected lanes) is the window's waste
                    waste += k - appended
                self.stats.window(dispatch_s, readback_s,
                                  steps=decoding_at_dispatch * k, waste=waste)
                if self._tracer is not None:
                    wid = self._tracer.complete(
                        "window", t_w0, self.clock(), cat="serving", k=k,
                        tid=self._trace_tid, occupied=occupied_at_dispatch,
                        produced=produced, waste=waste)
                    self._tracer.complete("dispatch", t_disp,
                                          t_disp + dispatch_s, cat="serving",
                                          tid=self._trace_tid, parent=wid)
                    self._tracer.complete("readback", t_rb,
                                          t_rb + readback_s, cat="serving",
                                          tid=self._trace_tid, parent=wid)

        if self._prefill_chunk:
            if not decoded:
                # nothing decoding (every occupied slot PREFILLING, or the
                # window faulted): chunks still pump — one per iteration
                chunked = self._chunk_tick(reset_mask)
            # land AFTER the readback so the wholesale _slot_tok copy
            # above cannot clobber a landed request's first token
            self._chunk_land(reset_mask)

        # 4) zero retired rows so idle cursors restart from 0 (bounded) and
        #    the next admission starts from a clean row
        if reset_mask.any():
            with self._compile.site(self._site("slot_reset")):
                self.cache = self._reset(self.cache, self._dev(reset_mask))
        # deferred page frees apply only now, AFTER the reset dispatch is
        # enqueued: single-stream device execution guarantees every program
        # still reading a retired slot's block table runs before any later
        # tenant of the reallocated pages writes them
        self._flush_freed_pages()

        if produced > 0 or admitted or chunked or self.occupied == 0:
            self._last_progress_t = self.clock()
            self._last_progress_ever = self._last_progress_t
        if self._pool is not None:
            self.stats.pool_sample(self._pool.allocated, self._pool.capacity,
                                   self._page_size, self._page_bytes)
        self.stats.tick(self.occupied, max(self.clock() - t0, 0.0),
                        decoded=decoded)
        # counters only at their change points (admission shrinks the
        # queue, retirement frees slots) — the tracer dedups repeats
        # anyway, but the calls themselves are hot-loop cost
        if self._tracer is not None and (admitted or reset_mask.any()):
            self._tracer.counter("queue_depth", len(self.scheduler),
                                 tid=self._trace_tid)
            self._tracer.counter("occupied_slots", self.occupied,
                                 tid=self._trace_tid)
        if self._telemetry is not None:
            if produced:
                self._telemetry.inc("tokens_generated", int(produced))
            self._telemetry.maybe_sample()  # clock + compare between samples
        return produced

    def _fail_in_flight(self, exc: BaseException, now: float) -> None:
        """Fail every running request and reset their rows — the clean-exit
        half of the watchdog contract (the engine stays consistent for a
        caller that catches EngineStalled)."""
        mask = np.zeros((self.slots,), bool)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            self._slot_req[slot] = None
            self._slot_prefill[slot] = None
            self._release_slot_alloc(slot)
            req.engine_fault = True  # collateral, not the request's own fault
            self._fail(req, exc, now)
            mask[slot] = True
        if mask.any():
            self.cache = self._reset(self.cache, self._dev(mask))
        self._flush_freed_pages()
        self._active_dev = None
        self._planes_dev = None
        self._pos_dev = None
        self._last_progress_t = None

    def run(self, max_steps: int | None = None) -> list[Request]:
        """Drive :meth:`step` until every submitted request has retired
        (or ``max_steps`` host iterations elapse), then return the
        completed requests in retirement order.  Emits the stats summary
        through ``writer`` (when one was given) on drain."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        # overdue-before-admission cancellations belong to this run's book
        for req in self.scheduler.cancelled:
            self.completed.append(req)
            self.stats.add(req)
        self.scheduler.cancelled.clear()
        if not self.has_work:
            if self._prefix is not None:
                self.stats.prefix_oversized(self._prefix.oversized)
            self.stats.set_compile(CompileTracker.delta(
                self._compile.snapshot(), self._compile0))
            self._stamp_memory()
            if self.writer is not None:
                self.stats.emit(self.writer)
        return self.completed

    # ------------------------------------------------------------------
    # graceful shutdown

    def drain(self, max_steps: int | None = None) -> list[Request]:
        """Graceful shutdown, phase 1: serve every request already accepted
        (queued + in-flight) to retirement, admitting NOTHING new —
        :meth:`submit` raises from the moment drain starts.  Returns the
        completed list; call :meth:`close` afterwards to release the
        engine."""
        if self._closed:
            raise RuntimeError("engine is closed")
        self._draining = True
        return self.run(max_steps=max_steps)

    def close(self) -> None:
        """Graceful shutdown, phase 2 (or an immediate one): cancel every
        queued and in-flight request (terminal ``cancelled``, partial
        output kept), emit the stats record, and refuse all further
        submit/step/run/drain calls.  A parked request whose landing
        STALLED on a dry page pool (overcommit) is instead FAILED
        terminally — it was accepted and then starved, not merely queued.
        Every request terminated here carries ``engine_fault=True`` (the
        engine quit on it; a router re-dispatches exactly these).
        Idempotent."""
        if self._closed:
            return
        self._draining = True
        now = self.clock()
        mask = np.zeros((self.slots,), bool)
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            req.engine_fault = True
            self._retire(slot, "cancelled", now)
            mask[slot] = True
        if mask.any():
            self.cache = self._reset(self.cache, self._dev(mask))
        self._flush_freed_pages()
        while self._outbox:
            # packaged-but-undelivered handoffs: accepted work the engine
            # quit on — cancelled with engine_fault, so the router's
            # failover harvest re-dispatches exactly these (the replay's
            # re-prefill is a radix hit wherever the trie survives)
            packet = self._outbox.popleft()
            packet.release()
            req = packet.req
            req.engine_fault = True
            req.status = "cancelled"
            req.finish_t = now
            self._tr_close(req, status="cancelled")
            self.completed.append(req)
            self.stats.add(req)
        for req, _prefilled in self._pending:  # overlap-prefilled, unlanded
            req.engine_fault = True
            if req.id in self._stalled_ids:
                self._fail(req, RuntimeError(
                    "engine closed while the request was overcommit-stalled "
                    "(accepted, prefilled, starved of KV pages)"), now)
            else:
                req.status = "cancelled"
                req.finish_t = now
                self._tr_close(req, status="cancelled")
                self.completed.append(req)
                self.stats.add(req)
        self._pending.clear()
        self._stalled_ids.clear()
        while (req := self.scheduler.pop(now)) is not None:
            req.engine_fault = True
            req.status = "cancelled"
            req.finish_t = now
            self._tr_close(req, status="cancelled")
            self.completed.append(req)
            self.stats.add(req)
        for req in self.scheduler.cancelled:  # overdue-at-pop sweepings
            self.completed.append(req)
            self.stats.add(req)
        self.scheduler.cancelled.clear()
        if self._prefix is not None:
            self.stats.prefix_oversized(self._prefix.oversized)
        if self._pool is not None:  # final occupancy (post-cancel flush)
            self.stats.pool_sample(self._pool.allocated, self._pool.capacity,
                                   self._page_size, self._page_bytes)
        self.stats.set_compile(CompileTracker.delta(
            self._compile.snapshot(), self._compile0))
        self._stamp_memory()
        if self.writer is not None:
            self.stats.emit(self.writer)
        self._closed = True

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------------------
    # live weight replacement

    def swap_params(self, params) -> None:
        """Replace the decode weights of an IDLE engine in place — the
        replica half of the router's hot-swap (drain → swap → re-admit).

        The engine must be fully quiesced (no occupied slot, no parked
        pending, no queued request): every cached KV entry was computed
        under the OLD weights, so a swap with work in flight would splice
        old-weight keys/values into new-weight attention.  For the same
        reason the prefix cache and the radix trie are dropped wholesale —
        their entries are stale the instant the weights change — with the
        trie's pages returned to the pool.  The compiled program family is
        shape-keyed, not weight-keyed, so NO recompilation follows: the
        swapped engine serves its first new-weight request at full speed.
        """
        if self._closed:
            raise RuntimeError("engine is closed")
        if self.has_work or self._outbox:
            # a parked handoff packet HOLDS pool pages and radix nodes —
            # the wholesale trie eviction below assumes no outstanding
            # references, so an undelivered outbox counts as busy too
            raise RuntimeError(
                f"swap_params on a busy engine (occupied={self.occupied}, "
                f"pending={len(self._pending)}, queued={len(self.scheduler)}, "
                f"outbox={len(self._outbox)})"
                " — drain it first (stop submitting, pump step() until "
                "has_work is False)")
        if self.quant == "int8":
            # the hot-swap contract hands FULL-PRECISION host trees (the
            # router gives every replica the same checkpoint): re-quantize
            # to the engine's int8+scale layout before placement.  A tree
            # that already carries int8 kernels passes through unchanged
            # (quantize_params_int8 is idempotent).
            params = quantize_params_int8(params)
        if self._mesh is not None:
            # accepts a full host/single-chip tree and re-shards it
            # wholesale onto THIS engine's mesh (the router's hot-swap
            # hands every replica the same unsharded checkpoint tree);
            # an already-correctly-sharded tree is a no-op placement
            params = jax.device_put(params, self._param_shardings)
        self.params = params
        if self._prefix is not None:
            self._prefix.clear()
        if self._radix is not None:
            # every node is unreferenced on an idle engine; evict the lot
            self._radix.evict(self._radix.n_blocks,
                              lambda p: self._pool.free([p]))

    # ------------------------------------------------------------------
    # launch-path compile prewarm (ROADMAP item 5a)

    def prewarm(self) -> dict:
        """Compile the engine's ENTIRE program family before the first
        request — the launch-path half of the cold-start fix (ROADMAP item
        5a; the persistent compile cache from ISSUE 7 is the cross-process
        half, and ``compile_cache_dir=`` makes these compiles land there).

        Runs each resident program once with zero/dummy inputs on the IDLE
        engine: every bucket's prefill, the shared first-token pick, the
        window program this mode actually dispatches (decode window, or
        the verify window in speculative mode), the slot insert/reset,
        and — paged — every bucket's suffix-extend.  Execution (not
        ``lower().compile()``) is deliberate: it populates the real jit
        call caches, so the first request pays ZERO compile anywhere, and
        the compile events fire under the same ``CompileTracker`` site
        labels they would at first use — the census budget sees the
        identical program family, just earlier.  Dummy work is confined
        to idle-slot garbage the engine's contract already tolerates
        (all-inactive masks, the trash page, rows an insert overwrites at
        admission), and sampling keys are pure per-request data (no
        engine-held stream to perturb), so prewarmed output is
        token-identical to cold output.

        Returns ``{"programs", "compile_s", "wall_s", "by_site"}`` — the
        compile delta this call caused (0 programs on a warm persistent
        cache is the success case the bench ``compile_cache`` block
        measures as cold-vs-prewarmed TTFT).
        """
        if self._closed:
            raise RuntimeError("engine is closed")
        if self.has_work:
            raise RuntimeError(
                f"prewarm on a busy engine (occupied={self.occupied}, "
                f"pending={len(self._pending)}, "
                f"queued={len(self.scheduler)}) — prewarm belongs in the "
                "launch path, before the first submit")
        t0 = self.clock()
        before = self._compile.snapshot()
        slot0 = jnp.asarray(0, jnp.int32)
        if self.role == "decode":
            # decode replicas own NO prefill program: pages arrive via
            # admit_prefilled (serving/kv_handoff.py), so the family here
            # is first_pick + the decode window + reset + the per-page
            # handoff writer — and the per-role census (bench_disagg)
            # pins that no prefill[b*]/extend[b*] site ever appears
            vocab = getattr(self.model, "num_classes")
            last_logits = self._dev(np.zeros((1, vocab), np.float32))
            with self._compile.site(self._site("handoff_install")):
                # zero payload through the SAME _dev commitment the real
                # admit_prefilled upload uses, so tp engines compile one
                # page-writer here and reuse it for every handoff
                payload = jax.tree.map(
                    lambda leaf: self._dev(
                        np.zeros(leaf.shape[1:], leaf.dtype)),
                    pool_page_leaves(self.cache))
                self.cache = self._page_write(self.cache, payload, slot0)
                bt_row = self._dev(np.zeros(
                    (self.max_len // self._page_size,), np.int32))
                self.cache = self._bt_install(
                    self.cache, bt_row, slot0, jnp.asarray(0, jnp.int32))
        elif self._prefill_chunk:
            # chunked mode never dispatches bucketed prefills or the
            # dense slot insert: the resident prefill family is the ONE
            # extend[b{C}] program every chunk of every prompt runs
            # through, warmed here over the trash-page block table
            # (garbage K/V the admission protocol already tolerates)
            c = self._prefill_chunk
            bt_row = self._dev(np.zeros((self.max_len // self._page_size,),
                                        np.int32))
            with self._compile.site(self._site(f"extend[b{c}]")):
                self.cache, last_logits = self._extend(
                    self.params, self.cache, slot0, bt_row,
                    jnp.zeros((1, c), jnp.int32),
                    jnp.asarray(0, jnp.int32),
                    jnp.asarray(1, jnp.int32))
        else:
            last_logits = None
            for b in self.buckets:
                with self._compile.site(self._site(f"prefill[b{b}]")):
                    # lens through the same list->asarray route
                    # _dense_prefill uses, so its scalar-conversion
                    # program is warm too, not just the prefill itself
                    _, last_logits = self._prefill_row(
                        self.params, jnp.zeros((1, b), jnp.int32),
                        jnp.asarray([1], jnp.int32))
        if self.role == "prefill":
            # the source half of the handoff family: the ONE fixed-shape
            # page gather every transferred page reads through (read-only
            # — jitted without donation), warmed so the first packet pays
            # zero compile
            with self._compile.site(self._site("handoff_gather")):
                jax.block_until_ready(self._page_gather(
                    self.cache, jnp.asarray(0, jnp.int32)))
        # the shared first-token pick over the (1, V) prefill logits —
        # same program whatever landing path (miss/hit/extend/handoff)
        # runs it.  A prefill-role engine never picks a token (the pick
        # runs on the decode side from the handed-off logits row), so it
        # skips this — its census carries zero pick/decode programs.
        if self.role != "prefill":
            with self._compile.site(self._site("first_pick")):
                tok, logp = first_pick(
                    last_logits,
                    self._dev(np.zeros((1,), np.float32)),
                    self._dev(np.zeros((1,), np.float32)),
                    self._dev(np.zeros((1,), np.int32)),
                    self._dev(np.zeros((1,), np.float32)),
                    self._dev(np.zeros((1, 2), np.uint32)),
                    self._dev(np.zeros((1,), np.int32)))
                # the landing path reads the pick eagerly (_first_pick
                # returns python scalars); under a mesh those committed
                # outputs key their own tiny gather programs, so read
                # them here or the first real admission compiles them
                int(tok[0]), float(logp[0])
        if not self._prefill_chunk and self.role != "decode":
            # a zeroed B=1 prefill row in the dense decode layout — the
            # same eval_shape probe init_cache uses, so dtypes (incl.
            # int8+scales) match what a real prefill hands to insert
            row_shapes = jax.eval_shape(
                lambda p: self.model.apply(
                    {"params": p}, jnp.zeros((1, 1), jnp.int32),
                    decode=True, max_len=self.max_len, ragged=True,
                    mutable=["cache"])[1]["cache"],
                self.params)
            row_cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype), row_shapes)
            if self._mesh is not None:
                # match the layout a REAL prefill's pinned output arrives
                # in, so prewarm compiles the same insert program serving
                # reuses
                row_cache = jax.device_put(row_cache, mesh_shardings(
                    self._mesh, make_param_specs(row_shapes, self._kv_rule)))
            if self._pool is not None:
                bt_row = self._dev(
                    np.zeros((self.max_len // self._page_size,), np.int32))
                with self._compile.site(self._site("slot_insert")):
                    self.cache = self._insert(self.cache, row_cache, bt_row,
                                              slot0)
                for b in self.buckets:
                    with self._compile.site(self._site(f"extend[b{b}]")):
                        self.cache, _ = self._extend(
                            self.params, self.cache, slot0, bt_row,
                            jnp.zeros((1, b), jnp.int32),
                            jnp.asarray(0, jnp.int32),
                            jnp.asarray(1, jnp.int32))
            else:
                with self._compile.site(self._site("slot_insert")):
                    self.cache = self._insert(self.cache, row_cache, slot0)
        inactive = self._dev(np.zeros((self.slots,), bool))
        if self.role != "prefill":
            # a prefill-role engine never dispatches a decode/verify
            # window — the per-role census pins zero window programs there
            temps0 = self._dev(np.zeros((self.slots,), np.float32))
            topps0 = self._dev(np.zeros((self.slots,), np.float32))
            topks0 = self._dev(np.zeros((self.slots,), np.int32))
            minps0 = self._dev(np.zeros((self.slots,), np.float32))
            keys0 = self._dev(np.zeros((self.slots, 2), np.uint32))
            pos0 = self._dev(np.zeros((self.slots,), np.int32))
            if self._verify is not None:
                k = self.draft_len + 1
                with self._compile.site(self._site(f"verify_window[k{k}]")):
                    self.cache, _, _, _, _ = self._verify(
                        self.params, self.cache,
                        self._dev(np.full((self.slots, k), self.pad_id,
                                          np.int32)),
                        self._dev(np.zeros((self.slots,), np.int32)),
                        inactive, temps0, topps0, topks0, minps0, keys0,
                        pos0)
            else:
                k = self.decode_ahead
                with self._compile.site(self._site(f"decode_window[k{k}]")):
                    self.cache, _, _, _, _ = self._window(
                        self.params, self.cache,
                        self._dev(np.zeros((self.slots,), np.int32)),
                        inactive, temps0, topps0, topks0, minps0, keys0,
                        pos0)
        with self._compile.site(self._site("slot_reset")):
            self.cache = self._reset(self.cache, inactive)
        delta = CompileTracker.delta(self._compile.snapshot(), before)
        return {"programs": delta["n_compiled_programs"],
                "compile_s": delta["compile_time_s"],
                "wall_s": round(self.clock() - t0, 6),
                "by_site": delta["by_site"]}

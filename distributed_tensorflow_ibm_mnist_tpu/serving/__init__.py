"""Continuous-batching serving: host-side scheduling over compiled decode.

The first inference-side subsystem (ISSUE 2): a fixed-shape compiled
decode-step program stays resident while a host loop multiplexes a stream
of variable-length requests through its batch slots — the TF-Replicator /
Mesh-TensorFlow separation of device program from execution driver
(PAPERS.md), applied to serving.

* :class:`~.engine.InferenceEngine` — the slot-multiplexed host loop
* :class:`~.scheduler.FIFOScheduler` / :class:`~.scheduler.Request` —
  bounded FIFO admission with prompt-length bucketing and deadlines
* :class:`~.stats.ServingStats` — TTFT/latency percentiles, tokens/sec,
  slot occupancy, emitted through :class:`~..utils.metrics.MetricWriter`

See docs/SERVING.md for the architecture and knobs.
"""

from distributed_tensorflow_ibm_mnist_tpu.serving.engine import (
    EngineStalled,
    InferenceEngine,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
    FIFOScheduler,
    QueueFull,
    Request,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.stats import ServingStats

__all__ = [
    "EngineStalled",
    "InferenceEngine",
    "FIFOScheduler",
    "QueueFull",
    "Request",
    "ServingStats",
]

"""Continuous-batching serving: host-side scheduling over compiled decode.

The first inference-side subsystem (ISSUE 2): a fixed-shape compiled
decode-step program stays resident while a host loop multiplexes a stream
of variable-length requests through its batch slots — the TF-Replicator /
Mesh-TensorFlow separation of device program from execution driver
(PAPERS.md), applied to serving.

* :class:`~.engine.InferenceEngine` — the slot-multiplexed host loop
  (``decode_ahead=k`` batches k fused decode steps per host sync — ISSUE 5)
* :class:`~.scheduler.FIFOScheduler` / :class:`~.scheduler.Request` —
  bounded FIFO admission with prompt-length bucketing and deadlines
* :class:`~.prefix_cache.PrefixCache` — content-addressed byte-bounded LRU
  of prefill results; repeated prompt prefixes skip prefill entirely
* :class:`~.kv_pool.KVPagePool` — paged KV cache (ISSUE 7,
  ``kv_page_size=``): per-layer page pools + per-slot block tables, so HBM
  scales with live tokens, not ``slots * max_len``
* :class:`~.radix_cache.RadixCache` — radix trie over token blocks:
  refcounted prompt-prefix pages shared between requests (the exact-match
  prefix cache's generalization; partial hits skip prefill compute)
* :class:`~.drafter.NgramDrafter` — model-free prompt-lookup drafting for
  speculative decoding (ISSUE 9, ``speculative="ngram"``): one verify
  forward accepts multiple host-drafted tokens per window with EXACT
  greedy parity (rejection sampling for sampled rows — ISSUE 13);
  ``InferenceEngine.prewarm()`` / ``Router.prewarm()``
  compile the full program family in the launch path (ROADMAP 5a)
* :class:`~.sampling.SamplingParams` — per-request
  ``(temperature, top_p, top_k, seed)`` sampling (ISSUE 13; ``top_k``
  per-request since ISSUE 14): per-slot data
  planes into ONE compiled window program, position-keyed PRNG (a
  request's stream is a pure function of its seed — restarts and
  failover replays are token-identical), per-token raw-logits logprobs
  on every :class:`~.scheduler.Request`
* chunked prefill (ISSUE 14, ``InferenceEngine(prefill_chunk=C)``): any
  admitted prompt — past every bucket, up to ``max_len - max_new`` —
  prefills as C-token chunks through ONE paged ``extend[b{C}]`` program,
  one chunk per engine iteration at the prefill-overlap seam, so
  admission costs the decoding slots at most one chunk of latency; the
  slot holds a transient ``PREFILLING`` state until its last chunk lands
  (docs/SERVING.md §Chunked prefill)
* :class:`~.stats.ServingStats` — TTFT/latency percentiles, tokens/sec,
  slot occupancy, decode-ahead window/waste accounting, prefix hit rate,
  compile accounting (``n_compiled_programs`` — ISSUE 6), emitted through
  :class:`~..utils.metrics.MetricWriter`; ``ServingStats.merge`` rolls N
  engine records into one cluster record (ISSUE 8)
* :class:`~.router.Router` / :class:`~.replica.Replica` /
  :class:`~.router.WeightWatcher` — the multi-replica tier (ISSUE 8):
  least-loaded dispatch over N engine replicas, chaos-proven failover
  (``Request.engine_fault`` collateral re-dispatched to survivors,
  exactly-once token delivery for greedy AND seeded-sampled decode), and
  live weight hot swap (drain → ``swap_params`` → re-admit, one replica
  at a time, validated through ``restore_latest_intact``)
* :class:`~.daemon.ServingDaemon` / :class:`~.daemon.DaemonRequest` —
  the daemonized tier (ISSUE 15): one pump thread per replica turns the
  step-pumped router into a long-lived service with thread-safe
  ``submit()``/``stream()``, per-request-ordered delivery, an external
  pump-wedge watchdog, and graceful ``drain``/``close``; admission order
  and shed-at-submit are pluggable via serving/policies.py
  (:class:`~.policies.FIFOPolicy`, :class:`~.policies.PriorityPolicy`,
  :class:`~.policies.DeadlineAwarePolicy` raising
  :class:`~.policies.SLOUnmeetable`)
* the internet-shaped front door (ISSUE 17): :class:`~.frontend.
  FrontDoor` — an asyncio HTTP/1.1 + SSE protocol server over the daemon
  (``POST /v1/generate`` streaming or unary, ``GET /healthz``,
  ``GET /metrics``; disconnect cancels, 429/503 carry policy
  ``Retry-After`` hints) with :class:`~.frontend.FrontDoorClient` as the
  stdlib wire client; :class:`~.traces.ArrivalTrace` /
  :class:`~.traces.TraceEvent` — recorded arrival traces (bursty /
  diurnal / heavy-tail / Poisson generators, JSONL round-trip,
  per-class interactive-vs-batch goodput via
  :func:`~.traces.replay_trace`); :class:`~.autoscaler.Autoscaler` —
  telemetry-driven elastic capacity (warm scale-up through the compile
  cache + ``WeightWatcher`` stamping, drain-before-retire scale-down
  with zero drops)
* crash durability (ISSUE 18): :class:`~.journal.RequestJournal` — an
  append-only, checksummed, segment-rotated write-ahead request journal
  (``admitted`` before ack / ``delivered`` high-water / ``retired``,
  ``fsync_policy=never|interval|always``) wired through
  ``ServingDaemon(journal=)``; :func:`~.journal.scan_journal` is the
  torn-tail-tolerant reader and :func:`~.journal.recover` rebuilds a
  fresh tier after SIGKILL and re-submits every incomplete request —
  deterministic seeded sampling (ISSUE 13) re-derives the exact token
  stream and the delivered high-water mark suppresses re-emission, so
  streams are exactly-once ACROSS the crash.  The front door grows
  ``Idempotency-Key`` (a retried POST binds to the original execution)
  and SSE ``id:`` / ``Last-Event-ID`` resume, plus keep-alive ping
  frames and a slow-loris body-read timeout.

Observability (ISSUE 6): pass ``tracer=`` (utils/tracing.Tracer) to the
engine and every request records a span tree (submit → queue → admit/
prefill or prefix hit → decode windows → retirement, with chaos faults
attached to the requests they hit); ``tracer.export_trace(path)`` writes a
Chrome-/Perfetto-loadable timeline and ``scripts/trace_report.py`` renders
it as a per-phase latency table.  See docs/OBSERVABILITY.md.

Live telemetry (ISSUE 11): pass ``telemetry=`` (utils/telemetry.Telemetry)
to the engine, router, and trainer — same nil-guard zero-cost-off contract
— and the health sampler snapshots their vitals (queue depth, slot/pool
occupancy, per-replica state + last-progress heartbeat) every
``interval_s`` into an append-mode JSONL time-series plus a Prometheus
text file; requests may declare ``(ttft_slo_s, tpot_slo_s)`` latency SLOs
that the engine judges at first token and retirement, flowing
``slo_met``/``slo_miss``/``goodput_rps`` through :class:`~.stats.
ServingStats` and the router rollup (``stats.slo_verdict`` is the
met/miss rule; ``scripts/telemetry_report.py`` renders the time-series).

See docs/SERVING.md for the architecture and knobs.
"""

from distributed_tensorflow_ibm_mnist_tpu.serving.daemon import (
    DaemonRequest,
    ServingDaemon,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.autoscaler import Autoscaler
from distributed_tensorflow_ibm_mnist_tpu.serving.drafter import NgramDrafter
from distributed_tensorflow_ibm_mnist_tpu.serving.engine import (
    EngineStalled,
    InferenceEngine,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.frontend import (
    FrontDoor,
    FrontDoorClient,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.journal import (
    JournalScan,
    JournalWriteError,
    RecoveredRequest,
    Recovery,
    RequestJournal,
    recover,
    scan_journal,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.kv_pool import (
    KVPagePool,
    init_paged_cache,
    pages_needed,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.policies import (
    AdmissionPolicy,
    DeadlineAwarePolicy,
    FIFOPolicy,
    PriorityPolicy,
    SLOUnmeetable,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.prefix_cache import PrefixCache
from distributed_tensorflow_ibm_mnist_tpu.serving.radix_cache import RadixCache
from distributed_tensorflow_ibm_mnist_tpu.serving.replica import Replica
from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import SamplingParams
from distributed_tensorflow_ibm_mnist_tpu.serving.router import (
    NoHealthyReplica,
    Router,
    RouterRequest,
    WeightWatcher,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
    FIFOScheduler,
    QueueFull,
    Request,
    request_fingerprint,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.stats import (
    ServingStats,
    slo_verdict,
    transcript_digest,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.traces import (
    ArrivalTrace,
    TraceEvent,
    bursty_trace,
    diurnal_trace,
    heavy_tail_trace,
    per_class_report,
    poisson_trace,
    replay_trace,
    with_slos,
)

__all__ = [
    "AdmissionPolicy",
    "ArrivalTrace",
    "Autoscaler",
    "DaemonRequest",
    "DeadlineAwarePolicy",
    "EngineStalled",
    "FIFOPolicy",
    "FrontDoor",
    "FrontDoorClient",
    "InferenceEngine",
    "FIFOScheduler",
    "JournalScan",
    "JournalWriteError",
    "KVPagePool",
    "NgramDrafter",
    "NoHealthyReplica",
    "PrefixCache",
    "PriorityPolicy",
    "QueueFull",
    "RadixCache",
    "RecoveredRequest",
    "Recovery",
    "Replica",
    "Request",
    "RequestJournal",
    "Router",
    "RouterRequest",
    "SLOUnmeetable",
    "SamplingParams",
    "ServingDaemon",
    "ServingStats",
    "TraceEvent",
    "WeightWatcher",
    "bursty_trace",
    "diurnal_trace",
    "heavy_tail_trace",
    "init_paged_cache",
    "pages_needed",
    "per_class_report",
    "poisson_trace",
    "recover",
    "replay_trace",
    "request_fingerprint",
    "scan_journal",
    "slo_verdict",
    "transcript_digest",
    "with_slos",
]

"""Crash durability for the serving tier: the write-ahead request journal.

Every durability guarantee the tier had before this module — PR 8
failover, the daemonized tier, the disaggregated handoff, the front
door — lives inside ONE process: a SIGKILL drops every queued, parked,
and in-flight request, and an HTTP client that retries after a
connection reset double-executes.  This module extends the repo's
signature exactly-once contract ACROSS the process boundary, the same
move the reference lineage makes for training (parameter-server
checkpoint recovery, PAPERS.md 1605.08695; TF-Replicator's point that
replication inside a job is not durability across job restarts,
1902.00465).

Three record types, appended write-ahead by :class:`~.daemon.
ServingDaemon` (wired via ``ServingDaemon(journal=...)``):

* ``admitted`` — the full request identity (prompt, ``max_new``,
  deadline, priority, SLOs, sampling params, idempotency key,
  fingerprint), written BEFORE the request enters the admission heap:
  an acknowledged submit is on disk before the caller hears "yes", so
  an accepted request can never be lost to a crash.  A raising append
  fails the submit — the caller never gets an ack the journal cannot
  back.
* ``delivered`` — the per-request delivered-token high-water mark,
  appended AFTER each token crosses to the caller.  The mark therefore
  never overstates what the client received: replay after a crash can
  re-emit a small suffix the client already has (closed client-side by
  SSE ``id:``/``Last-Event-ID`` stitching — frontend.py) but can never
  create a gap the client cannot fill.
* ``retired`` — the terminal verdict (done/cancelled/failed).  A
  request with no ``retired`` record is incomplete and gets replayed.

Why replay works: greedy and seeded-sampled streams are pure functions
of ``(prompt, max_new, SamplingParams)`` — the token at generated index
``n`` is picked with ``fold_in(base_key, n)`` (serving/sampling.py), so
a fresh tier re-derives the exact token stream and
``Router.submit(resume_from=...)`` suppresses the already-delivered
prefix through the SAME high-water wrapper that keeps failover replays
exactly-once (router.py).  Exactly-once ACROSS the crash, not just
across a replica.

On-disk format — segment-rotated JSONL, every line checksummed::

    <crc32 hex, 8 chars> <compact JSON payload>\n

Segments are ``journal-<n>.jsonl`` files in one directory, rotated at
``segment_bytes``; a writer never appends to a pre-existing segment (a
crashed process's torn tail stays exactly where the scan expects it —
at the end of a dead segment).  :func:`scan_journal` is torn-tail
tolerant the way ``restore_latest_intact`` is for checkpoints (PR 3):
a record that fails to parse or checksum is dropped and counted
(``records_dropped``), a bad FINAL record of the FINAL segment is the
expected crash signature (``torn_tail``), and missing segment numbers
are surfaced (``segment_gaps``) — recovery proceeds on everything that
survived instead of refusing.

``fsync_policy`` prices durability explicitly.  At EVERY policy an
``admitted`` record is flushed to the kernel before the append returns
— that is the WAL ack contract (a SIGKILLed process cannot lose a
request it acknowledged).  ``delivered``/``retired`` marks are safe to
lose (replay re-emits the suffix and SSE ids dedup it; a lost retire
merely re-runs a finished request to the same tokens), so outside
``always`` they ride the userspace buffer until the next flush:

* ``"never"`` — no fsync, ever (admitted marks survive the process
  dying, nothing is promised against the host dying);
* ``"interval"`` (default) — a background syncer thread flushes and
  ``os.fsync``-s at most every ``fsync_interval_s`` seconds when dirty
  (group commit: bounded host-crash exposure, and the ~ms fsync never
  rides the serving path);
* ``"always"`` — flush + fsync every append (a database WAL; the
  2 %-overhead bench gate runs the default policy,
  scripts/bench_crash.py measures all three).

Chaos: the ``journal-write`` site (utils/chaos.py) fires one event per
append.  ``kind="torn"`` writes a prefix of the encoded line and stops
(the crash-mid-write signature), ``kind="corrupt"`` flips one payload
byte (bit-rot), any other kind raises :class:`JournalWriteError` before
the write (a full disk).  All consultation is nil-guarded — a journal
built without an injector pays zero chaos instructions per append.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable

from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import SamplingParams

_SEG_PREFIX = "journal-"
_SEG_SUFFIX = ".jsonl"
FSYNC_POLICIES = ("never", "interval", "always")


class JournalWriteError(RuntimeError):
    """An append the journal could not land (I/O fault, chaos ``io``).

    On the ADMITTED path this propagates out of ``ServingDaemon.submit``
    — the caller is never acknowledged for a request the journal cannot
    back (the front door maps it to a 503).  On the delivered/retired
    paths the daemon counts it (``journal_errors``) and keeps serving:
    a sick journal degrades durability, never availability.
    """


def _segment_name(n: int) -> str:
    return f"{_SEG_PREFIX}{n:08d}{_SEG_SUFFIX}"


def _segment_index(name: str) -> int | None:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    digits = name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]
    return int(digits) if digits.isdigit() else None


def _encode(rec: dict) -> bytes:
    payload = json.dumps(rec, separators=(",", ":"), sort_keys=True)
    data = payload.encode("utf-8")
    return b"%08x " % zlib.crc32(data) + data + b"\n"


class RequestJournal:
    """Append-only, checksummed, segment-rotated request journal.

    Thread-safe: one lock serializes append/rotate/close — the daemon
    appends from its submit callers AND its delivery thread.  ``stats()``
    is the overhead ledger the bench gate reads (append count/bytes/
    seconds, fsyncs, rotations).
    """

    def __init__(self, directory: str, *,
                 fsync_policy: str = "interval",
                 fsync_interval_s: float = 0.05,
                 segment_bytes: int = 1 << 20,
                 chaos=None):
        if fsync_policy not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync_policy must be one of {FSYNC_POLICIES}, "
                f"got {fsync_policy!r}")
        if fsync_interval_s <= 0:
            raise ValueError(
                f"fsync_interval_s must be > 0, got {fsync_interval_s}")
        if segment_bytes < 1:
            raise ValueError(f"segment_bytes must be >= 1, got {segment_bytes}")
        self.directory = str(directory)
        self.fsync_policy = fsync_policy
        self.fsync_interval_s = float(fsync_interval_s)
        self.segment_bytes = int(segment_bytes)
        self._chaos = chaos
        os.makedirs(self.directory, exist_ok=True)
        # never reopen an existing segment: a previous process's torn
        # tail must stay at the end of ITS segment, where the scan's
        # torn-tail verdict expects it
        existing = [i for i in (_segment_index(n)
                                for n in os.listdir(self.directory))
                    if i is not None]
        self._seg_idx = (max(existing) + 1) if existing else 0
        self._lock = threading.Lock()
        self._fh = None
        self._seg_written = 0
        self._last_fsync = time.monotonic()
        self._closed = False
        self._stats = {"records": 0, "bytes": 0, "fsyncs": 0,
                       "rotations": 0, "append_s": 0.0, "errors": 0,
                       "chaos_torn": 0, "chaos_corrupt": 0,
                       "by_type": {"admitted": 0, "delivered": 0,
                                   "retired": 0}}
        # interval policy = group commit: appends only write + flush
        # (microseconds); a background syncer fsyncs every
        # fsync_interval_s WHEN dirty.  The durability contract is the
        # same — at most interval_s of exposure — but the ~1ms fsync
        # never rides the serving path, which is what keeps the bench's
        # 2% overhead gate honest.
        self._dirty = False
        self._syncer = None
        if self.fsync_policy == "interval":
            self._syncer = threading.Thread(
                target=self._sync_loop, name="journal-syncer", daemon=True)
            self._syncer.start()

    # ------------------------------------------------------------------
    # write side

    def append(self, rec: dict) -> None:
        """Land one record (checksummed line) per the fsync policy.
        Raises :class:`JournalWriteError` on any failure to write.

        ``append_s`` accounting: this thread's CPU time plus the wall
        time of any I/O the append actually awaited (flush/fsync).
        Wall-clock over the whole call would bill the journal for GIL
        preemptions that land inside the span — scheduler noise an
        order of magnitude above the journal's own work — and the
        bench's overhead gate would be measuring the scheduler.
        """
        t0 = time.thread_time()
        io_s = 0.0
        line = _encode(rec)
        with self._lock:
            if self._closed:
                raise JournalWriteError("journal is closed")
            torn = False
            if self._chaos is not None:          # nil-guarded, like every site
                event, spec = self._chaos.fire_event("journal-write")
                if spec is not None:
                    if spec.kind == "torn":
                        # crash-mid-write: a prefix lands, no newline —
                        # the scan must drop exactly this record
                        line = line[:max(1, len(line) // 2)]
                        torn = True
                        self._stats["chaos_torn"] += 1
                    elif spec.kind == "corrupt":
                        # bit-rot: full-length line, one payload byte
                        # flipped — the checksum must catch it
                        mid = len(line) // 2
                        line = (line[:mid]
                                + bytes([line[mid] ^ 0x01])
                                + line[mid + 1:])
                        self._stats["chaos_corrupt"] += 1
                    else:
                        self._stats["errors"] += 1
                        raise JournalWriteError(
                            f"chaos: injected {spec.kind!r} fault at site "
                            f"'journal-write' event {event}")
            try:
                if self._fh is None or self._seg_written >= self.segment_bytes:
                    self._rotate()
                self._fh.write(line)
                self._seg_written += len(line)
                self._dirty = True
                # flush discipline: `admitted` is the WAL ack contract —
                # it must reach the kernel before the submit returns, at
                # every policy.  delivered/retired marks are safe to
                # lose (replay re-emits, SSE ids dedup; a lost retire
                # re-runs a finished request to the same tokens), so
                # they ride the userspace buffer until the syncer, the
                # next admitted, a rotate, or close flushes them —
                # nothing but an 8-byte buffered write on the per-token
                # path.
                if self.fsync_policy == "always":
                    t_io = time.perf_counter()
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    io_s += time.perf_counter() - t_io
                    self._last_fsync = time.monotonic()
                    self._stats["fsyncs"] += 1
                    self._dirty = False
                elif rec.get("t") == "admitted":
                    t_io = time.perf_counter()
                    self._fh.flush()
                    io_s += time.perf_counter() - t_io
            except OSError as e:
                self._stats["errors"] += 1
                raise JournalWriteError(f"journal append failed: {e}") from e
            self._stats["records"] += 1
            self._stats["bytes"] += len(line)
            kind = rec.get("t")
            if kind in self._stats["by_type"]:
                self._stats["by_type"][kind] += 1
            self._stats["append_s"] += (time.thread_time() - t0) + io_s
            if torn:
                # the torn prefix has no newline: close the segment so
                # later appends (this process survived the "crash") land
                # in a fresh one instead of gluing onto the torn tail
                self._close_segment(sync=False)

    def _sync_loop(self) -> None:
        """Interval-policy background syncer: fsync when dirty, at most
        once per ``fsync_interval_s``.  Exits when the journal closes
        (close() does the final sync itself)."""
        while True:
            time.sleep(self.fsync_interval_s)
            with self._lock:
                if self._closed:
                    return
                if not self._dirty or self._fh is None:
                    continue
                # dup the fd so the ~ms fsync runs OUTSIDE the lock —
                # holding it would make some unlucky append pay the
                # fsync it was moved off-path to avoid (and the dup
                # survives a concurrent rotate closing the original)
                try:
                    self._fh.flush()   # buffered delivered/retired marks
                    fd = os.dup(self._fh.fileno())
                except OSError:
                    self._stats["errors"] += 1
                    continue
                self._dirty = False
            try:
                os.fsync(fd)
                with self._lock:
                    self._last_fsync = time.monotonic()
                    self._stats["fsyncs"] += 1
            except OSError:
                with self._lock:
                    self._dirty = True
                    self._stats["errors"] += 1
            finally:
                try:
                    os.close(fd)
                except OSError:
                    pass

    def _rotate(self) -> None:
        self._close_segment(sync=self.fsync_policy != "never")
        path = os.path.join(self.directory, _segment_name(self._seg_idx))
        self._seg_idx += 1
        self._fh = open(path, "ab")
        self._seg_written = 0
        self._stats["rotations"] += 1

    def _close_segment(self, sync: bool) -> None:
        if self._fh is None:
            return
        try:
            self._fh.flush()
            if sync:
                os.fsync(self._fh.fileno())
                self._stats["fsyncs"] += 1
        finally:
            self._fh.close()
            self._fh = None

    # convenience writers — the daemon's three journaling points

    def admitted(self, dr) -> None:
        """WAL the full identity of one :class:`~.daemon.DaemonRequest`
        (call BEFORE acknowledging the submit)."""
        self.append({
            "t": "admitted", "id": int(dr.id),
            "prompt": [int(t) for t in dr.prompt],
            "max_new": int(dr.max_new),
            "deadline_s": dr.deadline_s,
            "priority": int(dr.priority),
            "ttft_slo_s": dr.ttft_slo_s, "tpot_slo_s": dr.tpot_slo_s,
            "sampling": (dr.sampling.to_dict()
                         if dr.sampling is not None else None),
            "key": dr.idempotency_key,
            "fp": dr.fingerprint,
            "resume_from": int(dr.resume_from),
            # the W3C traceparent, so a post-crash replay CONTINUES the
            # request's distributed trace instead of starting a new one
            "tp": (dr.trace_ctx.to_traceparent()
                   if getattr(dr, "trace_ctx", None) is not None else None),
            "wall_t": time.time(),
        })

    def delivered(self, rid: int, n: int) -> None:
        """High-water: the client has been handed tokens ``[0, n)`` (in
        LOGICAL indices — a recovered request's count includes the
        suppressed prefix it resumed past)."""
        self.append({"t": "delivered", "id": int(rid), "n": int(n)})

    def retired(self, rid: int, status: str, error: str | None) -> None:
        self.append({"t": "retired", "id": int(rid), "status": str(status),
                     "error": error})

    def sync(self) -> None:
        """Force everything buffered onto the disk, regardless of policy."""
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._last_fsync = time.monotonic()
                self._stats["fsyncs"] += 1

    def close(self) -> None:
        """Flush + fsync + close the active segment.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._close_segment(sync=True)

    def __enter__(self) -> "RequestJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["by_type"] = dict(self._stats["by_type"])
            out["policy"] = self.fsync_policy
            out["segments"] = self._seg_idx
            return out


# ----------------------------------------------------------------------
# read side: the torn-tail-tolerant recovery scan


@dataclass
class JournalScan:
    """What survived on disk, folded into per-request state.

    ``requests`` maps request id -> ``{"meta": <admitted record>,
    "delivered": <logical high-water>, "retired": <status | None>}``.
    ``records_dropped`` counts lines that failed to parse or checksum
    (``torn_tail`` flags the expected crash signature: the bad record
    was the LAST line of the LAST segment); ``orphan_records`` counts
    delivered/retired records whose admitted record did not survive —
    nothing can be replayed for those, so they are surfaced, not
    silently absorbed.
    """

    directory: str
    requests: dict = field(default_factory=dict)
    records: int = 0
    records_dropped: int = 0
    torn_tail: bool = False
    orphan_records: int = 0
    segments: list = field(default_factory=list)
    segment_gaps: list = field(default_factory=list)

    def incomplete(self) -> list:
        """Admitted-but-never-retired request states, in id order — the
        replay set."""
        return [state for _rid, state in sorted(self.requests.items())
                if state["retired"] is None]

    def report(self) -> dict:
        retired = sum(1 for s in self.requests.values()
                      if s["retired"] is not None)
        return {
            "records": self.records,
            "journal_records_dropped": self.records_dropped,
            "torn_tail": self.torn_tail,
            "orphan_records": self.orphan_records,
            "segments": len(self.segments),
            "segment_gaps": list(self.segment_gaps),
            "requests": len(self.requests),
            "retired": retired,
            "incomplete": len(self.requests) - retired,
        }


def scan_journal(directory: str) -> JournalScan:
    """Read every segment, drop exactly what cannot be trusted.

    Tolerates: a torn final record (crash mid-append), bit-flipped
    checksums anywhere, empty segments, and missing segment numbers —
    each dropped record costs exactly itself, never the scan.
    """
    scan = JournalScan(directory=str(directory))
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return scan
    numbered = sorted((i, n) for i, n in
                      ((_segment_index(n), n) for n in names)
                      if i is not None)
    scan.segments = [n for _i, n in numbered]
    for prev, cur in zip(numbered, numbered[1:]):
        for missing in range(prev[0] + 1, cur[0]):
            scan.segment_gaps.append(_segment_name(missing))
    for seg_pos, (_idx, name) in enumerate(numbered):
        with open(os.path.join(directory, name), "rb") as fh:
            lines = fh.read().split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()   # trailing newline, not an empty record
        for line_pos, raw in enumerate(lines):
            rec = _decode(raw)
            if rec is None:
                scan.records_dropped += 1
                if (seg_pos == len(numbered) - 1
                        and line_pos == len(lines) - 1):
                    scan.torn_tail = True
                continue
            scan.records += 1
            _apply(scan, rec)
    return scan


def _decode(raw: bytes) -> dict | None:
    if len(raw) < 10 or raw[8:9] != b" ":
        return None
    try:
        if int(raw[:8], 16) != zlib.crc32(raw[9:]):
            return None
        rec = json.loads(raw[9:])
    except (ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def _apply(scan: JournalScan, rec: dict) -> None:
    kind, rid = rec.get("t"), rec.get("id")
    if not isinstance(rid, int):
        scan.records_dropped += 1
        scan.records -= 1
        return
    if kind == "admitted":
        scan.requests[rid] = {"meta": rec,
                              "delivered": int(rec.get("resume_from") or 0),
                              "retired": None}
    elif kind == "delivered":
        state = scan.requests.get(rid)
        if state is None:
            scan.orphan_records += 1
        else:
            state["delivered"] = max(state["delivered"], int(rec.get("n", 0)))
    elif kind == "retired":
        state = scan.requests.get(rid)
        if state is None:
            scan.orphan_records += 1
        else:
            state["retired"] = rec.get("status", "done")
    else:
        scan.orphan_records += 1


# ----------------------------------------------------------------------
# whole-process recovery


@dataclass
class RecoveredRequest:
    """One incomplete journal entry re-submitted into the fresh tier."""

    orig_id: int                 # id in the CRASHED process's journal
    dr: object                   # the fresh DaemonRequest serving it
    resume_from: int             # delivered high-water it resumed past
    idempotency_key: str | None


@dataclass
class Recovery:
    """The rebuilt tier plus the replay ledger.

    ``bindings`` seeds ``FrontDoor(idempotency_bindings=...)`` so a
    client's retried POST (same ``Idempotency-Key``) binds to the
    replayed request instead of double-executing — the cross-crash half
    of the front door's dedup table.
    """

    daemon: object
    scan: JournalScan
    requests: list
    bindings: dict

    def wait(self, timeout: float | None = None) -> bool:
        """Block until every replayed request is terminal."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for rec in self.requests:
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            if not rec.dr.wait(left):
                return False
        return True

    def report(self) -> dict:
        out = self.scan.report()
        out["replayed"] = len(self.requests)
        out["rebound_keys"] = len(self.bindings)
        return out


def recover(journal, make_daemon: Callable, *, start: bool = True,
            resubmit_timeout_s: float = 60.0) -> Recovery:
    """Rebuild a serving tier from what the journal preserved.

    ``journal`` is a journal directory path (or a
    :class:`RequestJournal`, whose directory is used).  ``make_daemon``
    builds the fresh :class:`~.daemon.ServingDaemon` — wire a NEW
    journal into it (same directory is fine: segments are never
    reopened) and the re-admissions are re-journaled with their original
    idempotency keys, so recovery composes: a crash during recovery
    recovers.  The fresh daemon's id counter is bumped past every
    journaled id (no cross-generation collisions) and each crashed
    entry is closed with a ``retired(status="replayed")`` record the
    moment its replacement is admitted — the replay's own admitted
    record carries the request from there.

    Every admitted-but-not-retired request is re-submitted with its
    original identity and ``resume_from=<delivered high-water>``: the
    stream is a pure function of its seed (sampling.py), so the replay
    re-derives the exact tokens and the router's high-water wrapper
    suppresses the prefix the client already received.  Deadlines are
    re-anchored by wall-clock elapsed time (the journal stamps
    ``wall_t``): a request that lapsed while the process was dead is
    re-admitted already overdue and retires ``cancelled`` — counted,
    journaled, never silently dropped.
    """
    directory = (journal.directory if isinstance(journal, RequestJournal)
                 else str(journal))
    scan = scan_journal(directory)
    daemon = make_daemon()
    if scan.requests:
        # fresh ids must never collide with journaled ids: the replay's
        # own admitted/delivered/retired records would otherwise fold
        # into a DIFFERENT crashed request's state on the next scan
        daemon._ids = max(daemon._ids, max(scan.requests) + 1)
    if start and not daemon._started:
        daemon.start()
    requests: list[RecoveredRequest] = []
    bindings: dict[str, object] = {}
    now_wall = time.time()
    for state in scan.incomplete():
        meta = state["meta"]
        sampling = (SamplingParams.from_dict(meta["sampling"])
                    if meta.get("sampling") else None)
        deadline = meta.get("deadline_s")
        if deadline is not None:
            elapsed = max(0.0, now_wall - float(meta.get("wall_t", now_wall)))
            # 1e-9, not 0: an already-lapsed deadline must still ADMIT so
            # the dispatcher retires it down the normal cancelled path
            deadline = max(float(deadline) - elapsed, 1e-9)
        dr = _submit_with_retry(
            daemon, meta, sampling, deadline, state["delivered"],
            resubmit_timeout_s)
        if daemon._journal is not None:
            # close the crashed entry: its replay's OWN admitted record
            # (fresh id, resume_from baked in) now carries the request,
            # so a crash during recovery replays the replay, once
            try:
                daemon._journal.retired(
                    int(meta["id"]), "replayed",
                    f"resumed as request {dr.id}")
            except Exception:
                pass   # degraded durability must not abort recovery
        requests.append(RecoveredRequest(
            orig_id=int(meta["id"]), dr=dr,
            resume_from=int(state["delivered"]),
            idempotency_key=meta.get("key")))
        if meta.get("key"):
            bindings[meta["key"]] = dr
    return Recovery(daemon=daemon, scan=scan, requests=requests,
                    bindings=bindings)


def _submit_with_retry(daemon, meta, sampling, deadline, resume_from,
                       timeout_s: float):
    """Re-admit one journaled request, waiting out transient QueueFull
    (the replay set may exceed ``max_queue``; the dispatcher drains it)."""
    from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
        QueueFull,
    )
    from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
        TraceContext,
    )
    # the crashed process's traceparent: the replay CONTINUES that trace
    # (same trace id; this hop parents under the journaled span via the
    # parent_ctx hex edge in the merged export)
    trace_ctx = TraceContext.parse_traceparent(meta.get("tp"))
    give_up = time.monotonic() + timeout_s
    while True:
        try:
            return daemon.submit(
                meta["prompt"], meta["max_new"], deadline_s=deadline,
                priority=int(meta.get("priority") or 0),
                ttft_slo_s=meta.get("ttft_slo_s"),
                tpot_slo_s=meta.get("tpot_slo_s"),
                sampling=sampling,
                idempotency_key=meta.get("key"),
                resume_from=int(resume_from),
                trace_ctx=trace_ctx)
        except QueueFull:
            if time.monotonic() >= give_up:
                raise
            time.sleep(daemon.watchdog_interval_s)

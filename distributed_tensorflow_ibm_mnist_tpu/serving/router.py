"""Multi-replica serving tier: least-loaded routing, failover, hot swap.

One :class:`~.engine.InferenceEngine` is a single failure domain and a
single weight version.  The north-star traffic (ROADMAP) needs N of them —
and the moment there are N, three problems exist that the single-engine
contract never had to answer: WHERE does a request go (routing), what
happens to accepted work when a replica dies (failover), and how do
serving weights track a trainer that never stops (hot swap).  This module
is that layer — the TF-Replicator / TensorFlow-paper separation of cluster
topology from the step function (PAPERS.md), applied one level above the
engine: the engine multiplexes requests over slots; the router multiplexes
REPLICAS over failures and weight versions.

Routing — :meth:`Router.submit` picks the HEALTHY replica with the lowest
live load score (queued + parked + occupied requests, KV-pool fraction as
tiebreak — serving/replica.py); per-replica bounded queues still raise
``QueueFull`` when EVERY candidate is saturated (backpressure surfaces,
never buffers unboundedly).

Failover — when a replica raises an engine-wide fault (EngineStalled, a
decode fault with no watchdog) or flunks its health probe, the router
closes it and harvests exactly the requests the ENGINE gave up on:
``Request.engine_fault`` marks terminal states that are collateral of the
engine-wide fault (failed in-flight rows, close-cancelled queued/parked
work) as opposed to a request's OWN failure (poisoned prompt, raising
callback, lapsed deadline) — own failures stay failed, exactly the
single-engine isolation contract.  Collateral requests re-dispatch to
survivors with the failed replica excluded (the ``excluded``-set retry
pattern) and their REMAINING deadline recomputed.  A re-dispatched request
regenerates from token zero — decode is deterministic per request (greedy
by construction; sampled because a stream is a pure function of its
``SamplingParams`` seed, serving/sampling.py), so the replayed prefix is
token-identical and the per-request delivered-token high-water mark turns
at-most-once delivery per attempt into exactly-once delivery per TOKEN
across attempts, greedy and sampled alike (ISSUE 13; chaos-gated in
tests/test_sampling.py).

Hot swap — :class:`WeightWatcher` polls the trainer's checkpoint directory
on its OWN read-only :class:`~..utils.checkpoint.CheckpointManager` (its
``restore_latest_intact`` waits on ITS manager's in-flight saves — none —
so polling can never block the trainer's save pipeline) and validates new
steps through the full intact-walk (torn newest step → previous intact
one).  A validated step swaps into replicas ONE at a time: drain (stop
dispatching to the replica, keep pumping it until idle while the others
absorb traffic) → ``engine.swap_params`` (stale prefix/radix caches
dropped) → re-admit.  Zero requests drop by construction: draining never
cancels, and N−1 replicas keep serving throughout.

Disaggregation (ISSUE 16) — ``roles=`` types each replica: admissions
dispatch only to ``prefill``/``both`` capacity (least-loaded among them),
and each router step drains the prefill replicas' outboxes of finished
prefills (:mod:`~.kv_handoff` packets), delivering each to the
least-loaded ``decode``/``both`` replica via ``admit_prefilled``.  A
destination that cannot take a packet RIGHT NOW (no free slot, dry pool)
re-parks it on its source — admission-stall semantics, retried every
pump — and the source-side page hold is released only on confirmed
delivery (deferred source-free), so a transfer that dies anywhere leaves
the request re-dispatchable down the normal prefill path.  A tier with no
role-typed replica (all ``"both"``, the default) takes ZERO handoff
paths — the monolithic behavior is unchanged.

Chaos sites (utils/chaos.py): ``router-dispatch`` fires once per
router→replica dispatch attempt — a hit excludes that replica for THAT
request and retries the next-best survivor; ``weight-swap`` fires once per
swap attempt after the drain and before the params replacement — a hit
re-admits the replica on its OLD weights (the swap is all-or-nothing) and
the watcher retries at the next poll; ``kv-handoff`` fires once per
handoff delivery attempt — a hit releases the source hold and re-dispatches
the request (the delivered high-water mark keeps the replay exactly-once).
All follow the engine's nil-guard pattern: zero chaos instructions when
unwired.

Tracing: all replicas share ONE tracer; each gets its own track
(``replica <i>``), so N host loops render as N lanes, with
``replica_failed`` / ``failover_redispatch`` / ``weight_swap`` instants on
the lane they happened to.  The router itself is single-threaded like the
engine — one thread calls submit/step/close — and that is still how the
step-pumped benchmarks drive it.  The daemonized tier
(serving/daemon.py) is the concurrency seam: it serializes every
router-level mutation (submit/dispatch, failover, orphan retry) under
its tier lock and gives each replica its own pump thread, so the router
never needs internal locks of its own.
"""

from __future__ import annotations

import contextlib
import itertools
import time
from typing import Callable

import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.serving.engine import EngineStalled
from distributed_tensorflow_ibm_mnist_tpu.serving.replica import (
    DRAINING,
    FAILED,
    HEALTHY,
    Replica,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import QueueFull, Request
from distributed_tensorflow_ibm_mnist_tpu.serving.stats import ServingStats


class NoHealthyReplica(RuntimeError):
    """Every replica is FAILED/DRAINING (or excluded for this request) —
    the router cannot place work.  Distinct from :class:`QueueFull`
    (healthy replicas exist but all their queues are at bound)."""


class RouterRequest:
    """One LOGICAL request across however many engine attempts it takes.

    The router owns the identity; each dispatch creates a fresh engine
    :class:`Request` (the attempt).  ``status``/``generated``/``error``
    delegate to the CURRENT attempt, so a failed-over request reads like
    any other once its retry completes.  ``delivered`` is the streaming
    high-water mark: attempt-local token counts below it are replayed
    prefix (suppressed), above it are new tokens (delivered once).
    """

    def __init__(self, rid: int, tokens, max_new: int,
                 deadline_s: float | None, submit_t: float,
                 callback: Callable | None,
                 ttft_slo_s: float | None = None,
                 tpot_slo_s: float | None = None,
                 sampling=None, resume_from: int = 0,
                 trace_ctx=None, trace_parent: int | None = None):
        self.id = rid
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.deadline_s = deadline_s      # relative to submit_t, like Request
        self.submit_t = submit_t          # router clock at FIRST dispatch
        self.callback = callback          # the USER's hook; router wraps it
        # per-request SamplingParams, identical on every attempt — the
        # seed makes a failover replay token-identical, which is what
        # keeps the delivered high-water mark exactly-once for SAMPLED
        # streams too (module docstring)
        self.sampling = sampling
        # SLO targets ride along to every attempt's engine Request.  The
        # SLO clock is PER-ATTEMPT (each attempt's submit_t), matching
        # deadline_s semantics: a failed-over attempt is judged on its own
        # service time, and the failover cost itself shows up as the dead
        # attempt's miss in the merged slo_miss counter
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.req: Request | None = None   # current engine attempt
        self.replica: int | None = None   # current attempt's replica index
        self.attempts: list[tuple[int, Request]] = []
        self.excluded: set[int] = set()   # replicas barred for THIS request
        self.redispatches = 0
        # cross-attempt delivery high-water.  Seeding it above 0
        # (``resume_from`` — crash recovery, serving/journal.py) makes
        # the FIRST attempt replay like a failover retry: the engine
        # regenerates the stream from scratch (pure function of the
        # seed), and the wrapper below suppresses everything at or below
        # the mark — the tokens a pre-crash client already received.
        self.resume_from = int(resume_from)
        self.delivered = self.resume_from
        self._attempt_delivered = 0       # tokens seen in the CURRENT attempt
        # router-level terminal override: set when the ROUTER ends the
        # request (deadline lapsed between attempts, no replica left)
        self.final_status: str | None = None
        self.final_error: str | None = None
        # distributed tracing: the W3C TraceContext this request carries
        # (None for untraced callers) and the span id — in the SHARED
        # tier tracer — that each attempt's engine span should parent
        # under (the daemon's per-request root).  ``_last_attempt_span``
        # is the previous attempt's engine span id: a failover replay
        # attaches it as a span LINK, so the replay reads as a
        # continuation of the original attempt, not a silent restart.
        self.trace_ctx = trace_ctx
        self.trace_parent = trace_parent
        self._last_attempt_span: int | None = None

    @property
    def status(self) -> str:
        if self.final_status is not None:
            return self.final_status
        return self.req.status if self.req is not None else "queued"

    @property
    def generated(self) -> list[int]:
        return self.req.generated if self.req is not None else []

    @property
    def logprobs(self) -> list[float]:
        return self.req.logprobs if self.req is not None else []

    @property
    def error(self) -> str | None:
        if self.final_error is not None:
            return self.final_error
        return self.req.error if self.req is not None else None

    @property
    def done(self) -> bool:
        """Terminal at the ROUTER level: a terminal engine status only
        counts once the router has decided not to re-dispatch it (an
        engine_fault casualty is terminal for the ATTEMPT, transit for the
        request — the failover harvest resolves it synchronously)."""
        if self.final_status is not None:
            return True
        return (self.req is not None and not self.req.engine_fault
                and self.req.status in ("done", "cancelled", "failed"))

    @property
    def overdue_at(self) -> float:
        return (np.inf if self.deadline_s is None
                else self.submit_t + self.deadline_s)


class Router:
    """Front N engine replicas: see the module docstring.

    ``make_engine(trace_tid)`` is the replica factory (serving/replica.py
    — wire ``compile_cache_dir=`` there for warm respawns, share this
    router's ``clock`` for deadline coherence, leave ``writer=`` unset).
    A two-parameter factory ``make_engine(trace_tid, replica_index)``
    composes replicas x tensor parallelism: give replica ``i`` the
    ``i``-th disjoint device group from ``parallel.tensor_parallel.
    tp_device_groups(n_replicas, tp)`` as its ``tp_devices=`` — failover,
    probes, and hot-swap then work unchanged (the engine re-shards a
    swapped host tree onto its own mesh; ``ServingStats.merge`` rolls
    per-chip bytes up as max-per-chip + cluster totals).
    ``probe=`` optionally layers a policy health check (``probe(replica)
    -> bool``) over the structural one; a False verdict fails the replica
    exactly like an engine-wide fault.  ``max_drain_steps`` bounds how
    long a hot-swap drain may pump before giving up (the replica re-admits
    on its old weights — never a hang, never a drop).
    """

    def __init__(self, make_engine: Callable, n_replicas: int, *,
                 clock: Callable[[], float] = time.monotonic,
                 chaos=None, tracer=None, writer=None,
                 probe: Callable | None = None,
                 max_drain_steps: int = 10_000,
                 telemetry=None, roles: list | None = None):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        if roles is not None and len(roles) != n_replicas:
            raise ValueError(
                f"roles has {len(roles)} entries for {n_replicas} replicas")
        self.clock = clock
        self._chaos = chaos
        self._tracer = tracer
        # utils/telemetry.Telemetry | None, nil-guarded like _chaos.  The
        # router's source reports cluster state + per-replica vitals
        # (state/load/heartbeat — serving/replica.Replica.vitals); wire
        # the SAME object into the factory's engines for per-engine
        # queue/pool vitals alongside
        self._telemetry = telemetry
        if telemetry is not None:
            telemetry.register_source("router", self._telemetry_vitals)
        self.writer = writer
        self._probe = probe
        self.max_drain_steps = int(max_drain_steps)
        self.tid = tracer.track("router") if tracer is not None else 0
        # kept for elastic capacity (ISSUE 17): add_replica() builds new
        # replicas through the SAME factory construction built with — a
        # factory wired to the persistent compile cache makes every
        # scale-up spawn warm, which is what makes elasticity affordable
        self._make_engine = make_engine
        self.replicas = [
            Replica(i, make_engine, tracer=tracer,
                    role=(roles[i] if roles is not None else "both"))
            for i in range(n_replicas)]
        for rep in self.replicas:
            rep.spawn()
        if roles is not None and not any(
                r.role in ("prefill", "both") for r in self.replicas):
            raise ValueError(
                "roles leaves no prefill-capable replica — nothing could "
                "ever admit a prompt")
        if roles is not None and not any(
                r.role in ("decode", "both") for r in self.replicas):
            raise ValueError(
                "roles leaves no decode-capable replica — nothing could "
                "ever produce a token")
        self.handoffs = 0        # packets delivered prefill → decode
        self.handoff_faults = 0  # kv-handoff chaos hits (re-dispatched)
        # daemon seam: ``admit_prefilled`` mutates the DESTINATION engine,
        # which in the daemonized tier is concurrently stepped by its own
        # pump thread — the daemon installs a per-replica lock factory
        # here (``_admit_guard(replica) -> context manager``) so the
        # landing serializes with that pump.  The step-pumped tier is
        # single-threaded and leaves it None (zero overhead).
        self._admit_guard: Callable | None = None
        self._ids = itertools.count()
        self.requests: list[RouterRequest] = []   # submit order, forever
        # engine Request (by object identity) -> owning RouterRequest: the
        # failover harvest walks a dead engine's completed list and needs
        # the logical request each casualty belongs to
        self._owner: dict[int, RouterRequest] = {}
        # accepted-then-unplaceable requests (failover raced a full/absent
        # survivor): re-dispatched every step until they land or lapse —
        # the zero-drop guarantee under transient backpressure
        self._orphans: list[RouterRequest] = []
        self.failovers = 0   # replicas failed over
        self.retires = 0     # replicas drained and retired (scale-down)
        self.scale_ups = 0   # replicas added/restarted for capacity
        # replica indices mid-retire: DRAINING (undispatchable, still
        # pumped) until idle, then closed clean by finish_retires()
        self._retiring: set[int] = set()
        self.swapped_steps: list[int] = []  # checkpoint steps hot-swapped in
        # the newest (params, step) any hot_swap delivered: a restarted
        # replica re-applies these — the factory rebuilds on its ORIGINAL
        # params, which are stale the moment a swap has happened
        self._current_weights: tuple | None = None
        self._closed = False

    # ------------------------------------------------------------------
    # dispatch

    def healthy(self) -> list[Replica]:
        return [r for r in self.replicas if r.state == HEALTHY and r.alive]

    def submit(self, prompt, max_new: int, deadline_s: float | None = None,
               callback: Callable | None = None,
               ttft_slo_s: float | None = None,
               tpot_slo_s: float | None = None,
               sampling=None, resume_from: int = 0,
               trace_ctx=None, trace_parent: int | None = None
               ) -> RouterRequest:
        """Place one request on the least-loaded healthy replica.  Raises
        :class:`NoHealthyReplica` when no replica can be tried and
        :class:`QueueFull` when every healthy replica's queue is at bound
        (backpressure — the caller sheds or retries, as with one engine).
        ``ttft_slo_s``/``tpot_slo_s`` ride to every attempt (see
        :class:`RouterRequest` for the per-attempt clock semantics);
        ``sampling`` (serving/sampling.SamplingParams) rides identically,
        so a failover replay consumes the same seed.  ``resume_from``
        (crash recovery — serving/journal.py) seeds the delivered
        high-water mark: the first attempt regenerates the whole stream
        but only tokens past the mark reach ``callback``.  ``trace_ctx``
        (utils/tracing.TraceContext) joins every attempt's engine spans
        into the request's distributed trace; ``trace_parent`` is the
        caller's span id in the SHARED tier tracer (the attempt spans
        re-parent under it)."""
        if self._closed:
            raise RuntimeError("router is closed")
        if resume_from < 0:
            raise ValueError(f"resume_from must be >= 0, got {resume_from}")
        rr = RouterRequest(next(self._ids), prompt, max_new, deadline_s,
                           self.clock(), callback,
                           ttft_slo_s=ttft_slo_s, tpot_slo_s=tpot_slo_s,
                           sampling=sampling, resume_from=resume_from,
                           trace_ctx=trace_ctx, trace_parent=trace_parent)
        self._dispatch(rr)   # propagates QueueFull / NoHealthyReplica
        self.requests.append(rr)
        return rr

    def cancel(self, rr: RouterRequest,
               reason: str = "cancelled by caller") -> bool:
        """Cancel one logical request wherever it currently is (ISSUE 17
        — the client-disconnect path).  Returns False when ``rr`` is
        already terminal, True when cancellation was initiated.

        No new teardown machinery: the deadline clocks the request rides
        are forced into the past, so the SAME sweeps that retire a lapsed
        deadline collect it — the engine's per-iteration sweep for
        running/prefilling rows (slot freed, pages freed, tracer span
        closed), ``scheduler.pop`` for engine-queued ones, the handoff
        pump for parked prefill packets, orphan retry for unplaced
        requests.  A deadline-cancel is the request's OWN terminal state
        (``engine_fault`` stays False), so failover never resurrects it.
        Call under the tier lock in the daemonized tier (the daemon's
        :meth:`~.daemon.ServingDaemon.cancel` does)."""
        if rr.done:
            return False
        rr.deadline_s = -1e18   # overdue everywhere, immediately
        req = rr.req
        if req is not None and req.status not in ("done", "cancelled",
                                                  "failed"):
            req.deadline_s = -1e18
        elif req is None and rr.final_status is None:
            # never dispatched (or orphaned pre-attempt): terminal now —
            # nothing downstream holds resources for it
            rr.final_status = "cancelled"
            rr.final_error = reason
        if self._tracer is not None:
            self._tracer.instant("request_cancelled", cat="router",
                                 tid=self.tid, request=rr.id, reason=reason)
        return True

    def _wrap_callback(self, rr: RouterRequest) -> Callable:
        def _cb(_req, tok):
            rr._attempt_delivered += 1
            if rr._attempt_delivered > rr.delivered:
                rr.delivered = rr._attempt_delivered
                if rr.callback is not None:
                    rr.callback(rr, tok)
        return _cb

    def _dispatch(self, rr: RouterRequest) -> None:
        """Place ``rr`` on the best candidate, walking the load order.

        Durable exclusions (``rr.excluded``) are replicas that FAILED this
        request — a chaos ``router-dispatch`` hit or the replica it died
        on; ``QueueFull`` is transient backpressure, so a full replica is
        skipped this round but stays eligible for a later re-dispatch.
        """
        full: list[Replica] = []
        while True:
            # admissions go to PREFILL capacity: decode-role replicas take
            # no prompts (their engines refuse submit() outright) — their
            # work arrives as handoff packets through _pump_handoffs
            cands = sorted(
                (r for r in self.healthy()
                 if r.role in ("prefill", "both")
                 and r.index not in rr.excluded and r not in full),
                key=lambda r: r.load)
            if not cands:
                if full:
                    raise QueueFull(
                        f"every healthy replica's queue is at bound "
                        f"({len(full)} tried) — retry later or shed load")
                raise NoHealthyReplica(
                    f"no healthy replica to place request {rr.id} on "
                    f"({len(self.replicas)} total, {len(rr.excluded)} "
                    "excluded for this request)")
            rep = cands[0]
            if self._chaos is not None:
                # one router-dispatch event per ATTEMPT, so seeded plans
                # are stable across retries; a hit bars this replica for
                # this request only (at-most-once per replica)
                spec = self._chaos.fire("router-dispatch")
                if spec is not None:
                    rr.excluded.add(rep.index)
                    if self._tracer is not None:
                        self._tracer.instant(
                            "dispatch_fault", cat="router", tid=self.tid,
                            request=rr.id, replica=rep.index,
                            fault_kind=spec.kind)
                    continue
            remaining = None
            if rr.deadline_s is not None:
                remaining = rr.overdue_at - self.clock()
                if remaining <= 0:
                    rr.final_status = "cancelled"
                    return
            try:
                req = rep.engine.submit(rr.tokens, rr.max_new,
                                        deadline_s=remaining,
                                        callback=self._wrap_callback(rr),
                                        ttft_slo_s=rr.ttft_slo_s,
                                        tpot_slo_s=rr.tpot_slo_s,
                                        sampling=rr.sampling)
            except QueueFull:
                full.append(rep)
                continue
            rr.req = req
            rr.replica = rep.index
            rr.attempts.append((rep.index, req))
            rr._attempt_delivered = 0
            self._owner[id(req)] = rr
            if rr.trace_ctx is not None:
                # distributed trace join: stamp the context on the engine
                # attempt (exemplars + handoff packets read it) and claim
                # the engine's request span for the trace — re-parented
                # under the daemon's span, replays LINKED to the attempt
                # they replace (not silent restarts)
                req.trace_ctx = rr.trace_ctx
                if self._tracer is not None and req.trace is not None:
                    prior = rr._last_attempt_span
                    self._tracer.annotate(
                        req.trace["id"], parent=rr.trace_parent,
                        links=[prior] if prior is not None else None,
                        trace=rr.trace_ctx.trace_id,
                        sampled=rr.trace_ctx.sampled,
                        attempt=len(rr.attempts), replica=rep.index)
                    rr._last_attempt_span = req.trace["id"]
            return

    # ------------------------------------------------------------------
    # the pump

    def step(self) -> int:
        """One cluster iteration: probe health, pump every live replica one
        host-loop step, retry orphans.  Engine-wide faults become replica
        failovers IN this step (collateral harvested and re-dispatched
        before returning).  Returns real tokens produced."""
        if self._closed:
            raise RuntimeError("router is closed")
        produced = 0
        for rep in self.replicas:
            if rep.state == FAILED or not rep.alive:
                continue
            try:
                if (rep.state == HEALTHY and self._probe is not None
                        and not self._probe(rep)):
                    raise RuntimeError("health probe failed")
                if not rep.engine.has_work:
                    continue
                produced += rep.engine.step()
            except Exception as e:
                # per-request faults never propagate from step() (the
                # single-engine isolation contract) — anything that does
                # is engine-wide: EngineStalled after the watchdog, a raw
                # decode fault without one, a probe that raised instead of
                # returning False.  The blast radius is ONE replica: fail
                # it over and keep pumping the siblings this same
                # iteration (a raising probe used to propagate out of
                # step() and starve every replica after it in the loop).
                if rep.state != FAILED:
                    try:
                        self._fail_replica(rep, e)
                    except Exception as fe:
                        # failover machinery itself failing (a close that
                        # raises mid-harvest) still must not starve
                        # siblings; the replica is already marked FAILED
                        # (first statement of _fail_replica), so nothing
                        # re-dispatches to it
                        if self._tracer is not None:
                            self._tracer.instant(
                                "failover_error", cat="router", tid=rep.tid,
                                replica=rep.index,
                                error=f"{type(fe).__name__}: {fe}")
        self._pump_handoffs()
        if self._retiring:
            self.finish_retires()
        if self._orphans:
            self._retry_orphans()
        if self._telemetry is not None:
            self._telemetry.maybe_sample()
        return produced

    # ------------------------------------------------------------------
    # prefill → decode handoff (disaggregated tiers; module docstring)

    def _handoff_target(self, rr: RouterRequest | None):
        """Least-loaded healthy DECODE-capable replica eligible for this
        request, or None (re-park and retry next pump)."""
        excluded = rr.excluded if rr is not None else set()
        cands = sorted(
            (r for r in self.healthy()
             if r.role in ("decode", "both") and r.index not in excluded),
            key=lambda r: r.load)
        return cands[0] if cands else None

    def _pump_handoffs(self) -> int:
        """Drain every live prefill-capable replica's outbox, delivering
        each packet to decode capacity.  Undeliverable packets re-park on
        their SOURCE outbox (pages still held — deferred source-free), so
        a source that later dies converts them to engine_fault casualties
        via its close() and the ordinary failover harvest.  Returns
        packets delivered this pump."""
        delivered = 0
        for rep in self.replicas:
            if rep.state == FAILED or not rep.alive:
                continue
            outbox = getattr(rep.engine, "_outbox", None)
            if not outbox:
                continue
            for _ in range(len(outbox)):
                packet = outbox.popleft()
                rr = self._owner.get(id(packet.req))
                if rr is not None and rr.req is not packet.req:
                    # a stale attempt's packet (the request already failed
                    # over while parked): the hold is all that's left
                    packet.release()
                    continue
                if rr is not None and self.clock() > rr.overdue_at:
                    packet.release()
                    rr.final_status = "cancelled"
                    rep.engine._tr_close(packet.req, status="cancelled")
                    continue
                if self._chaos is not None:
                    # one kv-handoff event per delivery ATTEMPT: a hit is
                    # the transfer dying in flight
                    spec = self._chaos.fire("kv-handoff")
                    if spec is not None:
                        self.handoff_faults += 1
                        self._handoff_fault(rep, packet, rr, spec)
                        continue
                dest = self._handoff_target(rr)
                if dest is None:
                    outbox.append(packet)
                    continue
                guard = (self._admit_guard(dest)
                         if self._admit_guard is not None
                         else contextlib.nullcontext())
                try:
                    with guard:
                        ok = dest.engine.admit_prefilled(packet)
                except Exception as e:
                    # engine-wide destination fault (the landing tail's
                    # own failures return True): re-park, fail the dest —
                    # its harvest runs now, the packet retries next pump
                    outbox.append(packet)
                    if dest.state != FAILED:
                        self._fail_replica(dest, e)
                    continue
                if not ok:
                    outbox.append(packet)   # no slot / dry pool: stall
                    continue
                packet.release()
                delivered += 1
                self.handoffs += 1
                if rr is not None:
                    rr.replica = dest.index
                if self._tracer is not None:
                    kw = {}
                    t = getattr(packet.req, "trace", None)
                    if t is not None:
                        kw["parent"] = t["id"]
                    if packet.trace_ctx is not None:
                        kw["trace"] = packet.trace_ctx.trace_id
                    self._tracer.instant(
                        "handoff_delivered", cat="router", tid=dest.tid,
                        request=getattr(packet.req, "id", None),
                        source=rep.index, replica=dest.index,
                        pages=len(packet.payloads),
                        bytes=packet.payload_bytes, **kw)
        return delivered

    def _handoff_fault(self, rep: Replica, packet, rr: RouterRequest | None,
                       spec) -> None:
        """A kv-handoff chaos hit: the in-flight transfer died.  Release
        the source hold, close out the dead attempt, and re-dispatch the
        request down the normal prefill path — the source is NOT excluded
        (its trie still holds the prompt's blocks, making it the cheapest
        retry), and the delivered high-water mark keeps the replayed
        prefix exactly-once."""
        packet.release()
        req = packet.req
        req.engine_fault = True
        req.status = "cancelled"
        req.finish_t = self.clock()
        rep.engine._tr_close(req, status="cancelled")
        rep.engine.completed.append(req)
        rep.engine.stats.add(req)
        if self._tracer is not None:
            self._tracer.instant(
                "handoff_fault", cat="router", tid=rep.tid,
                request=getattr(req, "id", None), source=rep.index,
                fault_kind=spec.kind)
        if rr is None or rr.req is not req:
            return
        rr.redispatches += 1
        try:
            self._dispatch(rr)
        except (QueueFull, NoHealthyReplica) as e:
            if isinstance(e, NoHealthyReplica) and not self.healthy():
                rr.final_status = "failed"
                rr.final_error = f"{type(e).__name__}: {e}"
                return
            self._orphans.append(rr)

    def _telemetry_vitals(self) -> dict:
        """Health-sampler source: cluster counters + per-replica vitals
        (every replica, dead or alive — a killed replica's ``state`` /
        frozen ``heartbeat_t`` must stay visible in the time-series)."""
        return {
            "n_replicas": len(self.replicas),
            "healthy": len(self.healthy()),
            "failovers": self.failovers,
            "retires": self.retires,
            "scale_ups": self.scale_ups,
            "retiring": len(self._retiring),
            "orphans": len(self._orphans),
            "router_requests": len(self.requests),
            "outstanding": sum(1 for rr in self.requests if not rr.done),
            "weight_swaps": len(self.swapped_steps),
            "handoffs": self.handoffs,
            "handoff_faults": self.handoff_faults,
            "replicas": {str(r.index): r.vitals() for r in self.replicas},
        }

    def _fail_replica(self, rep: Replica, exc: BaseException) -> None:
        rep.state = FAILED
        self.failovers += 1
        if self._tracer is not None:
            self._tracer.instant("replica_failed", cat="router", tid=rep.tid,
                                 replica=rep.index,
                                 error=f"{type(exc).__name__}: {exc}")
        # close() converts everything the engine had accepted into
        # engine_fault-marked terminal records (failed in-flight rows were
        # already marked by the fault path itself); harvest = exactly the
        # collateral, never a request's own failure.  A close that raises
        # (the engine is already sick) must not abort the harvest —
        # whatever made it into ``completed`` still gets re-dispatched.
        try:
            rep.close()
        except Exception as ce:
            if self._tracer is not None:
                self._tracer.instant("replica_close_error", cat="router",
                                     tid=rep.tid, replica=rep.index,
                                     error=f"{type(ce).__name__}: {ce}")
        casualties = [
            self._owner[id(req)]
            for req in rep.engine.completed
            if req.engine_fault and id(req) in self._owner
            and self._owner[id(req)].req is req
        ]
        for rr in sorted(casualties, key=lambda rr: rr.id):
            rr.excluded.add(rep.index)
            rr.redispatches += 1
            try:
                self._dispatch(rr)
            except (QueueFull, NoHealthyReplica) as e:
                if isinstance(e, NoHealthyReplica) and not self.healthy():
                    # the whole tier is down — terminal, not retryable
                    rr.final_status = "failed"
                    rr.final_error = f"{type(e).__name__}: {e}"
                    continue
                self._orphans.append(rr)
                continue
            if self._tracer is not None and rr.replica is not None:
                kw = {}
                t = getattr(rr.req, "trace", None)
                if t is not None:
                    kw["parent"] = t["id"]
                if rr.trace_ctx is not None:
                    kw["trace"] = rr.trace_ctx.trace_id
                self._tracer.instant(
                    "failover_redispatch", cat="router",
                    tid=self.replicas[rr.replica].tid, request=rr.id,
                    source=rep.index, replica=rr.replica, **kw)

    def _retry_orphans(self) -> None:
        still: list[RouterRequest] = []
        for rr in self._orphans:
            if rr.done:
                continue
            if self.clock() > rr.overdue_at:
                rr.final_status = "cancelled"
                continue
            try:
                self._dispatch(rr)
            except (QueueFull, NoHealthyReplica):
                if not self.healthy():
                    rr.final_status = "failed"
                    rr.final_error = "no healthy replica remained"
                    continue
                still.append(rr)
        self._orphans = still

    @property
    def outstanding(self) -> int:
        return sum(not rr.done for rr in self.requests)

    def run_until_done(self, max_steps: int | None = None
                       ) -> list[RouterRequest]:
        """Pump :meth:`step` until every submitted request is terminal (or
        ``max_steps``); the multi-replica analog of ``engine.run()``."""
        steps = 0
        while self.outstanding:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
            if not self.healthy() and not any(
                    r.state == DRAINING for r in self.replicas):
                self._retry_orphans()  # finalize strands against a dead tier
                break
        return self.requests

    # ------------------------------------------------------------------
    # replica lifecycle

    def prewarm(self) -> dict:
        """Fan :meth:`InferenceEngine.prewarm` across every healthy
        replica — the launch-path half of ROADMAP item 5a: compile each
        replica's full program family BEFORE the first request, so no
        request anywhere in the tier pays first-use compile as TTFT.
        When the factory wires ``compile_cache_dir=``, the first replica
        compiles and the rest (and every later respawn) hit the
        persistent cache.  Call after construction, before traffic.

        Returns per-replica prewarm reports keyed by replica index
        (see :meth:`InferenceEngine.prewarm`), plus ``"total_s"``.
        """
        if self._closed:
            raise RuntimeError("router is closed")
        t0 = self.clock()
        by_replica = {}
        for rep in self.healthy():
            by_replica[rep.index] = rep.engine.prewarm()
        out = {"replicas": by_replica,
               "total_s": round(self.clock() - t0, 6)}
        if self._tracer is not None:
            self._tracer.instant(
                "prewarm", cat="router", tid=self.tid,
                replicas=len(by_replica), total_s=out["total_s"])
        return out

    def restart(self, index: int) -> float:
        """Respawn a FAILED replica in place (fresh engine via the factory
        — warm when the factory wires a persistent compile cache).  When
        the tier has hot-swapped since the factory's params were captured,
        the fresh engine immediately re-applies the CURRENT weights — a
        restart must never quietly reintroduce a stale weight version.
        Returns the bring-up seconds."""
        rep = self.replicas[index]
        if rep.state != FAILED:
            raise RuntimeError(
                f"replica {index} is {rep.state}, not failed — restart "
                "replaces dead replicas only")
        spawn_s = rep.spawn()
        self.scale_ups += 1
        if self._current_weights is not None:
            params, step = self._current_weights
            rep.engine.swap_params(params)  # fresh engine: trivially idle
            rep.weight_step = step
        return spawn_s

    # ------------------------------------------------------------------
    # elastic capacity (ISSUE 17): scale-up appends/restarts replicas
    # through the construction factory; scale-down drains before closing

    def add_replica(self, role: str = "both") -> Replica:
        """Scale-up: append one fresh replica built through the SAME
        factory this router was constructed with (warm when the factory
        wires a persistent compile cache — the spawn reuses the program
        family the first replica compiled).  When the tier has hot-swapped
        weights since construction, the new replica immediately re-applies
        the CURRENT weights and is stamped with their step, so a
        late-spawned replica never serves the factory's stale originals
        (the :class:`WeightWatcher` completeness check reads the stamp).
        Returns the new replica, HEALTHY and dispatchable."""
        if self._closed:
            raise RuntimeError("router is closed")
        rep = Replica(len(self.replicas), self._make_engine,
                      tracer=self._tracer, role=role)
        rep.spawn()
        self.replicas.append(rep)
        self.scale_ups += 1
        if self._current_weights is not None:
            params, step = self._current_weights
            rep.engine.swap_params(params)  # fresh engine: trivially idle
            rep.weight_step = step
        if self._tracer is not None:
            self._tracer.instant(
                "replica_added", cat="router", tid=rep.tid,
                replica=rep.index, role=rep.role,
                spawn_s=round(rep.spawn_s, 6))
        return rep

    def begin_retire(self, index: int) -> bool:
        """Scale-down, phase 1: mark replica ``index`` DRAINING — no new
        dispatches or handoff landings, but its pump keeps stepping it
        until the in-flight work retires (zero-drop by construction, the
        same drain discipline as a weight swap).  Refused (False) when the
        replica is not HEALTHY or when retiring it would leave the tier
        without prefill- or decode-capable capacity — the autoscaler's
        floor, enforced where it cannot be forgotten."""
        rep = self.replicas[index]
        if rep.state != HEALTHY or not rep.alive:
            return False
        survivors = [r for r in self.healthy() if r.index != index]
        if not any(r.role in ("prefill", "both") for r in survivors) or \
                not any(r.role in ("decode", "both") for r in survivors):
            return False
        rep.state = DRAINING
        self._retiring.add(index)
        if self._tracer is not None:
            self._tracer.instant("retire_drain_begin", cat="router",
                                 tid=rep.tid, replica=rep.index)
        return True

    def finish_retires(self) -> list[int]:
        """Scale-down, phase 2: close every retiring replica that has
        drained idle (no slot work, no queued work, no parked handoff
        packets).  The idle check and the close are atomic under the
        replica's engine guard (``_admit_guard``) so a daemon pump is
        never mid-``step()`` when the engine closes under it.  A replica
        that FAILED mid-drain is dropped from the retiring set — the
        failover harvest already owns its exit.  Returns the indices
        retired by THIS call; runs every router step / daemon watchdog
        tick while any retire is pending."""
        done: list[int] = []
        for index in sorted(self._retiring):
            rep = self.replicas[index]
            if rep.state == FAILED or not rep.alive:
                self._retiring.discard(index)
                continue
            guard = (self._admit_guard(rep)
                     if self._admit_guard is not None
                     else contextlib.nullcontext())
            with guard:
                if (rep.engine.has_work
                        or len(getattr(rep.engine, "_outbox", ()))):
                    continue
                rep.close()
            rep.state = FAILED
            rep.retired = True
            self._retiring.discard(index)
            self.retires += 1
            done.append(index)
            if self._tracer is not None:
                self._tracer.instant(
                    "replica_retired", cat="router", tid=rep.tid,
                    replica=rep.index, spawns=rep.spawns)
        return done

    def swap_replica(self, rep: Replica, params) -> bool:
        """Drain → swap → re-admit ONE replica; the others keep serving.
        Returns False without harm when the swap cannot proceed (replica
        busy past ``max_drain_steps``, failed mid-drain, chaos hit) — the
        replica re-admits on its old weights and the caller retries later.
        """
        if rep.state != HEALTHY or not rep.alive:
            return False
        rep.state = DRAINING
        if self._tracer is not None:
            self._tracer.instant("swap_drain_begin", cat="router",
                                 tid=rep.tid, replica=rep.index)
        steps = 0
        # a parked handoff packet holds pool pages and radix nodes, so a
        # non-empty outbox is in-flight work for the drain: swap_params
        # evicts the trie wholesale and must not free pages a packet holds
        while rep.engine is not None and rep.alive and (
                rep.engine.has_work or len(getattr(rep.engine, "_outbox", ()))):
            self.step()  # the whole tier keeps moving while rep drains
            steps += 1
            if steps >= self.max_drain_steps:
                rep.state = HEALTHY
                return False
        if rep.state == FAILED or not rep.alive:
            return False  # died mid-drain; failover already handled it
        if self._chaos is not None:
            # one weight-swap event per attempt, after the drain and
            # before the replacement: a hit models the swap interrupted —
            # all-or-nothing, so the replica re-admits on OLD weights
            spec = self._chaos.fire("weight-swap")
            if spec is not None:
                rep.state = HEALTHY
                if self._tracer is not None:
                    self._tracer.instant("swap_aborted", cat="router",
                                         tid=rep.tid, replica=rep.index,
                                         fault_kind=spec.kind)
                return False
        rep.engine.swap_params(params)
        rep.swaps += 1
        rep.state = HEALTHY
        if self._tracer is not None:
            self._tracer.instant("weight_swap", cat="router", tid=rep.tid,
                                 replica=rep.index, swap=rep.swaps)
        return True

    def hot_swap(self, params, step: int | None = None) -> int:
        """Swap ``params`` into every healthy replica, one at a time.
        Returns how many swapped this call.  A chaos-aborted or busy
        replica stays on its old weights with its ``weight_step`` behind —
        re-calling with the same ``step`` retries exactly those (the
        watcher's rollout-completion loop); replicas already stamped with
        ``step`` are skipped, so the retry never double-drains."""
        self._current_weights = (params, step)
        swapped = 0
        for rep in list(self.replicas):
            if step is not None and rep.weight_step == step:
                continue
            if self.swap_replica(rep, params):
                rep.weight_step = step if step is not None else rep.weight_step
                swapped += 1
        if swapped and step is not None and (
                not self.swapped_steps or self.swapped_steps[-1] != int(step)):
            self.swapped_steps.append(int(step))
        return swapped

    # ------------------------------------------------------------------
    # stats / shutdown

    def stats_records(self) -> list[ServingStats]:
        """Every engine stats record the tier has produced: closed engines
        (failed-over, shut down) plus each replica's live one."""
        out: list[ServingStats] = []
        for rep in self.replicas:
            out.extend(rep.stats_records)
            if rep.alive:
                out.append(rep.engine.stats)
        return out

    def summary(self) -> dict:
        """Cluster rollup (``ServingStats.merge``) plus router-level
        counters: failovers, redispatches, spawn timings, swapped steps."""
        merged = ServingStats.merge(self.stats_records())
        merged.update({
            "n_replicas": len(self.replicas),
            "replicas_failed": sum(r.state == FAILED and not r.retired
                                   for r in self.replicas),
            "replicas_retired": sum(r.retired for r in self.replicas),
            "failovers": self.failovers,
            "retires": self.retires,
            "scale_ups": self.scale_ups,
            "redispatches": sum(rr.redispatches for rr in self.requests),
            "router_requests": len(self.requests),
            "weight_swaps": sum(r.swaps for r in self.replicas),
            "handoffs": self.handoffs,
            "handoff_faults": self.handoff_faults,
            "swapped_steps": list(self.swapped_steps),
            "spawn_s_by_replica": [
                [round(s, 6) for s in r.spawn_history] for r in self.replicas],
        })
        return merged

    def emit(self, writer=None) -> dict:
        """Write the cluster rollup as ONE ``router`` record."""
        writer = writer if writer is not None else self.writer
        if writer is None:
            raise ValueError("no MetricWriter wired (writer=)")
        return writer.write("router", **self.summary())

    def close(self) -> None:
        """Close every replica engine and (when a writer is wired) emit
        the merged ``router`` record.  Idempotent."""
        if self._closed:
            return
        for rep in self.replicas:
            rep.close()
        if self.writer is not None:
            self.emit(self.writer)
        self._closed = True

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class WeightWatcher:
    """Poll a trainer's checkpoint directory and hot-swap validated steps.

    Owns its OWN read-only :class:`~..utils.checkpoint.CheckpointManager`
    over ``directory`` — ``restore_latest_intact`` begins by waiting on
    ITS manager's in-flight saves (none, ever), so a poll can never block
    the trainer's async save pipeline, and the intact-walk (manifest
    digests → restorability → finiteness/step agreement) makes a torn
    newest step cost one poll, not a bad swap: the walk lands on the
    previous intact step, which ``poll`` then ignores as not-new.

    ``target`` is the abstract restore template (the trainer's
    ``TrainState``); ``extract(state)`` maps it to the decode params the
    engines consume (e.g. ``lambda s: trainer._decode_params()`` after
    adopting, or a plain ``s.params`` cast).  ``min_poll_s`` rate-limits
    directory walks against a hot loop calling :meth:`poll` per step.
    """

    def __init__(self, directory: str, target, router: Router, *,
                 extract: Callable = None, min_poll_s: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        from distributed_tensorflow_ibm_mnist_tpu.utils.checkpoint import (
            CheckpointManager,
        )

        self._mgr = CheckpointManager(directory)
        self._target = target
        self._router = router
        self._extract = extract if extract is not None else (
            lambda state: state.params)
        self._clock = clock
        self.min_poll_s = float(min_poll_s)
        self._last_poll_t: float | None = None
        self.last_step: int | None = None   # newest FULLY-rolled-out step
        self._pending: tuple | None = None  # (params, step) mid-rollout
        self.polls = 0
        self.skipped: list[tuple[int, str]] = []  # (step, why) torn/raced

    def _rolled_out(self, step: int) -> bool:
        """True when every serving replica is stamped with ``step`` — a
        FAILED replica doesn't count against completion (a restart
        re-applies the tier's current weights anyway)."""
        live = [rep for rep in self._router.replicas
                if rep.alive and rep.state != FAILED]
        return bool(live) and all(rep.weight_step == step for rep in live)

    def poll(self) -> int | None:
        """One watch iteration: look for a newer intact step, then push the
        pending rollout (a chaos-aborted or busy replica declines a swap
        and stays behind — each poll retries exactly the stragglers).
        Returns the step once it is on EVERY serving replica, else None
        (nothing new, not yet intact, rate-limited, rollout incomplete)."""
        now = self._clock()
        if (self._last_poll_t is not None
                and now - self._last_poll_t < self.min_poll_s):
            return None
        self._last_poll_t = now
        self.polls += 1
        horizon = (self._pending[1] if self._pending is not None
                   else self.last_step)
        try:
            # the watcher OBSERVES a directory someone else writes: drop
            # the manager's cached step listing before every look
            self._mgr.reload()
            newest = self._mgr.latest_step()
        except Exception:
            newest = None
        if newest is not None and (horizon is None or newest > horizon):
            try:
                state = self._mgr.restore_latest_intact(self._target)
                step = (int(np.asarray(state.step))
                        if hasattr(state, "step") else int(newest))
                if horizon is None or step > horizon:
                    self._pending = (self._extract(state), step)
                else:
                    # the intact-walk fell back behind what we already
                    # serve (newest step torn mid-write): retry next poll
                    self.skipped.append(
                        (int(newest), f"intact walk fell back to {step}"))
            except FileNotFoundError as e:
                # nothing intact YET (first save still landing / torn):
                # the next poll retries — never surface a transient race
                self.skipped.append((int(newest), f"no intact step: {e}"))
        if self._pending is None:
            return None
        params, step = self._pending
        self._router.hot_swap(params, step=step)
        if self._rolled_out(step):
            self._pending = None
            self.last_step = step
            return step
        return None

"""Pluggable admission policies for the daemonized serving tier.

The step-pumped tier had exactly one admission decision: a bounded FIFO
queue that raises :class:`~..serving.scheduler.QueueFull` at the bound.
The daemon (serving/daemon.py) keeps that backpressure contract but adds
a policy seam AT THE FRONT DOOR — the admission queue between
``ServingDaemon.submit()`` and the router dispatch — because that is the
only place where requests WAIT in a reorderable set.  Once a request
reaches a replica's scheduler it is FIFO like before; the policy decides
(a) who gets rejected at submit time and (b) in what order the waiting
set drains into the tier.

Three policies, mirroring the classic serving triad:

* :class:`FIFOPolicy` — arrival order, reject only at the queue bound.
  The baseline: identical end-to-end behaviour to the step-pumped tier.
* :class:`PriorityPolicy` — strict priority classes (higher first), FIFO
  within a class.  An overloaded tier serves interactive traffic before
  batch traffic instead of interleaving them.
* :class:`DeadlineAwarePolicy` — shed-at-submit: reject a request whose
  TTFT SLO is already unmeetable given the predicted queue wait, raising
  :class:`SLOUnmeetable` (a :class:`QueueFull` subclass, so existing
  backpressure handlers shed it the same way).  Rejecting doomed work at
  the door is what keeps GOODPUT (requests meeting SLO per second) high
  under overload — admitting it would burn slots on requests that can
  only ever count as misses.

The wait predictor is deliberately a heuristic: an EMA of observed
submit→first-token latency (fed back by the daemon's delivery thread via
:meth:`AdmissionPolicy.note_first_token`), scaled by the queue depth
ahead of the candidate over the tier's concurrency.  Until the first
observation the policy is optimistic (admit everything) — the cold tier
has no basis to shed.

Thread model: ``admit``/``key`` are called under the daemon's admission
lock and ``note_first_token`` from the single delivery thread, so a
policy needs no internal locking of its own.
"""

from __future__ import annotations

from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import QueueFull


class SLOUnmeetable(QueueFull):
    """Rejected at submit: the predicted queue wait already exceeds the
    request's TTFT SLO, so admitting it could only produce an SLO miss.
    Subclasses :class:`QueueFull` so callers that already turn
    backpressure into shed/429 handle deadline shedding for free."""


class AdmissionPolicy:
    """Base policy: FIFO order, no shedding beyond the queue bound.

    Subclass hooks:

    ``key(dr)``
        Sort key for the admission heap — smallest drains first.  Must
        embed a tiebreaker (``dr.id`` — monotone submit order) so equal
        keys stay FIFO and the heap never compares request objects.
    ``admit(dr, queued)``
        Called BEFORE the request enters the admission queue, with the
        number of requests already waiting or in flight ahead of it.
        Raise (:class:`SLOUnmeetable` or any :class:`QueueFull`) to shed;
        return normally to admit.
    ``note_first_token(wait_s)``
        Feedback from the daemon's delivery thread: one request's
        observed submit→first-token latency.  Policies that predict wait
        fold it into their estimate; the base policy ignores it.
    ``retry_after_s(queued)``
        A backoff hint for a request rejected with ``queued`` ahead of
        it: the predicted seconds until the tier is likely to admit
        again, or None (no basis).  The daemon stamps it onto every
        :class:`QueueFull`/:class:`SLOUnmeetable` it raises so protocol
        front ends can emit real ``Retry-After`` headers; the base
        policy predicts nothing.
    """

    name = "fifo"

    def key(self, dr) -> tuple:
        return (dr.id,)

    def admit(self, dr, queued: int) -> None:
        return

    def note_first_token(self, wait_s: float) -> None:
        return

    def retry_after_s(self, queued: int) -> float | None:
        return None


class FIFOPolicy(AdmissionPolicy):
    """Arrival order, queue-bound backpressure only — the baseline that
    behaves exactly like the step-pumped tier's scheduler front door."""


class PriorityPolicy(AdmissionPolicy):
    """Strict priority classes: higher ``dr.priority`` drains first,
    FIFO (submit order) within a class.  No shedding beyond the bound —
    under sustained overload low-priority work waits, it is not dropped,
    so conservation still holds exactly."""

    name = "priority"

    def key(self, dr) -> tuple:
        return (-int(dr.priority), dr.id)


class DeadlineAwarePolicy(PriorityPolicy):
    """Priority ordering + shed-at-submit for unmeetable TTFT SLOs.

    Predicted wait for a candidate with ``queued`` requests ahead::

        predicted = ema_wait * (1 + queued / concurrency)

    where ``ema_wait`` is the EMA (``alpha``) of observed
    submit→first-token latencies and ``concurrency`` is the tier's
    rough parallel capacity (replicas × slots — how many of the queued
    requests are served concurrently rather than serially).  A request
    with ``ttft_slo_s`` set is rejected with :class:`SLOUnmeetable` when
    ``predicted > ttft_slo_s * slack``; requests without a TTFT SLO are
    never shed here (they fall through to the queue bound).  ``slack >
    1`` sheds late (optimistic), ``< 1`` sheds early (conservative).
    """

    name = "deadline"

    def __init__(self, *, alpha: float = 0.3, concurrency: int = 1,
                 slack: float = 1.0):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        if concurrency < 1:
            raise ValueError(f"concurrency must be >= 1, got {concurrency}")
        if slack <= 0:
            raise ValueError(f"slack must be > 0, got {slack}")
        self.alpha = float(alpha)
        self.concurrency = int(concurrency)
        self.slack = float(slack)
        self.ema_wait_s: float | None = None
        self.shed = 0          # requests this policy rejected
        self.observations = 0  # note_first_token feedback count

    def predicted_wait_s(self, queued: int) -> float | None:
        """The estimator, exposed for tests/vitals: None = no basis yet."""
        if self.ema_wait_s is None:
            return None
        return self.ema_wait_s * (1.0 + queued / self.concurrency)

    def admit(self, dr, queued: int) -> None:
        if dr.ttft_slo_s is None:
            return
        predicted = self.predicted_wait_s(queued)
        if predicted is None:
            return  # cold start: no observed latency to predict from
        if predicted > dr.ttft_slo_s * self.slack:
            self.shed += 1
            raise SLOUnmeetable(
                f"request {dr.id}: predicted TTFT {predicted:.4f}s with "
                f"{queued} ahead exceeds SLO {dr.ttft_slo_s:.4f}s "
                f"(x{self.slack:g} slack) — shed at submit")

    def note_first_token(self, wait_s: float) -> None:
        self.observations += 1
        if self.ema_wait_s is None:
            self.ema_wait_s = float(wait_s)
        else:
            self.ema_wait_s += self.alpha * (wait_s - self.ema_wait_s)

    def retry_after_s(self, queued: int) -> float | None:
        """Backoff hint = the same estimator the shed verdict used: the
        predicted wait at the CURRENT depth is how long the rejected
        caller should expect the tier to take to digest what is ahead
        of it.  None before the first observation (cold tier — nothing
        sheds then either)."""
        return self.predicted_wait_s(queued)

"""One engine replica under the router: lifecycle, health, load score.

The router (serving/router.py) never constructs an :class:`InferenceEngine`
directly — it holds N :class:`Replica` wrappers, each owning the engine's
LIFECYCLE: spawn (build via the caller's factory, timed — the cold-vs-warm
bring-up figure the persistent compile cache exists to improve), health
state, restart after failure, and the live load score the least-loaded
dispatch sorts by.  The split mirrors the engine/scheduler split one level
up: the engine multiplexes requests over slots; the replica multiplexes
ENGINES over failures and weight swaps.

Health is a three-state machine, transitions owned by the router:

* ``HEALTHY`` — dispatchable; pumped every router step.
* ``DRAINING`` — pumped but NOT dispatchable: a weight hot-swap is
  waiting for the engine to quiesce (``has_work`` to go False) while the
  other replicas absorb the traffic.  Transient by construction.
* ``FAILED`` — the engine raised an engine-wide fault (EngineStalled, a
  decode fault with no watchdog) or flunked a health probe; the router
  closed it, harvested its collateral requests for failover, and may
  :meth:`spawn` a replacement in place.

The factory (``make_engine(trace_tid)``) is the configuration seam: it
chooses slots/paging/decode-ahead AND ``compile_cache_dir=`` — a factory
wired to a persistent compile cache makes every respawn warm (the restarted
replica reuses the program family the first spawn compiled, so bring-up
drops from whole-family compile time to cache reads; ``spawn_history``
records the difference).  The ``trace_tid`` argument is the replica's own
timeline track: all N engines share ONE tracer, and per-replica tracks keep
their host loops from interleaving on a single lane.
"""

from __future__ import annotations

import inspect
import time
from typing import Callable

HEALTHY = "healthy"
DRAINING = "draining"
FAILED = "failed"


class Replica:
    """Engine lifecycle wrapper: see the module docstring.

    ``make_engine(trace_tid)`` must return a fresh
    :class:`~.engine.InferenceEngine`; it is called at every (re)spawn.
    A factory that takes a SECOND positional parameter is called as
    ``make_engine(trace_tid, replica_index)`` — the tensor-parallel seam:
    replica ``i`` builds its engine on its own disjoint device group
    (``tp_devices=tp_device_groups(n, tp)[i]``), so failover and hot-swap
    compose with tp without sharing a chip between failure domains.  The
    arity is inspected once at construction, so respawns never re-probe.
    The factory should NOT wire a per-engine ``writer=`` — the router
    emits ONE merged cluster record (``ServingStats.merge``) instead of N
    interleaved per-engine records.
    """

    def __init__(self, index: int, make_engine: Callable, tracer=None,
                 role: str = "both"):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', got {role!r}")
        self.index = int(index)
        self._make_engine = make_engine
        # serving role (ISSUE 16): "both" adopts whatever role the
        # factory's engine declares (monolithic replicas stay "both");
        # an explicit "prefill"/"decode" is VALIDATED against the spawned
        # engine — a replica advertised as prefill capacity whose engine
        # would decode locally (or vice versa) is a misconfiguration, not
        # a policy choice
        self._role = str(role)
        try:
            n_params = len(inspect.signature(make_engine).parameters)
        except (TypeError, ValueError):  # builtins/partials w/o signature
            n_params = 1
        self._factory_wants_index = n_params >= 2
        self._tracer = tracer
        # the replica's own timeline lane, stable across respawns — every
        # engine this replica ever runs logs its host loop here
        self.tid = tracer.track(f"replica {self.index}") if tracer is not None else 0
        self.engine = None
        self.state = FAILED  # nothing to serve until spawn()
        # elastic capacity (ISSUE 17): a retired replica is terminal-FAILED
        # for every dispatch/liveness purpose (nothing routes to it, its
        # pump exits) but ``retired`` records that it drained CLEAN — the
        # autoscaler scaled it down, it did not crash — so vitals and the
        # failover counters keep the two exits distinguishable.  restart()
        # (warm via the compile cache) clears it on the way back up.
        self.retired = False
        self.spawns = 0
        self.swaps = 0
        # checkpoint step of the weights this replica currently serves;
        # None = the factory's originals.  The router stamps it on every
        # successful swap (and on restart, which re-applies the tier's
        # current weights) — the watcher's rollout-completeness check
        # reads it to retry replicas a chaos hit left behind
        self.weight_step: int | None = None
        self.spawn_s: float | None = None     # last bring-up wall seconds
        self.spawn_history: list[float] = []  # all bring-ups (cold vs warm)
        # ServingStats of engines this replica has already CLOSED (failure
        # or shutdown); the router folds these + the live engine's stats
        # into the cluster rollup
        self.stats_records: list = []
        # last non-None engine progress stamp seen by vitals(): the
        # engine's fault path resets its own watchdog anchor, so the
        # health sampler needs this copy to show a killed replica's
        # heartbeat FROZEN at its final progress instead of null
        self._heartbeat_t: float | None = None

    def spawn(self) -> float:
        """Build a fresh engine via the factory and mark HEALTHY.  Returns
        the bring-up wall seconds (factory call: construction + compiles
        not served by a persistent compile cache)."""
        if self.engine is not None and not self.engine._closed:
            raise RuntimeError(
                f"replica {self.index} already has a live engine — close it "
                "(router failover does) before respawning")
        t0 = time.perf_counter()
        self.engine = (self._make_engine(self.tid, self.index)
                       if self._factory_wants_index
                       else self._make_engine(self.tid))
        engine_role = getattr(self.engine, "role", "both")
        if self._role != "both" and engine_role != self._role:
            raise RuntimeError(
                f"replica {self.index} declared role {self._role!r} but the "
                f"factory built a {engine_role!r}-role engine — the router "
                "would route the wrong traffic here")
        self.spawn_s = time.perf_counter() - t0
        self.spawn_history.append(self.spawn_s)
        self.spawns += 1
        self.state = HEALTHY
        self.retired = False
        if self._tracer is not None:
            self._tracer.instant("replica_spawn", cat="router", tid=self.tid,
                                 replica=self.index, spawn=self.spawns,
                                 spawn_s=round(self.spawn_s, 6))
        return self.spawn_s

    @property
    def alive(self) -> bool:
        return self.engine is not None and not self.engine._closed

    @property
    def role(self) -> str:
        """The replica's serving role: the live engine's declaration when
        one exists (stable across respawns — the factory rebuilds the
        same configuration), else the constructor's."""
        if self.engine is not None:
            return getattr(self.engine, "role", self._role)
        return self._role

    def probe(self) -> bool:
        """Liveness check the router runs each step on HEALTHY replicas.
        The base probe is structural (an engine exists and is not closed);
        the router's injectable ``probe=`` hook layers policy on top."""
        return self.alive

    @property
    def load(self) -> float:
        """Least-loaded sort key: requests ahead of a new arrival (queued +
        parked + occupied slots) plus the live KV-pool fraction as the
        fractional tiebreak — two replicas with equal request counts route
        to the one with more free pages (pool-aware routing), and the
        fraction is < 1 so it can never outvote a whole request."""
        e = self.engine
        if e is None:
            return float("inf")
        # role-aware (ISSUE 16): a prefill replica's outbox is accepted
        # work not yet delivered — its pages are still held, so it counts
        # ahead of a new arrival exactly like a parked request (empty on
        # both/decode replicas, where the term vanishes)
        ahead = (len(e.scheduler) + len(e._pending) + e.occupied
                 + len(getattr(e, "_outbox", ())))
        frac = (e._pool.allocated / e._pool.capacity
                if e._pool is not None else e.occupied / e.slots)
        return ahead + frac

    def vitals(self) -> dict:
        """Health-sampler vitals for the router's telemetry source
        (utils/telemetry): state, spawn/swap counts, served weight step,
        the load score, and the engine's last-progress heartbeat.  A
        killed replica stays VISIBLE in every sample — ``state`` goes
        ``failed``, ``heartbeat_t`` freezes at its last observed progress
        (None only if it never made any) — instead of vanishing from the
        dict."""
        e = self.engine
        if e is not None and e.heartbeat_t is not None:
            self._heartbeat_t = e.heartbeat_t
        return {
            "state": self.state,
            "retired": self.retired,
            "role": self.role,
            "outbox": (len(e._outbox)
                       if e is not None and hasattr(e, "_outbox") else 0),
            "alive": self.alive,
            "spawns": self.spawns,
            "swaps": self.swaps,
            "weight_step": self.weight_step,
            "spawn_s": self.spawn_s,
            "load": self.load if self.alive else None,
            "heartbeat_t": self._heartbeat_t,
        }

    def close(self) -> None:
        """Close the live engine (if any) and bank its stats record for
        the router's cluster rollup."""
        if self.engine is not None and not self.engine._closed:
            self.engine.close()
            self.stats_records.append(self.engine.stats)

"""Admission control for the continuous-batching engine: FIFO + buckets.

The host side of the TF-Replicator / Mesh-TensorFlow split the serving
design follows (PAPERS.md): the DEVICE program is fixed-shape (one compiled
decode step, a small set of padded prefill shapes); everything variable —
arrival order, queue depth, deadlines — lives here, in plain Python the
compiler never sees.

Three jobs:

* **Bucketing** — a prompt admitted at its raw length would compile a
  fresh prefill program per distinct length.  ``buckets`` is the closed set
  of padded prefill shapes: a prompt rides in the smallest bucket that
  fits, right-padded with ``pad_id`` (the causal mask keeps real tokens
  from seeing the pads — models/transformer.py ``_decode_attention``), so
  the engine compiles at most ``len(buckets)`` prefill programs, ever.
* **Backpressure** — the queue is bounded (``max_queue``); ``submit`` on a
  full queue raises :class:`QueueFull` instead of buffering unboundedly.
  The caller (a request handler) turns that into load-shedding/429s.
* **Deadlines** — a request may carry ``deadline_s`` (seconds from
  submit).  Overdue QUEUED requests are cancelled at pop time (never
  admitted — prefilling a request that cannot finish wastes the slot);
  overdue RUNNING rows are cancelled by the engine's per-iteration sweep.

Thread model: the queue is a ``collections.deque``, whose ``append`` and
``popleft`` are each atomic under CPython — a daemon pump thread can pop
while a producer appends without a scheduler-level lock.  What is NOT
atomic is the bounded-queue check-then-append in ``submit``: concurrent
submitters must serialize it externally, which the daemonized tier does
under its tier lock (serving/daemon.py) — single-threaded callers get it
for free.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.serving.prefix_cache import prefix_key
from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import SamplingParams


def request_fingerprint(prompt, max_new: int, sampling=None) -> str:
    """Content address of one generation request's REPLAY identity:
    blake2b over the prompt tokens, the budget, and the sampling params
    (which fully determine the token stream — sampling.py).

    Two uses, both about binding identity across retries:

    * the front door stores it beside each ``Idempotency-Key`` binding
      and rejects a key REUSED with a different body (422) — a retried
      POST must be the SAME request, not a new one wearing an old key;
    * the request journal persists it in ``admitted`` records, so a
      recovered binding enforces the same check across a process crash.

    Deliberately excludes deadline/priority/SLOs: a client may retry
    with a fresher deadline and still mean the same request.
    """
    h = hashlib.blake2b(digest_size=16)
    h.update(np.asarray(prompt, np.int32).tobytes())
    h.update(int(max_new).to_bytes(8, "little"))
    if sampling is not None:
        h.update(json.dumps(sampling.to_dict(), sort_keys=True).encode())
    return h.hexdigest()


class QueueFull(RuntimeError):
    """Bounded-queue backpressure: the caller must retry or shed load.

    ``retry_after_s`` is the machine-readable retry hint: an estimate of
    how long the caller should back off before the tier is likely to
    admit again (seconds), or None when the rejecting layer has no basis
    to predict one.  The daemon front door stamps it from the admission
    policy's wait predictor (serving/policies.py) so a protocol server
    can surface it as a ``Retry-After`` header instead of inventing
    backoff client-side.
    """

    retry_after_s: float | None = None


@dataclass
class Request:
    """One generation request and its lifecycle record.

    The scheduler fills the identity/admission fields; the engine fills the
    timing/output fields as the request moves through a slot.  ``status``
    walks queued -> running -> (done | cancelled | failed) — a chunked-
    prefill engine (ISSUE 14) inserts a transient ``prefilling`` between
    queued and running while the prompt advances chunk by chunk.
    ``failed`` is
    the TERMINAL state of a request whose own processing raised (poisoned
    prompt at prefill, raising user ``callback``) — the failure is
    isolated to this request (``error`` records it) and the engine keeps
    serving every other slot.
    """

    id: int
    tokens: np.ndarray          # (len,) int32 — the real (unpadded) prompt
    max_new: int                # generation budget (EOS may stop earlier)
    bucket: int                 # padded prefill length the prompt rides in
    deadline_s: float | None    # seconds from submit; None = no deadline
    submit_t: float             # scheduler clock at submit
    callback: Callable | None = None    # per-token streaming hook:
    #   callback(request, token) after every generated token; an exception
    #   FAILS this request only (see engine docs)
    ttft_slo_s: float | None = None     # SLO target: submit -> first token
    #   on the host, seconds; None = this request declares no TTFT SLO
    tpot_slo_s: float | None = None     # SLO target: mean seconds per
    #   output token AFTER the first (decode steady-state); None = no SLO.
    #   Unlike deadline_s these never cancel anything — the engine judges
    #   them (slo_ttft_ok at first token, slo_tpot_ok at retirement) and
    #   ServingStats folds the verdicts into slo_met/slo_miss/goodput
    #   (ISSUE 11; the accounting ROADMAP item 3's load harness gates on)
    admit_t: float | None = None        # engine: slot admission (prefill)
    first_token_t: float | None = None  # engine: first token on host (TTFT)
    finish_t: float | None = None       # engine: retirement
    generated: list[int] = field(default_factory=list)  # engine: output
    status: str = "queued"
    error: str | None = None            # engine: why status == "failed"
    slo_ttft_ok: bool | None = None     # engine verdict at first token;
    #   None = not judged (no SLO declared, or never got a first token)
    slo_tpot_ok: bool | None = None     # engine verdict at retirement;
    #   None = not judged (no SLO declared, or not retired "done")
    engine_fault: bool = False          # engine: True when a terminal
    #   failed/cancelled status is COLLATERAL of an engine-wide fault
    #   (stall watchdog, close during an overcommit stall) rather than the
    #   request's own poison/callback/deadline — the router's failover
    #   re-dispatches exactly the collateral (serving/router.py)
    prefix_key: str | None = None       # blake2b content address of the
    #   (bucket, prompt) pair — the prefix-cache lookup key
    #   (serving/prefix_cache.py); filled by the scheduler at submit
    sampling: "SamplingParams | None" = None  # per-request sampling config
    #   (serving/sampling.py), validated at submit; None = the engine's
    #   default (its temperature/top_p/rng construction knobs)
    logprobs: list[float] = field(default_factory=list)  # engine: one
    #   log_softmax(raw logits)[token] per generated token (the model's
    #   pre-temperature distribution — comparable across sampling configs;
    #   len(logprobs) == len(generated) at every point in the lifecycle)
    pages: int = 0                      # paged engine: KV pages this
    #   request's block table spans (shared radix pages included); 0 on
    #   the dense layout — the per-request HBM footprint record
    radix_tokens: int = 0               # paged engine: prompt tokens served
    #   from shared radix pages (prefill skipped for them); 0 = full prefill
    trace: dict | None = None           # tracing bookkeeping (utils/tracing):
    #   {"id": request span, "tid": the request's track, "phase": the open
    #   lifecycle-phase span (queue/admit/decode) or None}; None when no
    #   tracer is wired — every touch is nil-guarded like the chaos hooks
    trace_ctx: "object | None" = None   # distributed TraceContext
    #   (utils/tracing.TraceContext) stamped by the ROUTER after submit —
    #   the engine never parses trace headers; it just carries the context
    #   so the handoff packet and the telemetry exemplars can read
    #   trace_ctx.trace_id.  None for direct engine callers.

    @property
    def overdue_at(self) -> float:
        return np.inf if self.deadline_s is None else self.submit_t + self.deadline_s


class FIFOScheduler:
    """Bounded FIFO request queue with prompt-length bucketing.

    ``max_len`` is the engine's KV-cache length: a request must satisfy
    ``len(prompt) + max_new <= max_len`` (its slot cursor may never run off
    the cache) and fit some bucket.  ``clock`` is injectable for tests.
    """

    def __init__(self, max_len: int, buckets: tuple[int, ...] = (16, 32, 64, 128),
                 max_queue: int = 64, clock: Callable[[], float] = time.monotonic,
                 tracer=None, chunked_prefill: bool = False):
        if not buckets:
            raise ValueError("need at least one prefill bucket")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_len = max_len
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        if self.buckets[-1] > max_len:
            raise ValueError(
                f"largest bucket ({self.buckets[-1]}) exceeds max_len "
                f"({max_len}) — a prompt that long could never prefill"
            )
        self.max_queue = max_queue
        self.clock = clock
        # chunked-prefill admission regime (ISSUE 14): the engine prefills
        # prompts in fixed chunks through ONE extend program, so a prompt
        # needs NO matching bucket — submit accepts any length that fits
        # the cache (len + max_new <= max_len) and `bucket` is capped at
        # the largest bucket (it still keys prefix_key and stats; it is
        # never a compiled prefill shape in this regime)
        self.chunked_prefill = bool(chunked_prefill)
        # utils/tracing.Tracer | None.  The scheduler owns the submit end of
        # a request's span tree (the request root span + its queue-wait
        # phase); the engine adopts the same tracer (engine construction
        # enforces agreement) and owns every later phase.  Share the
        # scheduler's clock with the tracer, or durations won't agree with
        # the latencies computed from submit_t/finish_t.
        self.tracer = tracer
        self._queue: deque[Request] = deque()
        self._ids = itertools.count()
        self.cancelled: list[Request] = []  # overdue-before-admission

    def __len__(self) -> int:
        return len(self._queue)

    def bucket_for(self, n: int) -> int:
        """Smallest bucket holding an n-token prompt; raises if none does."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds the largest prefill bucket "
            f"({self.buckets[-1]}) — raise buckets=, shorten the prompt, "
            f"or serve with InferenceEngine(prefill_chunk=...) (chunked "
            f"prefill admits any prompt that fits the cache)"
        )

    def submit(self, prompt, max_new: int, deadline_s: float | None = None,
               callback: Callable | None = None,
               ttft_slo_s: float | None = None,
               tpot_slo_s: float | None = None,
               sampling: SamplingParams | None = None) -> Request:
        """Enqueue one request; raises :class:`QueueFull` (backpressure) or
        ``ValueError`` (request can never be served).  ``callback`` is the
        per-token streaming hook; ``ttft_slo_s``/``tpot_slo_s`` are the
        optional latency SLO targets (see :class:`Request`); ``sampling``
        is the per-request :class:`SamplingParams` (None = engine
        default) — already validated by its own constructor, the type is
        checked here so a stray ``(temp, top_p)`` tuple fails at submit,
        not mid-decode."""
        tokens = np.asarray(prompt, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if ttft_slo_s is not None and ttft_slo_s <= 0:
            raise ValueError(f"ttft_slo_s must be > 0, got {ttft_slo_s}")
        if tpot_slo_s is not None and tpot_slo_s <= 0:
            raise ValueError(f"tpot_slo_s must be > 0, got {tpot_slo_s}")
        if callback is not None and not callable(callback):
            raise ValueError("callback must be callable")
        if sampling is not None and not isinstance(sampling, SamplingParams):
            raise ValueError(
                f"sampling must be a SamplingParams, got {type(sampling).__name__}")
        if tokens.size + max_new > self.max_len:
            raise ValueError(
                f"prompt ({tokens.size}) + max_new ({max_new}) exceeds the "
                f"engine cache length ({self.max_len})"
            )
        if self.chunked_prefill and tokens.size > self.buckets[-1]:
            # chunked engines never dispatch bucketed prefills: long
            # prompts ride capped at the largest bucket (a label, not a
            # compiled shape) — the max_len check above already gated
            bucket = self.buckets[-1]
        else:
            bucket = self.bucket_for(tokens.size)
        if len(self._queue) >= self.max_queue:
            raise QueueFull(
                f"request queue full ({self.max_queue}) — retry later or "
                "shed load (bounded-queue backpressure)"
            )
        req = Request(id=next(self._ids), tokens=tokens, max_new=int(max_new),
                      bucket=bucket, deadline_s=deadline_s,
                      submit_t=self.clock(), callback=callback,
                      ttft_slo_s=ttft_slo_s, tpot_slo_s=tpot_slo_s,
                      prefix_key=prefix_key(bucket, tokens),
                      sampling=sampling)
        if self.tracer is not None:
            # root span of this request's tree, on its own viewer track;
            # "queue" is the first lifecycle phase (closed at pop, or at
            # overdue-cancel).  Engine phases chain off the same ids.
            tid = self.tracer.track(f"req {req.id}")
            rid = self.tracer.begin(
                "request", cat="serving", tid=tid, req=req.id,
                bucket=bucket, prompt_len=int(tokens.size),
                max_new=int(max_new))
            req.trace = {
                "id": rid, "tid": tid,
                "phase": self.tracer.begin("queue", cat="serving",
                                           parent=rid, tid=tid),
            }
        self._queue.append(req)
        return req

    def pop(self, now: float | None = None) -> Request | None:
        """Next admissible request (FIFO), or None.  Overdue queued
        requests are cancelled in passing, never returned — admitting a
        request that already blew its deadline would waste the prefill and
        the slot."""
        now = self.clock() if now is None else now
        while self._queue:
            req = self._queue.popleft()
            if now > req.overdue_at:
                req.status = "cancelled"
                req.finish_t = now
                if req.trace is not None and self.tracer is not None:
                    # terminal here: close the queue phase AND the request
                    # root (the engine never sees this request)
                    self.tracer.end(req.trace["phase"])
                    self.tracer.end(req.trace["id"], status="cancelled")
                    req.trace = None
                self.cancelled.append(req)
                continue
            if req.trace is not None and self.tracer is not None:
                self.tracer.end(req.trace["phase"])  # queue wait over
                req.trace["phase"] = None
            return req
        return None

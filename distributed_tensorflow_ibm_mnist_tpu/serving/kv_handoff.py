"""Paged-KV handoff: moving a finished prefill between engines (ISSUE 16).

The disaggregated tier splits the two phases of serving a request across
role-typed replicas: PREFILL replicas run the prompt through the
prefill/extend program family and stop at the moment every monolithic
engine would pick the first token; DECODE replicas run the decode window
and never compile a prefill bucket.  This module is the seam between
them — the packaging of a finished prefill into a host-side
:class:`HandoffPacket` and its landing on a decode engine — with three
invariants the chaos suite gates on:

* **Deferred source-free.**  The packet carries the source slot's page
  HOLD (private pool pages + acquired radix nodes) and nothing frees
  until the router confirms delivery (:meth:`HandoffPacket.release`).  A
  transfer that dies in flight (the ``kv-handoff`` chaos site) releases
  the hold and re-dispatches the request down the normal prefill path —
  the source trie still has the prompt's shared blocks, so the retry's
  re-prefill is a radix hit, and the router's delivered high-water mark
  keeps the replay exactly-once per token.
* **All-or-nothing landing.**  :func:`deliver` allocates the request's
  FULL destination page span before touching the destination cache; a
  dry pool returns False with zero writes issued (the router re-parks
  the packet and retries next pump — admission stall semantics, never
  corruption).  Failures after allocation are the request's own and
  reclaim every destination page.
* **Radix-aware arrival.**  The destination trie is matched before the
  scatter: blocks it already holds are acquired and mapped into the
  block table WITHOUT re-uploading their payload (shared-prefix pages
  dedup on arrival), and freshly landed full prompt blocks are donated
  back so the NEXT handoff of the same prefix skips them too.

Resharding falls out of the host hop: :func:`~.kv_pool.gather_page` is
jitted read-only on the SOURCE mesh and ``jax.device_get`` assembles its
shards into one full host array, which the DESTINATION engine re-uploads
through its own ``_dev`` commitment — a tp=4 prefill pool's head-sharded
page lands on a tp=1 decode pool (or any other degree) with no
device-to-device protocol and no extra program.

Census discipline: the transfer unit is ONE page, so a prompt of any
length moves as N dispatches of the same two fixed-shape programs
(``handoff_gather`` on the source, the per-page writer + no-forward
``bt_install`` under ``handoff_install`` on the destination) — the
per-role compile census never moves with traffic, which is what
``scripts/bench_disagg.py`` pins.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.serving.kv_pool import pages_needed


@dataclasses.dataclass
class HandoffPacket:
    """One finished prefill, portable across engines.

    ``req`` is the SAME engine :class:`~.scheduler.Request` object the
    source admitted — its router-wrapped callback (and therefore the
    cross-attempt delivered high-water mark) travels with it, so the
    decode side's tokens stream through the identical exactly-once path
    a monolithic engine's would.  ``payloads`` holds one host tree per
    CONTENT page (the :func:`~.kv_pool.gather_page` layout, prompt pages
    only — decode-span pages are garbage by contract and never move);
    ``last_logits`` is the prefill's (1, V) float32 last-position row,
    from which the destination picks the first token through the shared
    ``first_pick`` program — bit-identical to the pick the source would
    have made.  ``hold`` is the source-side page hold released only at
    :meth:`release` (deferred source-free; module docstring).
    """

    req: Any
    n_tok: int
    payloads: list
    last_logits: np.ndarray
    source: Any                      # the source InferenceEngine
    hold: list | None                # [private page ids, held radix nodes]
    created_t: float
    gather_s: float
    payload_bytes: int
    trace_ctx: Any = None            # distributed TraceContext riding the
    #   handoff: the prefill-role and decode-role spans of one request
    #   join under trace_ctx.trace_id even when the roles run on separate
    #   tracers (the merged export connects them via span_ctx/parent_ctx)

    def release(self) -> None:
        """Free the source-side hold — called by the router exactly when
        the packet is consumed (delivered, or abandoned to a re-dispatch
        after a transfer fault).  Idempotent; a closed/dead source engine
        is a no-op (its pool died with it)."""
        hold, self.hold = self.hold, None
        if hold is None:
            return
        src = self.source
        if src is None or getattr(src, "_closed", False):
            return
        pages, nodes = hold
        if pages:
            src._pool.free(pages)
        if nodes:
            src._radix.release(nodes)


def package(engine, req, slot: int, logits_dev, bt_row) -> "HandoffPacket":
    """Source half: gather ``slot``'s prompt pages to the host and build
    the packet.  Called by the prefill-role engine at the exact point
    every other landing path would run ``first_pick`` — the slot's page
    hold transfers to the packet (the caller clears the slot and queues
    its block-table reset; the PAGES stay allocated until
    :meth:`HandoffPacket.release`).

    Gathers are read-only (no donation), so a fault anywhere in here
    leaves the source cache untouched: the caller's failure path reclaims
    the allocation exactly as for any admission-tail exception.
    """
    t0 = engine.clock()
    ps = engine._page_size
    n_tok = int(req.tokens.size)
    n_blocks = pages_needed(n_tok, ps)
    payloads = []
    for j in range(n_blocks):
        with engine._compile.site("handoff_gather"):
            payloads.append(jax.device_get(engine._page_gather(
                engine.cache, jnp.asarray(int(bt_row[j]), jnp.int32))))
    last = np.asarray(jax.device_get(logits_dev), np.float32)
    nbytes = sum(leaf.nbytes for p in payloads
                 for leaf in jax.tree.leaves(p)) + last.nbytes
    t1 = engine.clock()
    # the hold moves LAST, after every gather succeeded — an exception
    # above leaves it on the slot for _release_slot_alloc to reclaim
    hold = engine._slot_alloc[slot]
    engine._slot_alloc[slot] = None
    if req.admit_t is None:
        req.admit_t = t0
    req.status = "prefilled"
    engine._tr_phase(req, "handoff", slot=slot, pages=n_blocks)
    if engine._tracer is not None and req.trace is not None:
        engine._tracer.complete(
            "gather", t0, t1, cat="handoff",
            parent=req.trace.get("phase") or req.trace["id"],
            tid=req.trace["tid"], pages=n_blocks, bytes=int(nbytes))
    engine._last_progress_ever = t1
    return HandoffPacket(req=req, n_tok=n_tok, payloads=payloads,
                         last_logits=last, source=engine, hold=hold,
                         created_t=t0, gather_s=t1 - t0,
                         payload_bytes=int(nbytes),
                         trace_ctx=req.trace_ctx)


def deliver(engine, packet: "HandoffPacket") -> bool:
    """Destination half: land ``packet`` on a decode-capable engine.

    Returns True when the packet was CONSUMED — landed and decoding, or
    failed on its own admission tail (the request is terminal either
    way) — and False when the engine cannot take it RIGHT NOW (no free
    slot, or the all-or-nothing destination allocation found the pool
    dry): a False return issued zero cache writes, so the router re-parks
    the packet and retries after decode frees capacity.
    """
    req = packet.req
    slot = next((i for i in range(engine.slots)
                 if engine._slot_req[i] is None), None)
    if slot is None:
        return False
    now = engine.clock()
    ps = engine._page_size
    n_tok = packet.n_tok
    # radix dedup on arrival: full prompt blocks the destination trie
    # already shares need no payload upload — map them straight into the
    # block table (acquired first, so allocation cannot evict them)
    path: list = []
    if engine._radix is not None:
        path, _matched = engine._radix.match(req.tokens)
    m_blocks = len(path)
    if m_blocks:
        engine._radix.acquire(path)
    total = pages_needed(n_tok + req.max_new, ps)
    private = engine._alloc_pages(total - m_blocks)
    if private is None:
        if m_blocks:
            engine._radix.release(path)
        return False
    engine._slot_alloc[slot] = [list(private), list(path)]
    bt_row = np.zeros((engine.max_len // ps,), np.int32)
    for j, node in enumerate(path):
        bt_row[j] = node.page
    for j, page in enumerate(private):
        bt_row[m_blocks + j] = page
    try:
        t0 = engine.clock()
        n_blocks = pages_needed(n_tok, ps)
        for j in range(m_blocks, n_blocks):
            with engine._compile.site("handoff_install"):
                payload = jax.tree.map(engine._dev, packet.payloads[j])
                engine.cache = engine._page_write(
                    engine.cache, payload,
                    jnp.asarray(int(bt_row[j]), jnp.int32))
        with engine._compile.site("handoff_install"):
            engine.cache = engine._bt_install(
                engine.cache, engine._dev(bt_row),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray(n_tok, jnp.int32))
        t1 = engine.clock()
        if engine._tracer is not None and req.trace is not None:
            engine._tracer.complete(
                "install", t0, t1, cat="handoff",
                parent=req.trace.get("phase") or req.trace["id"],
                tid=req.trace["tid"], pages=n_blocks - m_blocks,
                dedup_pages=m_blocks, slot=slot)
        if engine._radix is not None:
            engine.stats.radix(m_blocks > 0, tokens=m_blocks * ps)
            engine._radix.record(m_blocks > 0, tokens=m_blocks * ps)
            donate = {j: int(bt_row[j])
                      for j in range(m_blocks, n_tok // ps)}
            if donate:
                priv, nodes = engine._slot_alloc[slot]
                held, _kept = engine._radix.insert(
                    req.tokens, m_blocks, donate, path)
                for node in held:
                    priv.remove(node.page)
                    nodes.append(node)
        req.pages = total
        # first token: the source's logits row through the SAME shared
        # pick program every landing path uses — bit-identical to the
        # token a monolithic engine would have picked, which is what the
        # bench's disagg-vs-monolithic token-parity gate checks
        first, first_logp = engine._first_pick(
            req, engine._dev(packet.last_logits))
        req.generated.append(first)
        req.logprobs.append(first_logp)
        req.first_token_t = engine.clock()
        engine._last_progress_ever = req.first_token_t
        if req.ttft_slo_s is not None:
            req.slo_ttft_ok = (
                req.first_token_t - req.submit_t <= req.ttft_slo_s)
        if engine._telemetry is not None:
            engine._telemetry.observe(
                "ttft_s", req.first_token_t - req.submit_t,
                exemplar=(packet.trace_ctx.trace_id
                          if packet.trace_ctx is not None else None))
            engine._telemetry.inc("tokens_generated")
        req.status = "running"
        engine._tr_phase(req, "decode", slot=slot, handoff=True)
        engine._tr_instant(req, "first_token", slot=slot,
                           cache_hit=False)
        engine._notify(req, first)
    except Exception as e:
        # the request's OWN failure (poisoned callback and kin): reclaim
        # the destination pages, reset the (possibly installed) row, and
        # report the packet consumed — terminal, not re-parkable
        engine._release_slot_alloc(slot)
        engine._fail(req, e, engine.clock())
        engine._reset_slot_now(slot)
        return True
    engine._slot_req[slot] = req
    engine._slot_tok[slot] = first
    temp, topp, topk, minp, key = engine._req_sampling(req)
    engine._slot_temp[slot] = temp
    engine._slot_topp[slot] = topp
    engine._slot_topk[slot] = topk
    engine._slot_minp[slot] = minp
    engine._slot_key[slot] = key
    engine._tok_dev = None
    engine._active_dev = None
    engine._planes_dev = None
    engine._pos_dev = None
    engine.stats.prompt_admitted(n_tok)
    engine.handoffs_in += 1
    if req.admit_t is None:
        req.admit_t = now
    if engine._done_reason(req) is not None:
        engine._retire(slot, engine._done_reason(req), engine.clock())
        engine._reset_slot_now(slot)
    return True

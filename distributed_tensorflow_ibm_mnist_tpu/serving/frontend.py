"""The internet-shaped front door: an asyncio protocol server over the
daemonized serving tier (ISSUE 17).

Everything below the network edge already behaves like a service —
:class:`~.daemon.ServingDaemon` is long-lived, thread-safe, policy-
admitted, chaos-proven — but its callers are in-process Python.  This
module is the protocol layer that turns the library into a SERVICE
(TensorFlow's own library→serving move, PAPERS.md 1605.08695), built the
TF-Replicator way (1902.00465): the user-facing API is a stable wire
schema, and the execution tier under it can change shape — replicas
failing over, weights hot-swapping, the autoscaler breathing — without
the client ever seeing anything but tokens.

Endpoints (HTTP/1.1, stdlib ``asyncio.start_server`` — no new deps):

* ``POST /v1/generate`` — JSON in (prompt token ids, ``max_new``,
  optional per-request ``sampling``/``priority``/``deadline_s``/SLOs);
  JSON out, or an SSE token stream when ``"stream": true`` (one
  ``data: {"token": t}`` event per token, a terminal ``event: end`` with
  the final status).  Tokens cross from the daemon's delivery thread
  into asyncio via ``loop.call_soon_threadsafe`` — the thread-world →
  event-loop bridge — so SSE order is exactly delivery order and the
  stream inherits the tier's exactly-once guarantee across failover.
* ``GET /healthz`` — replica census (every replica's vitals, dead or
  alive) + the daemon's exact-conservation check; 503 when no healthy
  replica remains.
* ``GET /metrics`` — the existing :class:`~..utils.telemetry.
  MetricsRegistry` Prometheus exposition, snapshotted atomically (the
  registry's own lock) — the front door adds its counters to the SAME
  registry, so one scrape sees the whole tier.

Backpressure maps to status codes instead of buffering: the daemon's
:class:`~.scheduler.QueueFull` becomes **429** and
:class:`~.policies.SLOUnmeetable` (plus a draining/dead tier) becomes
**503**, each carrying ``Retry-After`` from the admission policy's wait
predictor when it has one (``exc.retry_after_s`` — ISSUE 17 satellite).
The accept side is bounded too (``max_connections``): past the bound a
connection gets an immediate 503, never an unbounded accept queue.

Client disconnect mid-stream CANCELS the underlying request: the handler
watches the socket for EOF while it streams, and a hangup calls
:meth:`~.daemon.ServingDaemon.cancel` — the slot frees, the KV pages
free, the tracer span closes, and conservation counts it ``cancelled``
(pinned in tests/test_frontend.py).  A disconnected client costs the
tier at most one pump sweep, not a slot leaked until deadline.
EXCEPT when the request carries an ``Idempotency-Key``: a keyed request
survives its client's disconnect — retry-ability is what the key asks
for — and a retried POST with the same key binds to the ORIGINAL
request instead of double-executing (422 when the key is reused with a
different body — the fingerprint check, scheduler.request_fingerprint).
SSE events carry ``id: <logical token index>`` lines, so a reconnecting
client sends ``Last-Event-ID`` and receives exactly the suffix it
missed; ``FrontDoor(idempotency_bindings=recovery.bindings)`` seeds the
dedup table across a process crash (serving/journal.py) — together
these stitch a client transcript exactly-once across resets AND kills.

Two liveness guards on the socket itself (ISSUE 18 satellites): the
head/body read runs under ``body_timeout_s`` — a slow-loris client gets
a 408 (counted ``frontdoor_read_timeout``) instead of holding one of
``max_connections`` slots forever — and idle streams emit ``: ping``
SSE comment frames every ``keepalive_s`` so proxies don't sever long
generations and a silently-dead peer is detected BETWEEN tokens (the
ping's write fails → cancel), not after the full generation is paid.

Distributed tracing (ISSUE 19): every accepted ``/v1/generate`` mints —
or, when the client sent a W3C ``traceparent`` header, JOINS — a
:class:`~..utils.tracing.TraceContext`, opens an ``http_request`` root
span, and threads the context through ``daemon.submit`` so ONE trace id
names the request from HTTP accept to the last SSE byte, across
failover replays (span links), disagg handoffs, and journal recovery.
Responses echo ``traceparent`` next to ``X-Request-Id``
(client-supplied ids are honored after sanitization — satellite 2);
429/503 sheds record a terminal ``shed`` span the tail sampler always
keeps even at ``trace_sample_rate=0``; ``GET /v1/requests/{id}/trace``
returns the request's correlated span tree; and ``/metrics`` speaks
exemplar-bearing OpenMetrics when the scraper sends
``Accept: application/openmetrics-text``.

Thread model: the server runs on ONE asyncio event loop (optionally on
its own thread via :meth:`FrontDoor.start_in_thread` — the test/bench
harness path).  Handler coroutines touch the daemon only through its
thread-safe surface (``submit``/``cancel``/``conservation``); daemon
threads touch asyncio only through ``call_soon_threadsafe``.  The
frontend's own counters are loop-thread-only ints mirrored into the
registry.

:class:`FrontDoorClient` is the curl-equivalent blocking client
(stdlib ``http.client``) the example, tests, and bench drive the wire
with — including an SSE parser, so parity checks compare the actual
bytes on the wire against :meth:`ServingDaemon.stream`.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import math
import threading
from typing import Callable, Iterator

from distributed_tensorflow_ibm_mnist_tpu.serving.policies import SLOUnmeetable
from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import SamplingParams
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
    QueueFull,
    request_fingerprint,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.tracing import (
    TraceContext,
    TraceSampler,
)

_MAX_BODY = 1 << 20          # 1 MiB request-body bound (413 past it)
_MAX_HEAD = 32 << 10         # request line + headers bound
_SAMPLING_KEYS = ("temperature", "top_p", "top_k", "min_p", "seed")
_MAX_RID = 64                # client X-Request-Id length cap
_RID_OK = set("abcdefghijklmnopqrstuvwxyz"
              "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._:-")
_TRACED_CAP = 512            # request-id -> trace-id map bound


def _sanitize_request_id(raw) -> str | None:
    """Validate a client-supplied ``X-Request-Id``: non-empty, at most
    ``_MAX_RID`` chars, drawn from ``[A-Za-z0-9._:-]``.  Anything else
    returns None and the front door falls back to its own id — a hostile
    header can never inject header-splitting bytes into the echo or an
    unbounded key into the trace map."""
    if not isinstance(raw, str) or not raw:
        return None
    if len(raw) > _MAX_RID or not set(raw) <= _RID_OK:
        return None
    return raw


class _BadRequest(ValueError):
    """Maps to a 400 with the message in the JSON error body."""


def _parse_generate(payload: dict) -> dict:
    """Validate the ``/v1/generate`` body into ``ServingDaemon.submit``
    kwargs.  Every verdict is a :class:`_BadRequest` naming the field —
    a malformed request costs the client a 400, never the tier a slot."""
    if not isinstance(payload, dict):
        raise _BadRequest("body must be a JSON object")
    prompt = payload.get("prompt")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in prompt)):
        raise _BadRequest("'prompt' must be a non-empty list of token ids")
    max_new = payload.get("max_new")
    if not isinstance(max_new, int) or isinstance(max_new, bool) or max_new < 1:
        raise _BadRequest("'max_new' must be an int >= 1")
    out = {"prompt": prompt, "max_new": max_new,
           "stream": bool(payload.get("stream", False))}
    priority = payload.get("priority", 0)
    if not isinstance(priority, int) or isinstance(priority, bool):
        raise _BadRequest("'priority' must be an int")
    out["priority"] = priority
    for key in ("deadline_s", "ttft_slo_s", "tpot_slo_s"):
        val = payload.get(key)
        if val is not None:
            if not isinstance(val, (int, float)) or isinstance(val, bool) \
                    or not val > 0:
                raise _BadRequest(f"'{key}' must be a number > 0")
            val = float(val)
        out[key] = val
    sampling = payload.get("sampling")
    if sampling is not None:
        if not isinstance(sampling, dict):
            raise _BadRequest("'sampling' must be an object")
        unknown = set(sampling) - set(_SAMPLING_KEYS)
        if unknown:
            raise _BadRequest(
                f"unknown sampling keys {sorted(unknown)} — "
                f"allowed: {list(_SAMPLING_KEYS)}")
        try:
            sampling = SamplingParams(**sampling)
        except (TypeError, ValueError) as e:
            raise _BadRequest(f"bad sampling params: {e}") from None
    out["sampling"] = sampling
    return out


class FrontDoor:
    """HTTP/SSE network edge over one :class:`~.daemon.ServingDaemon`.

    ``port=0`` binds an ephemeral port (read :attr:`port` after start —
    the test/bench pattern).  ``max_connections`` bounds concurrently
    served connections; past it a connection is answered 503 +
    ``Retry-After`` immediately.  ``registry`` is the MetricsRegistry
    ``/metrics`` exposes — default: the daemon's telemetry registry when
    one is wired, else a private one (the endpoint always works).
    """

    def __init__(self, daemon, host: str = "127.0.0.1", port: int = 0, *,
                 max_connections: int = 64, registry=None,
                 keepalive_s: float = 15.0, body_timeout_s: float = 30.0,
                 idempotency_bindings: dict | None = None,
                 tracer=None, trace_sample_rate: float = 1.0):
        if max_connections < 1:
            raise ValueError(
                f"max_connections must be >= 1, got {max_connections}")
        if keepalive_s <= 0:
            raise ValueError(f"keepalive_s must be > 0, got {keepalive_s}")
        if body_timeout_s <= 0:
            raise ValueError(
                f"body_timeout_s must be > 0, got {body_timeout_s}")
        self.daemon = daemon
        self.host = host
        self.port = int(port)          # rebound to the real port at start
        self.max_connections = int(max_connections)
        self.keepalive_s = float(keepalive_s)
        self.body_timeout_s = float(body_timeout_s)
        if registry is None and daemon._telemetry is not None:
            registry = daemon._telemetry.registry
        if registry is None:
            from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import (
                MetricsRegistry,
            )
            registry = MetricsRegistry()
        self.registry = registry
        # loop-thread-only books (mirrored into the registry for scrapes)
        self.counters = {"connections": 0, "over_capacity": 0,
                         "requests": 0, "streams": 0, "bad_requests": 0,
                         "rejected_429": 0, "rejected_503": 0,
                         "disconnects": 0, "disconnect_cancels": 0,
                         "read_timeout": 0, "keepalive_pings": 0,
                         "idempotent_hits": 0, "idempotent_conflicts": 0,
                         "resumes": 0}
        # Idempotency-Key -> (fingerprint, DaemonRequest): loop-thread-
        # only, like the counters.  Seed with ``recovery.bindings``
        # (serving/journal.Recovery) so retries from before a crash bind
        # to their replayed request — the cross-crash dedup table.
        self._idem: dict[str, tuple[str | None, object]] = {}
        for key, dr in (idempotency_bindings or {}).items():
            self._idem[str(key)] = (getattr(dr, "fingerprint", None), dr)
        # distributed tracing: default to the daemon's tracer so the
        # http_request span parents the daemon/engine spans by plain int
        # id (one in-process tracer end to end); an explicitly different
        # tracer still joins via the span_ctx/parent_ctx hex edges
        self._tracer = (tracer if tracer is not None
                        else getattr(daemon, "_tracer", None))
        self.sampler = TraceSampler(rate=trace_sample_rate)
        # request id (client-supplied or daemon) -> trace id, bounded
        # FIFO — the lookup table behind GET /v1/requests/{id}/trace
        self._traced: dict[str, str] = {}
        self._active = 0
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._conn_tasks: set[asyncio.Task] = set()

    def _bump(self, name: str, n: int = 1) -> None:
        self.counters[name] += n
        self.registry.inc(f"frontdoor_{name}", n)

    # ------------------------------------------------------------------
    # distributed tracing (ISSUE 19)

    def _trace_begin(self, headers: dict, **span_args):
        """Mint — or, given a valid client ``traceparent``, JOIN — the
        request's trace context and open the ``http_request`` root span
        on its own viewer track.  The context is built even with no
        tracer wired (the header echo and the journal's trace
        persistence need it); the span carries ``span_ctx`` so a
        different-tracer daemon still connects via the hex edge, and a
        client parent lands as a ``parent_ctx`` edge pointing out of
        this process.  Returns ``(ctx, ts)`` where ``ts`` is the span
        bookkeeping dict (None when tracing is off)."""
        client = TraceContext.parse_traceparent(headers.get("traceparent"))
        if client is not None:
            ctx = client.child()   # same trace id, our own span id,
            #   the CLIENT's head-sampling verdict honored as-is
        else:
            ctx = TraceContext.mint()
            ctx.sampled = self.sampler.head(ctx.trace_id)
        ts = None
        if self._tracer is not None:
            kw = dict(trace=ctx.trace_id, sampled=ctx.sampled,
                      span_ctx=ctx.span_id, **span_args)
            if client is not None:
                kw["parent_ctx"] = client.span_id
            tid = self._tracer.track(f"http {ctx.span_id[:8]}")
            ts = {"span": self._tracer.begin(
                      "http_request", cat="frontdoor", tid=tid, **kw),
                  "tid": tid}
        return ctx, ts

    def _tr_finish(self, ts, status=None, **args) -> None:
        """Close the ``http_request`` root span — idempotent, called on
        EVERY exit path of ``_generate`` (the engine suite pins
        ``open_spans == 0`` after drain; the front door honors the same
        no-leak contract)."""
        if self._tracer is None or ts is None:
            return
        sid = ts.pop("span", None)
        if sid is None:
            return
        self._tracer.end(sid, status=status, **args)

    def _tr_shed(self, ts, code: int, error: str) -> None:
        """Mark a 429/503 rejection: a terminal ``shed`` child span plus
        ``status="shed"`` on the root — BOTH tail-sampler always-keep
        triggers, so shed requests survive export even at
        ``trace_sample_rate=0`` (satellite 6)."""
        if self._tracer is None or ts is None:
            return
        sid = ts.get("span")
        if sid is not None:
            now = self._tracer.clock()
            self._tracer.complete("shed", now, now, cat="frontdoor",
                                  parent=sid, tid=ts.get("tid", 0),
                                  code=code, error=error)
        self._tr_finish(ts, status="shed", code=code)

    def _remember_trace(self, rid, trace_id: str) -> None:
        m = self._traced
        m[str(rid)] = trace_id
        while len(m) > _TRACED_CAP:
            m.pop(next(iter(m)))

    @staticmethod
    def _trace_headers(rid, ctx) -> dict:
        h = {}
        if rid is not None:
            h["X-Request-Id"] = str(rid)
        if ctx is not None:
            h["traceparent"] = ctx.to_traceparent()
        return h

    # ------------------------------------------------------------------
    # lifecycle

    async def start(self) -> "FrontDoor":
        """Bind and start serving on the RUNNING event loop."""
        if self._server is not None:
            return self
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=_MAX_HEAD)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def aclose(self) -> None:
        """Stop accepting, cancel open handlers, close the socket."""
        if self._server is None:
            return
        self._server.close()
        await self._server.wait_closed()
        for task in list(self._conn_tasks):
            task.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._server = None

    def start_in_thread(self) -> "FrontDoor":
        """Run the server on a dedicated event-loop thread; returns once
        the socket is bound (``self.port`` live).  Pair with
        :meth:`stop`; this is the harness path for tests/benches/examples
        whose main thread drives blocking clients."""
        if self._thread is not None:
            return self
        loop = asyncio.new_event_loop()
        ready = threading.Event()
        boot_exc: list[BaseException] = []

        def _run():
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self.start())
            except BaseException as e:   # bind failure must reach caller
                boot_exc.append(e)
                ready.set()
                return
            ready.set()
            loop.run_forever()

        self._thread = threading.Thread(target=_run, name="dtm-frontdoor",
                                        daemon=True)
        self._thread.start()
        ready.wait()
        if boot_exc:
            self._thread.join(timeout=5.0)
            self._thread = None
            raise boot_exc[0]
        self._loop = loop
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Shut down a :meth:`start_in_thread` server (idempotent)."""
        if self._thread is None:
            return
        fut = asyncio.run_coroutine_threadsafe(self.aclose(), self._loop)
        try:
            fut.result(timeout=timeout)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=timeout)
            self._loop.close()
            self._thread = None

    def __enter__(self) -> "FrontDoor":
        return self.start_in_thread()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # connection handling

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        task.add_done_callback(self._conn_tasks.discard)
        self._bump("connections")
        if self._active >= self.max_connections:
            # bounded accept backpressure: answer, never queue unboundedly
            self._bump("over_capacity")
            await self._respond_json(
                writer, 503,
                {"error": "server at connection capacity", "retry_after_s": 1.0},
                extra_headers={"Retry-After": "1"})
            await self._hangup(writer)
            return
        self._active += 1
        try:
            await self._serve_one(reader, writer)
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        except Exception:
            with _swallow():
                await self._respond_json(
                    writer, 500, {"error": "internal server error"})
        finally:
            self._active -= 1
            await self._hangup(writer)

    async def _serve_one(self, reader, writer) -> None:
        try:
            head = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"),
                                          timeout=self.body_timeout_s)
        except asyncio.TimeoutError:
            # slow-loris: dribbling (or silent) headers past the read
            # deadline gets a verdict and frees the slot, never holds it
            self._bump("read_timeout")
            await self._respond_json(
                writer, 408, {"error": "request head read timed out"})
            return
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            return
        try:
            request_line, *header_lines = head.decode("latin-1").split("\r\n")
            method, target, _version = request_line.split(" ", 2)
            headers = {}
            for line in header_lines:
                if ":" in line:
                    k, v = line.split(":", 1)
                    headers[k.strip().lower()] = v.strip()
        except ValueError:
            await self._respond_json(writer, 400,
                                     {"error": "malformed request"})
            return
        target = target.split("?", 1)[0]
        if target == "/healthz":
            if method != "GET":
                await self._respond_json(writer, 405,
                                         {"error": "use GET /healthz"})
                return
            await self._healthz(writer)
        elif target == "/metrics":
            if method != "GET":
                await self._respond_json(writer, 405,
                                         {"error": "use GET /metrics"})
                return
            await self._metrics(writer, headers)
        elif target.startswith("/v1/requests/") and target.endswith("/trace"):
            if method != "GET":
                await self._respond_json(
                    writer, 405, {"error": "use GET /v1/requests/{id}/trace"})
                return
            await self._request_trace(writer, target)
        elif target == "/v1/generate":
            if method != "POST":
                await self._respond_json(writer, 405,
                                         {"error": "use POST /v1/generate"})
                return
            await self._generate(reader, writer, headers)
        else:
            await self._respond_json(writer, 404,
                                     {"error": f"no such endpoint {target}"})

    # ------------------------------------------------------------------
    # endpoints

    async def _healthz(self, writer) -> None:
        router = self.daemon.router
        conservation = self.daemon.conservation()
        healthy = len(router.healthy())
        body = {
            "status": ("ok" if healthy and conservation["conserved"]
                       else "degraded"),
            "healthy": healthy,
            "n_replicas": len(router.replicas),
            "retiring": len(router._retiring),
            "replicas": {str(r.index): r.vitals() for r in router.replicas},
            "conservation": conservation,
        }
        await self._respond_json(writer, 200 if healthy else 503, body)

    async def _metrics(self, writer, headers: dict | None = None) -> None:
        # to_prometheus()/to_openmetrics() serialize under the registry
        # lock — the scrape is one atomic snapshot even while pumps are
        # counting.  Content negotiation: an OpenMetrics Accept gets the
        # exemplar-bearing exposition (trace ids on histogram buckets).
        accept = (headers or {}).get("accept", "")
        if "application/openmetrics-text" in accept:
            text = self.registry.to_openmetrics().encode("utf-8")
            ctype = ("application/openmetrics-text; "
                     "version=1.0.0; charset=utf-8")
        else:
            text = self.registry.to_prometheus().encode("utf-8")
            ctype = "text/plain; version=0.0.4"
        await self._respond_raw(writer, 200, text, content_type=ctype)

    async def _request_trace(self, writer, target: str) -> None:
        """``GET /v1/requests/{id}/trace`` — the request's correlated
        span tree (closed events + still-open spans) straight off the
        tracer ring, keyed by the id the response echoed."""
        rid = target[len("/v1/requests/"):-len("/trace")]
        if self._tracer is None:
            await self._respond_json(
                writer, 503, {"error": "no tracer wired to this front door"})
            return
        trace_id = self._traced.get(rid)
        if trace_id is None:
            await self._respond_json(
                writer, 404,
                {"error": f"no trace recorded for request {rid!r}"})
            return
        events = self._tracer.trace_events(trace_id)
        await self._respond_json(
            writer, 200, {"request_id": rid, "trace_id": trace_id,
                          "n_events": len(events), "events": events})

    async def _generate(self, reader, writer, headers: dict) -> None:
        self._bump("requests")
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            length = -1
        if length <= 0:
            self._bump("bad_requests")
            await self._respond_json(
                writer, 400, {"error": "Content-Length body required"})
            return
        if length > _MAX_BODY:
            self._bump("bad_requests")
            await self._respond_json(
                writer, 413, {"error": f"body exceeds {_MAX_BODY} bytes"})
            return
        try:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          timeout=self.body_timeout_s)
            spec = _parse_generate(json.loads(body))
        except _BadRequest as e:
            self._bump("bad_requests")
            await self._respond_json(writer, 400, {"error": str(e)})
            return
        except (json.JSONDecodeError, UnicodeDecodeError):
            self._bump("bad_requests")
            await self._respond_json(writer, 400, {"error": "invalid JSON"})
            return
        except asyncio.TimeoutError:
            # slow-loris body: Content-Length promised bytes that never
            # came — verdict + counter, the connection slot frees
            self._bump("read_timeout")
            await self._respond_json(
                writer, 408, {"error": "request body read timed out"})
            return
        except asyncio.IncompleteReadError:
            return

        # trace begin AFTER the body parsed (a malformed request never
        # costs a span) and BEFORE admission — rejects are traced too
        ctx, ts = self._trace_begin(headers, method="POST",
                                    target="/v1/generate",
                                    stream=spec["stream"])
        client_rid = _sanitize_request_id(headers.get("x-request-id"))

        idem_key = headers.get("idempotency-key") or None
        last_event_id = None
        if "last-event-id" in headers:
            try:
                last_event_id = int(headers["last-event-id"])
            except ValueError:
                self._bump("bad_requests")
                self._tr_finish(ts, status="bad_request")
                await self._respond_json(
                    writer, 400,
                    {"error": "Last-Event-ID must be an integer token index"})
                return
        if idem_key is not None:
            fp = request_fingerprint(spec["prompt"], spec["max_new"],
                                     spec["sampling"])
            bound = self._idem.get(idem_key)
            if bound is not None:
                bound_fp, bound_dr = bound
                if bound_fp is not None and bound_fp != fp:
                    # a key names ONE request forever — reusing it with a
                    # different body is a client bug, not a new request
                    self._bump("idempotent_conflicts")
                    self._tr_finish(ts, status="conflict",
                                    request=bound_dr.id)
                    await self._respond_json(
                        writer, 422,
                        {"error": "Idempotency-Key already bound to a "
                                  "different request body",
                         "id": bound_dr.id})
                    return
                # the retry binds to the ORIGINAL request: no second
                # execution, the stream picks up wherever the client
                # says it left off (Last-Event-ID).  The rebind's OWN
                # http span closes here; the echoed traceparent is the
                # original execution's trace — the one worth looking up
                self._bump("idempotent_hits")
                self._tr_finish(ts, status="rebind", request=bound_dr.id)
                if spec["stream"]:
                    self._bump("streams")
                    self._bump("resumes")
                    await self._stream_resume(reader, writer, bound_dr,
                                              last_event_id,
                                              rid=client_rid)
                else:
                    await self._collect_rebind(writer, bound_dr,
                                               rid=client_rid)
                return

        loop = asyncio.get_running_loop()
        events: asyncio.Queue = asyncio.Queue()

        def on_token(_dr, tok):
            # delivery thread → event loop: the ONE legal crossing
            loop.call_soon_threadsafe(events.put_nowait, ("tok", int(tok)))

        # int-id parenting only works inside ONE tracer; a front door
        # given its own tracer still joins through the hex ctx edges
        tp_parent = (ts["span"] if ts is not None
                     and self._tracer is getattr(self.daemon, "_tracer", None)
                     else None)
        try:
            dr = self.daemon.submit(
                spec["prompt"], spec["max_new"], callback=on_token,
                deadline_s=spec["deadline_s"], priority=spec["priority"],
                ttft_slo_s=spec["ttft_slo_s"], tpot_slo_s=spec["tpot_slo_s"],
                sampling=spec["sampling"], idempotency_key=idem_key,
                trace_ctx=ctx, trace_parent=tp_parent)
        except SLOUnmeetable as e:
            self._bump("rejected_503")
            self._tr_shed(ts, 503, str(e))
            await self._respond_reject(writer, 503, e,
                                       trace=self._trace_headers(
                                           client_rid, ctx))
            return
        except QueueFull as e:
            self._bump("rejected_429")
            self._tr_shed(ts, 429, str(e))
            await self._respond_reject(writer, 429, e,
                                       trace=self._trace_headers(
                                           client_rid, ctx))
            return
        except RuntimeError as e:       # daemon draining/closed
            self._bump("rejected_503")
            self._tr_shed(ts, 503, str(e))
            await self._respond_json(
                writer, 503, {"error": str(e)},
                extra_headers=self._trace_headers(client_rid, ctx))
            return
        except ValueError as e:         # engine-level validation
            self._bump("bad_requests")
            self._tr_finish(ts, status="bad_request")
            await self._respond_json(writer, 400, {"error": str(e)})
            return
        # the id the response echoes (client-supplied when valid) and
        # the daemon id BOTH resolve through /v1/requests/{id}/trace
        rid = client_rid if client_rid is not None else str(dr.id)
        self._remember_trace(rid, ctx.trace_id)
        self._remember_trace(dr.id, ctx.trace_id)

        # the delivery callback only ENQUEUES to this loop — receipt is
        # the drained socket write, so THIS side journals the delivered
        # high-water (per token for SSE; unary clients receive nothing
        # until the end, so a crashed unary request replays from 0)
        dr.external_receipt = True
        if idem_key is not None:
            # bind AFTER a successful submit: a rejected request never
            # occupies its key (the client's retry should get a fresh try)
            self._idem[idem_key] = (fp, dr)

        # end-of-request watcher: a worker thread parks on the request's
        # terminal event and posts the sentinel AFTER every token callback
        # already crossed (the delivery thread runs callbacks before it
        # sets _done, and call_soon_threadsafe preserves order)
        async def _await_end():
            await loop.run_in_executor(None, dr._done.wait)
            events.put_nowait(("end", None))

        end_task = asyncio.ensure_future(_await_end())
        # disconnect watcher: the client sends nothing after the request,
        # so a read completing means EOF/reset — the socket is gone
        disconnect = asyncio.ensure_future(reader.read(1))
        try:
            if spec["stream"]:
                self._bump("streams")
                await self._stream_sse(writer, dr, events, disconnect,
                                       rid=rid)
            else:
                await self._collect_json(writer, dr, events, disconnect,
                                         rid=rid, ctx=ctx)
        finally:
            disconnect.cancel()
            end_task.cancel()
            with _swallow():
                await asyncio.gather(end_task, disconnect,
                                     return_exceptions=True)
            # the root span covers accept -> last byte written: close it
            # here, after the stream/collect finished (or died), with
            # the request's terminal verdict as the tail-keep signal
            self._tr_finish(ts, status=dr.status, request=dr.id)

    async def _next_event(self, events: asyncio.Queue,
                          disconnect: asyncio.Task,
                          timeout: float | None = None):
        """One delivery event, or ``("disconnect", None)`` the moment the
        client hangs up with nothing pending — pending tokens drain first
        (they are already paid for; the disconnect verdict can wait one
        queue pop).  With ``timeout`` (the keep-alive interval), an idle
        wait yields ``("ping", None)`` instead of parking forever."""
        if not events.empty():
            return events.get_nowait()
        getter = asyncio.ensure_future(events.get())
        done, _pending = await asyncio.wait(
            {getter, disconnect}, timeout=timeout,
            return_when=asyncio.FIRST_COMPLETED)
        if getter in done:
            return getter.result()
        getter.cancel()
        with _swallow():
            await getter
        if disconnect in done:
            return ("disconnect", None)
        return ("ping", None)

    def _cancel_on_disconnect(self, dr) -> None:
        self._bump("disconnects")
        if dr.idempotency_key is not None:
            # a keyed request SURVIVES its client's disconnect — retry-
            # ability is what the key asks for: it stays bound in the
            # dedup table and keeps generating, so the retried POST
            # resumes a live stream instead of a cancelled stump
            return
        if not dr.done:
            self.daemon.cancel(dr, reason="client disconnected")
            self._bump("disconnect_cancels")

    def _journal_hw(self, dr, hw: int) -> None:
        """Journal the delivered high-water AFTER a drained socket write
        — the only point where the front door knows the client's kernel
        has the bytes.  On loopback a SIGKILL still flushes drained
        data, so this mark never overstates what the client received."""
        j = self.daemon._journal
        if j is None:
            return
        try:
            j.delivered(dr.id, hw)
        except Exception:
            self.daemon._count("journal_errors")

    def _sse_head(self, dr, rid=None) -> bytes:
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: text/event-stream\r\n"
                b"Cache-Control: no-cache\r\n"
                b"Connection: close\r\n"
                + f"X-Request-Id: {dr.id if rid is None else rid}\r\n"
                .encode())
        # streams echo traceparent too (satellite 2) — derived from the
        # request itself so idempotent rebinds echo the ORIGINAL trace
        tctx = getattr(dr, "trace_ctx", None)
        if tctx is not None:
            head += f"traceparent: {tctx.to_traceparent()}\r\n".encode()
        return head + b"\r\n"

    @staticmethod
    def _sse_token(idx: int, token: int) -> bytes:
        # the id: line is the resume cursor — a client that reconnects
        # sends it back as Last-Event-ID and gets exactly the suffix
        return (f"id: {idx}\n".encode() + b"data: "
                + json.dumps({"token": token}).encode() + b"\n\n")

    def _sse_terminal(self, dr) -> bytes:
        terminal = {"id": dr.id, "status": dr.status, "error": dr.error,
                    "n_tokens": dr.total_tokens}
        return (b"event: end\ndata: "
                + json.dumps(terminal).encode() + b"\n\n")

    async def _stream_sse(self, writer, dr, events, disconnect,
                          rid=None) -> None:
        writer.write(self._sse_head(dr, rid=rid))
        idx = dr.resume_from   # 0 for every front-door-fresh request
        try:
            await writer.drain()
            while True:
                kind, payload = await self._next_event(
                    events, disconnect, timeout=self.keepalive_s)
                if kind == "tok":
                    writer.write(self._sse_token(idx, payload))
                    idx += 1
                    await writer.drain()
                    self._journal_hw(dr, idx)
                elif kind == "end":
                    writer.write(self._sse_terminal(dr))
                    await writer.drain()
                    return
                elif kind == "ping":
                    # idle heartbeat: keeps proxies from severing a slow
                    # generation AND probes the peer — writing to a dead
                    # socket raises here, between tokens, not after the
                    # whole generation was paid for
                    self._bump("keepalive_pings")
                    writer.write(b": ping\n\n")
                    await writer.drain()
                else:
                    self._cancel_on_disconnect(dr)
                    return
        except (ConnectionResetError, BrokenPipeError):
            self._cancel_on_disconnect(dr)

    async def _stream_resume(self, reader, writer, dr, last_event_id,
                             rid=None) -> None:
        """Serve an idempotent-retry SSE rebind by POLLING ``dr.tokens``
        growth (list append is atomic; the single-slot delivery callback
        belongs to the original connection, so a rebind cannot ride the
        queue path).  Starts after ``Last-Event-ID`` when the client
        sent one, else at the earliest token this process can serve
        (``dr.resume_from`` — pre-crash tokens below it were delivered
        to, and journaled against, the pre-crash stream)."""
        writer.write(self._sse_head(dr, rid=rid))
        start = dr.resume_from if last_event_id is None else last_event_id + 1
        idx = max(start, dr.resume_from)
        disconnect = asyncio.ensure_future(reader.read(1))
        try:
            await writer.drain()
            idle_s = 0.0
            while True:
                wrote = False
                while idx < dr.total_tokens:
                    writer.write(self._sse_token(
                        idx, dr.tokens[idx - dr.resume_from]))
                    idx += 1
                    wrote = True
                if wrote:
                    idle_s = 0.0
                    await writer.drain()
                    self._journal_hw(dr, idx)
                if dr.done and idx >= dr.total_tokens:
                    writer.write(self._sse_terminal(dr))
                    await writer.drain()
                    return
                if disconnect.done():
                    self._cancel_on_disconnect(dr)
                    return
                if idle_s >= self.keepalive_s:
                    idle_s = 0.0
                    self._bump("keepalive_pings")
                    writer.write(b": ping\n\n")
                    await writer.drain()
                await asyncio.sleep(0.005)
                idle_s += 0.005
        except (ConnectionResetError, BrokenPipeError):
            self._cancel_on_disconnect(dr)
        finally:
            disconnect.cancel()
            with _swallow():
                await disconnect

    async def _collect_rebind(self, writer, dr, rid=None) -> None:
        """Unary idempotent retry: wait out the ORIGINAL request and
        return its verdict — one execution, however many retries."""
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, dr._done.wait)
        body = {"id": dr.id, "status": dr.status, "error": dr.error,
                "tokens": list(dr.tokens), "resume_from": dr.resume_from}
        try:
            await self._respond_json(
                writer, 200, body,
                extra_headers=self._trace_headers(
                    dr.id if rid is None else rid,
                    getattr(dr, "trace_ctx", None)))
        except (ConnectionResetError, BrokenPipeError):
            self._bump("disconnects")

    async def _collect_json(self, writer, dr, events, disconnect,
                            rid=None, ctx=None) -> None:
        while True:
            kind, _payload = await self._next_event(events, disconnect)
            if kind == "end":
                break
            if kind == "disconnect":
                # keyed requests keep running for a future retry
                # (_cancel_on_disconnect skips the cancel) — but THIS
                # socket is gone either way, stop serving it
                self._cancel_on_disconnect(dr)
                return
        body = {"id": dr.id, "status": dr.status, "error": dr.error,
                "tokens": list(dr.tokens)}
        try:
            await self._respond_json(
                writer, 200, body,
                extra_headers=self._trace_headers(
                    dr.id if rid is None else rid, ctx))
        except (ConnectionResetError, BrokenPipeError):
            self._bump("disconnects")

    # ------------------------------------------------------------------
    # response plumbing

    async def _respond_reject(self, writer, code: int, exc: QueueFull,
                              trace: dict | None = None) -> None:
        """429/503 with the policy's backoff hint as a real Retry-After
        header (integer seconds, ceil — never rounded to an instant
        retry) AND machine-readable in the body; ``trace`` carries the
        X-Request-Id/traceparent echo so a shed request is findable."""
        hint = getattr(exc, "retry_after_s", None)
        extra = dict(trace or {})
        if hint is not None:
            extra["Retry-After"] = str(max(1, math.ceil(hint)))
        await self._respond_json(
            writer, code,
            {"error": str(exc),
             "retry_after_s": None if hint is None else round(float(hint), 6)},
            extra_headers=extra or None)

    async def _respond_json(self, writer, code: int, body: dict,
                            extra_headers: dict | None = None) -> None:
        await self._respond_raw(
            writer, code, json.dumps(body).encode("utf-8"),
            content_type="application/json", extra_headers=extra_headers)

    async def _respond_raw(self, writer, code: int, body: bytes, *,
                           content_type: str,
                           extra_headers: dict | None = None) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 408: "Request Timeout",
                  413: "Payload Too Large", 422: "Unprocessable Entity",
                  429: "Too Many Requests", 500: "Internal Server Error",
                  503: "Service Unavailable"}.get(code, "Unknown")
        head = [f"HTTP/1.1 {code} {reason}",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(body)}",
                "Connection: close"]
        for k, v in (extra_headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body)
        await writer.drain()

    async def _hangup(self, writer) -> None:
        with _swallow():
            writer.close()
            await writer.wait_closed()


class _swallow:
    """``with _swallow():`` — an async-teardown guard: nothing raised
    while closing an already-dead socket should replace the real story."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return True


# ----------------------------------------------------------------------
# the curl-equivalent client (stdlib http.client) — example/tests/bench


class FrontDoorClient:
    """Blocking wire client for one :class:`FrontDoor`.

    Every call opens a fresh connection (the server is
    ``Connection: close``).  :meth:`generate` returns the parsed JSON
    verdict; :meth:`stream` yields tokens off the SSE wire as they
    arrive and stores the terminal event on :attr:`last_terminal` —
    byte-level parity with :meth:`ServingDaemon.stream` is exactly what
    the bench gates.
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.last_terminal: dict | None = None
        self.last_status: int | None = None
        self.last_headers: dict | None = None
        # highest SSE id: seen on the most recent stream() — what a
        # reconnect sends as Last-Event-ID to resume exactly-once
        self.last_event_id: int | None = None

    def _request(self, method: str, path: str, payload: dict | None = None,
                 headers: dict | None = None):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        body = None if payload is None else json.dumps(payload)
        send_headers = ({"Content-Type": "application/json"}
                        if body is not None else {})
        send_headers.update(headers or {})
        conn.request(method, path, body=body, headers=send_headers)
        resp = conn.getresponse()
        self.last_status = resp.status
        self.last_headers = {k.lower(): v for k, v in resp.getheaders()}
        return conn, resp

    def _json_call(self, method: str, path: str,
                   payload: dict | None = None,
                   headers: dict | None = None) -> dict:
        conn, resp = self._request(method, path, payload, headers)
        try:
            raw = resp.read()
        finally:
            conn.close()
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return {"raw": raw.decode("utf-8", "replace")}

    @staticmethod
    def _retry_headers(idempotency_key, last_event_id) -> dict:
        h = {}
        if idempotency_key is not None:
            h["Idempotency-Key"] = str(idempotency_key)
        if last_event_id is not None:
            h["Last-Event-ID"] = str(int(last_event_id))
        return h

    def generate(self, prompt, max_new: int, *,
                 idempotency_key: str | None = None,
                 extra_headers: dict | None = None, **kw) -> dict:
        """POST /v1/generate, non-streaming; returns the JSON body (the
        ``tokens`` list on 200, the error + ``retry_after_s`` on 4xx/5xx;
        check :attr:`last_status`).  ``idempotency_key`` makes the call
        safe to re-issue after a connection reset: the retry binds to
        the original execution.  ``extra_headers`` rides along verbatim
        (``X-Request-Id``, ``traceparent``, ...)."""
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new": int(max_new), **kw}
        send = self._retry_headers(idempotency_key, None)
        send.update(extra_headers or {})
        return self._json_call("POST", "/v1/generate", payload, send)

    def stream(self, prompt, max_new: int, *,
               idempotency_key: str | None = None,
               last_event_id: int | None = None,
               extra_headers: dict | None = None, **kw) -> Iterator[int]:
        """POST /v1/generate with ``stream: true``; yields each token as
        its SSE event arrives.  On a non-200 the rejection body lands in
        :attr:`last_terminal` and nothing is yielded.  Each event's
        ``id:`` updates :attr:`last_event_id`; pass it back (with the
        same ``idempotency_key``) to resume a severed stream from
        exactly the next token."""
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new": int(max_new), "stream": True, **kw}
        self.last_terminal = None
        self.last_event_id = None if last_event_id is None else int(last_event_id)
        send = self._retry_headers(idempotency_key, last_event_id)
        send.update(extra_headers or {})
        conn, resp = self._request("POST", "/v1/generate", payload, send)
        try:
            if resp.status != 200:
                raw = resp.read()
                try:
                    self.last_terminal = json.loads(raw)
                except json.JSONDecodeError:
                    self.last_terminal = {"raw": raw.decode("utf-8", "replace")}
                return
            for event, data, eid in _iter_sse(resp):
                if event == "end":
                    self.last_terminal = data
                    return
                if eid is not None:
                    self.last_event_id = eid
                yield int(data["token"])
        finally:
            conn.close()

    def healthz(self) -> dict:
        return self._json_call("GET", "/healthz")

    def request_trace(self, request_id) -> dict:
        """GET /v1/requests/{id}/trace — the span tree the front door
        recorded for ``request_id`` (client-supplied or daemon id)."""
        return self._json_call("GET", f"/v1/requests/{request_id}/trace")

    def metrics(self, accept: str | None = None) -> str:
        """GET /metrics; pass ``accept="application/openmetrics-text"``
        for the exemplar-bearing OpenMetrics exposition."""
        conn, resp = self._request(
            "GET", "/metrics",
            headers=None if accept is None else {"Accept": accept})
        try:
            return resp.read().decode("utf-8")
        finally:
            conn.close()


def _iter_sse(resp) -> Iterator[tuple[str, dict, int | None]]:
    """Parse an SSE byte stream into ``(event, json_data, id)`` triples.
    ``event`` is ``"message"`` for bare ``data:`` lines (tokens) and the
    explicit event name otherwise (the terminal ``end``).  ``id`` is the
    logical token index from the event's ``id:`` line, ``None`` when the
    event carries none (the terminal).  ``:`` comment lines (keep-alive
    pings) are skipped."""
    event = "message"
    event_id: int | None = None
    data_lines: list[str] = []
    for raw in resp:
        line = raw.decode("utf-8").rstrip("\n").rstrip("\r")
        if line.startswith(":"):
            continue  # comment frame — keep-alive ping, not an event
        if line.startswith("event:"):
            event = line[len("event:"):].strip()
        elif line.startswith("id:"):
            try:
                event_id = int(line[len("id:"):].strip())
            except ValueError:
                event_id = None
        elif line.startswith("data:"):
            data_lines.append(line[len("data:"):].strip())
        elif line == "" and data_lines:
            yield event, json.loads("\n".join(data_lines)), event_id
            event = "message"
            event_id = None
            data_lines = []

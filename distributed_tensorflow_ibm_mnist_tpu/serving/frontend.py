"""The internet-shaped front door: an asyncio protocol server over the
daemonized serving tier (ISSUE 17).

Everything below the network edge already behaves like a service —
:class:`~.daemon.ServingDaemon` is long-lived, thread-safe, policy-
admitted, chaos-proven — but its callers are in-process Python.  This
module is the protocol layer that turns the library into a SERVICE
(TensorFlow's own library→serving move, PAPERS.md 1605.08695), built the
TF-Replicator way (1902.00465): the user-facing API is a stable wire
schema, and the execution tier under it can change shape — replicas
failing over, weights hot-swapping, the autoscaler breathing — without
the client ever seeing anything but tokens.

Endpoints (HTTP/1.1, stdlib ``asyncio.start_server`` — no new deps):

* ``POST /v1/generate`` — JSON in (prompt token ids, ``max_new``,
  optional per-request ``sampling``/``priority``/``deadline_s``/SLOs);
  JSON out, or an SSE token stream when ``"stream": true`` (one
  ``data: {"token": t}`` event per token, a terminal ``event: end`` with
  the final status).  Tokens cross from the daemon's delivery thread
  into asyncio via ``loop.call_soon_threadsafe`` — the thread-world →
  event-loop bridge — so SSE order is exactly delivery order and the
  stream inherits the tier's exactly-once guarantee across failover.
* ``GET /healthz`` — replica census (every replica's vitals, dead or
  alive) + the daemon's exact-conservation check; 503 when no healthy
  replica remains.
* ``GET /metrics`` — the existing :class:`~..utils.telemetry.
  MetricsRegistry` Prometheus exposition, snapshotted atomically (the
  registry's own lock) — the front door adds its counters to the SAME
  registry, so one scrape sees the whole tier.

Backpressure maps to status codes instead of buffering: the daemon's
:class:`~.scheduler.QueueFull` becomes **429** and
:class:`~.policies.SLOUnmeetable` (plus a draining/dead tier) becomes
**503**, each carrying ``Retry-After`` from the admission policy's wait
predictor when it has one (``exc.retry_after_s`` — ISSUE 17 satellite).
The accept side is bounded too (``max_connections``): past the bound a
connection gets an immediate 503, never an unbounded accept queue.

Client disconnect mid-stream CANCELS the underlying request: the handler
watches the socket for EOF while it streams, and a hangup calls
:meth:`~.daemon.ServingDaemon.cancel` — the slot frees, the KV pages
free, the tracer span closes, and conservation counts it ``cancelled``
(pinned in tests/test_frontend.py).  A disconnected client costs the
tier at most one pump sweep, not a slot leaked until deadline.

Thread model: the server runs on ONE asyncio event loop (optionally on
its own thread via :meth:`FrontDoor.start_in_thread` — the test/bench
harness path).  Handler coroutines touch the daemon only through its
thread-safe surface (``submit``/``cancel``/``conservation``); daemon
threads touch asyncio only through ``call_soon_threadsafe``.  The
frontend's own counters are loop-thread-only ints mirrored into the
registry.

:class:`FrontDoorClient` is the curl-equivalent blocking client
(stdlib ``http.client``) the example, tests, and bench drive the wire
with — including an SSE parser, so parity checks compare the actual
bytes on the wire against :meth:`ServingDaemon.stream`.
"""

from __future__ import annotations

import asyncio
import http.client
import json
import math
import threading
from typing import Callable, Iterator

from distributed_tensorflow_ibm_mnist_tpu.serving.policies import SLOUnmeetable
from distributed_tensorflow_ibm_mnist_tpu.serving.sampling import SamplingParams
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import QueueFull

_MAX_BODY = 1 << 20          # 1 MiB request-body bound (413 past it)
_MAX_HEAD = 32 << 10         # request line + headers bound
_SAMPLING_KEYS = ("temperature", "top_p", "top_k", "min_p", "seed")


class _BadRequest(ValueError):
    """Maps to a 400 with the message in the JSON error body."""


def _parse_generate(payload: dict) -> dict:
    """Validate the ``/v1/generate`` body into ``ServingDaemon.submit``
    kwargs.  Every verdict is a :class:`_BadRequest` naming the field —
    a malformed request costs the client a 400, never the tier a slot."""
    if not isinstance(payload, dict):
        raise _BadRequest("body must be a JSON object")
    prompt = payload.get("prompt")
    if (not isinstance(prompt, list) or not prompt
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in prompt)):
        raise _BadRequest("'prompt' must be a non-empty list of token ids")
    max_new = payload.get("max_new")
    if not isinstance(max_new, int) or isinstance(max_new, bool) or max_new < 1:
        raise _BadRequest("'max_new' must be an int >= 1")
    out = {"prompt": prompt, "max_new": max_new,
           "stream": bool(payload.get("stream", False))}
    priority = payload.get("priority", 0)
    if not isinstance(priority, int) or isinstance(priority, bool):
        raise _BadRequest("'priority' must be an int")
    out["priority"] = priority
    for key in ("deadline_s", "ttft_slo_s", "tpot_slo_s"):
        val = payload.get(key)
        if val is not None:
            if not isinstance(val, (int, float)) or isinstance(val, bool) \
                    or not val > 0:
                raise _BadRequest(f"'{key}' must be a number > 0")
            val = float(val)
        out[key] = val
    sampling = payload.get("sampling")
    if sampling is not None:
        if not isinstance(sampling, dict):
            raise _BadRequest("'sampling' must be an object")
        unknown = set(sampling) - set(_SAMPLING_KEYS)
        if unknown:
            raise _BadRequest(
                f"unknown sampling keys {sorted(unknown)} — "
                f"allowed: {list(_SAMPLING_KEYS)}")
        try:
            sampling = SamplingParams(**sampling)
        except (TypeError, ValueError) as e:
            raise _BadRequest(f"bad sampling params: {e}") from None
    out["sampling"] = sampling
    return out


class FrontDoor:
    """HTTP/SSE network edge over one :class:`~.daemon.ServingDaemon`.

    ``port=0`` binds an ephemeral port (read :attr:`port` after start —
    the test/bench pattern).  ``max_connections`` bounds concurrently
    served connections; past it a connection is answered 503 +
    ``Retry-After`` immediately.  ``registry`` is the MetricsRegistry
    ``/metrics`` exposes — default: the daemon's telemetry registry when
    one is wired, else a private one (the endpoint always works).
    """

    def __init__(self, daemon, host: str = "127.0.0.1", port: int = 0, *,
                 max_connections: int = 64, registry=None):
        if max_connections < 1:
            raise ValueError(
                f"max_connections must be >= 1, got {max_connections}")
        self.daemon = daemon
        self.host = host
        self.port = int(port)          # rebound to the real port at start
        self.max_connections = int(max_connections)
        if registry is None and daemon._telemetry is not None:
            registry = daemon._telemetry.registry
        if registry is None:
            from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import (
                MetricsRegistry,
            )
            registry = MetricsRegistry()
        self.registry = registry
        # loop-thread-only books (mirrored into the registry for scrapes)
        self.counters = {"connections": 0, "over_capacity": 0,
                         "requests": 0, "streams": 0, "bad_requests": 0,
                         "rejected_429": 0, "rejected_503": 0,
                         "disconnects": 0, "disconnect_cancels": 0}
        self._active = 0
        self._server: asyncio.AbstractServer | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._conn_tasks: set[asyncio.Task] = set()

    def _bump(self, name: str, n: int = 1) -> None:
        self.counters[name] += n
        self.registry.inc(f"frontdoor_{name}", n)

    # ------------------------------------------------------------------
    # lifecycle

    async def start(self) -> "FrontDoor":
        """Bind and start serving on the RUNNING event loop."""
        if self._server is not None:
            return self
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, limit=_MAX_HEAD)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def aclose(self) -> None:
        """Stop accepting, cancel open handlers, close the socket."""
        if self._server is None:
            return
        self._server.close()
        await self._server.wait_closed()
        for task in list(self._conn_tasks):
            task.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._server = None

    def start_in_thread(self) -> "FrontDoor":
        """Run the server on a dedicated event-loop thread; returns once
        the socket is bound (``self.port`` live).  Pair with
        :meth:`stop`; this is the harness path for tests/benches/examples
        whose main thread drives blocking clients."""
        if self._thread is not None:
            return self
        loop = asyncio.new_event_loop()
        ready = threading.Event()
        boot_exc: list[BaseException] = []

        def _run():
            asyncio.set_event_loop(loop)
            try:
                loop.run_until_complete(self.start())
            except BaseException as e:   # bind failure must reach caller
                boot_exc.append(e)
                ready.set()
                return
            ready.set()
            loop.run_forever()

        self._thread = threading.Thread(target=_run, name="dtm-frontdoor",
                                        daemon=True)
        self._thread.start()
        ready.wait()
        if boot_exc:
            self._thread.join(timeout=5.0)
            self._thread = None
            raise boot_exc[0]
        self._loop = loop
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Shut down a :meth:`start_in_thread` server (idempotent)."""
        if self._thread is None:
            return
        fut = asyncio.run_coroutine_threadsafe(self.aclose(), self._loop)
        try:
            fut.result(timeout=timeout)
        finally:
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=timeout)
            self._loop.close()
            self._thread = None

    def __enter__(self) -> "FrontDoor":
        return self.start_in_thread()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------
    # connection handling

    async def _on_connection(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._conn_tasks.add(task)
        task.add_done_callback(self._conn_tasks.discard)
        self._bump("connections")
        if self._active >= self.max_connections:
            # bounded accept backpressure: answer, never queue unboundedly
            self._bump("over_capacity")
            await self._respond_json(
                writer, 503,
                {"error": "server at connection capacity", "retry_after_s": 1.0},
                extra_headers={"Retry-After": "1"})
            await self._hangup(writer)
            return
        self._active += 1
        try:
            await self._serve_one(reader, writer)
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        except Exception:
            with _swallow():
                await self._respond_json(
                    writer, 500, {"error": "internal server error"})
        finally:
            self._active -= 1
            await self._hangup(writer)

    async def _serve_one(self, reader, writer) -> None:
        try:
            head = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"),
                                          timeout=30.0)
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
                asyncio.TimeoutError):
            return
        try:
            request_line, *header_lines = head.decode("latin-1").split("\r\n")
            method, target, _version = request_line.split(" ", 2)
            headers = {}
            for line in header_lines:
                if ":" in line:
                    k, v = line.split(":", 1)
                    headers[k.strip().lower()] = v.strip()
        except ValueError:
            await self._respond_json(writer, 400,
                                     {"error": "malformed request"})
            return
        target = target.split("?", 1)[0]
        if target == "/healthz":
            if method != "GET":
                await self._respond_json(writer, 405,
                                         {"error": "use GET /healthz"})
                return
            await self._healthz(writer)
        elif target == "/metrics":
            if method != "GET":
                await self._respond_json(writer, 405,
                                         {"error": "use GET /metrics"})
                return
            await self._metrics(writer)
        elif target == "/v1/generate":
            if method != "POST":
                await self._respond_json(writer, 405,
                                         {"error": "use POST /v1/generate"})
                return
            await self._generate(reader, writer, headers)
        else:
            await self._respond_json(writer, 404,
                                     {"error": f"no such endpoint {target}"})

    # ------------------------------------------------------------------
    # endpoints

    async def _healthz(self, writer) -> None:
        router = self.daemon.router
        conservation = self.daemon.conservation()
        healthy = len(router.healthy())
        body = {
            "status": ("ok" if healthy and conservation["conserved"]
                       else "degraded"),
            "healthy": healthy,
            "n_replicas": len(router.replicas),
            "retiring": len(router._retiring),
            "replicas": {str(r.index): r.vitals() for r in router.replicas},
            "conservation": conservation,
        }
        await self._respond_json(writer, 200 if healthy else 503, body)

    async def _metrics(self, writer) -> None:
        # to_prometheus() serializes under the registry lock — the scrape
        # is one atomic snapshot even while pumps are counting
        text = self.registry.to_prometheus().encode("utf-8")
        await self._respond_raw(writer, 200, text,
                                content_type="text/plain; version=0.0.4")

    async def _generate(self, reader, writer, headers: dict) -> None:
        self._bump("requests")
        try:
            length = int(headers.get("content-length", "0"))
        except ValueError:
            length = -1
        if length <= 0:
            self._bump("bad_requests")
            await self._respond_json(
                writer, 400, {"error": "Content-Length body required"})
            return
        if length > _MAX_BODY:
            self._bump("bad_requests")
            await self._respond_json(
                writer, 413, {"error": f"body exceeds {_MAX_BODY} bytes"})
            return
        try:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          timeout=30.0)
            spec = _parse_generate(json.loads(body))
        except _BadRequest as e:
            self._bump("bad_requests")
            await self._respond_json(writer, 400, {"error": str(e)})
            return
        except (json.JSONDecodeError, UnicodeDecodeError):
            self._bump("bad_requests")
            await self._respond_json(writer, 400, {"error": "invalid JSON"})
            return
        except (asyncio.IncompleteReadError, asyncio.TimeoutError):
            return

        loop = asyncio.get_running_loop()
        events: asyncio.Queue = asyncio.Queue()

        def on_token(_dr, tok):
            # delivery thread → event loop: the ONE legal crossing
            loop.call_soon_threadsafe(events.put_nowait, ("tok", int(tok)))

        try:
            dr = self.daemon.submit(
                spec["prompt"], spec["max_new"], callback=on_token,
                deadline_s=spec["deadline_s"], priority=spec["priority"],
                ttft_slo_s=spec["ttft_slo_s"], tpot_slo_s=spec["tpot_slo_s"],
                sampling=spec["sampling"])
        except SLOUnmeetable as e:
            self._bump("rejected_503")
            await self._respond_reject(writer, 503, e)
            return
        except QueueFull as e:
            self._bump("rejected_429")
            await self._respond_reject(writer, 429, e)
            return
        except RuntimeError as e:       # daemon draining/closed
            self._bump("rejected_503")
            await self._respond_json(writer, 503, {"error": str(e)})
            return
        except ValueError as e:         # engine-level validation
            self._bump("bad_requests")
            await self._respond_json(writer, 400, {"error": str(e)})
            return

        # end-of-request watcher: a worker thread parks on the request's
        # terminal event and posts the sentinel AFTER every token callback
        # already crossed (the delivery thread runs callbacks before it
        # sets _done, and call_soon_threadsafe preserves order)
        async def _await_end():
            await loop.run_in_executor(None, dr._done.wait)
            events.put_nowait(("end", None))

        end_task = asyncio.ensure_future(_await_end())
        # disconnect watcher: the client sends nothing after the request,
        # so a read completing means EOF/reset — the socket is gone
        disconnect = asyncio.ensure_future(reader.read(1))
        try:
            if spec["stream"]:
                self._bump("streams")
                await self._stream_sse(writer, dr, events, disconnect)
            else:
                await self._collect_json(writer, dr, events, disconnect)
        finally:
            disconnect.cancel()
            end_task.cancel()
            with _swallow():
                await asyncio.gather(end_task, disconnect,
                                     return_exceptions=True)

    async def _next_event(self, events: asyncio.Queue,
                          disconnect: asyncio.Task):
        """One delivery event, or ``("disconnect", None)`` the moment the
        client hangs up with nothing pending — pending tokens drain first
        (they are already paid for; the disconnect verdict can wait one
        queue pop)."""
        if not events.empty():
            return events.get_nowait()
        getter = asyncio.ensure_future(events.get())
        done, _pending = await asyncio.wait(
            {getter, disconnect}, return_when=asyncio.FIRST_COMPLETED)
        if getter in done:
            return getter.result()
        getter.cancel()
        with _swallow():
            await getter
        return ("disconnect", None)

    def _cancel_on_disconnect(self, dr) -> None:
        self._bump("disconnects")
        if not dr.done:
            self.daemon.cancel(dr, reason="client disconnected")
            self._bump("disconnect_cancels")

    async def _stream_sse(self, writer, dr, events, disconnect) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n"
            + f"X-Request-Id: {dr.id}\r\n\r\n".encode())
        try:
            await writer.drain()
            while True:
                kind, payload = await self._next_event(events, disconnect)
                if kind == "tok":
                    writer.write(b"data: "
                                 + json.dumps({"token": payload}).encode()
                                 + b"\n\n")
                    await writer.drain()
                elif kind == "end":
                    terminal = {"id": dr.id, "status": dr.status,
                                "error": dr.error,
                                "n_tokens": len(dr.tokens)}
                    writer.write(b"event: end\ndata: "
                                 + json.dumps(terminal).encode() + b"\n\n")
                    await writer.drain()
                    return
                else:
                    self._cancel_on_disconnect(dr)
                    return
        except (ConnectionResetError, BrokenPipeError):
            self._cancel_on_disconnect(dr)

    async def _collect_json(self, writer, dr, events, disconnect) -> None:
        while True:
            kind, _payload = await self._next_event(events, disconnect)
            if kind == "end":
                break
            if kind == "disconnect":
                self._cancel_on_disconnect(dr)
                return
        body = {"id": dr.id, "status": dr.status, "error": dr.error,
                "tokens": list(dr.tokens)}
        try:
            await self._respond_json(writer, 200, body)
        except (ConnectionResetError, BrokenPipeError):
            self._bump("disconnects")

    # ------------------------------------------------------------------
    # response plumbing

    async def _respond_reject(self, writer, code: int, exc: QueueFull) -> None:
        """429/503 with the policy's backoff hint as a real Retry-After
        header (integer seconds, ceil — never rounded to an instant
        retry) AND machine-readable in the body."""
        hint = getattr(exc, "retry_after_s", None)
        extra = None
        if hint is not None:
            extra = {"Retry-After": str(max(1, math.ceil(hint)))}
        await self._respond_json(
            writer, code,
            {"error": str(exc),
             "retry_after_s": None if hint is None else round(float(hint), 6)},
            extra_headers=extra)

    async def _respond_json(self, writer, code: int, body: dict,
                            extra_headers: dict | None = None) -> None:
        await self._respond_raw(
            writer, code, json.dumps(body).encode("utf-8"),
            content_type="application/json", extra_headers=extra_headers)

    async def _respond_raw(self, writer, code: int, body: bytes, *,
                           content_type: str,
                           extra_headers: dict | None = None) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed", 413: "Payload Too Large",
                  429: "Too Many Requests", 500: "Internal Server Error",
                  503: "Service Unavailable"}.get(code, "Unknown")
        head = [f"HTTP/1.1 {code} {reason}",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(body)}",
                "Connection: close"]
        for k, v in (extra_headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body)
        await writer.drain()

    async def _hangup(self, writer) -> None:
        with _swallow():
            writer.close()
            await writer.wait_closed()


class _swallow:
    """``with _swallow():`` — an async-teardown guard: nothing raised
    while closing an already-dead socket should replace the real story."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return True


# ----------------------------------------------------------------------
# the curl-equivalent client (stdlib http.client) — example/tests/bench


class FrontDoorClient:
    """Blocking wire client for one :class:`FrontDoor`.

    Every call opens a fresh connection (the server is
    ``Connection: close``).  :meth:`generate` returns the parsed JSON
    verdict; :meth:`stream` yields tokens off the SSE wire as they
    arrive and stores the terminal event on :attr:`last_terminal` —
    byte-level parity with :meth:`ServingDaemon.stream` is exactly what
    the bench gates.
    """

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host = host
        self.port = int(port)
        self.timeout = float(timeout)
        self.last_terminal: dict | None = None
        self.last_status: int | None = None
        self.last_headers: dict | None = None

    def _request(self, method: str, path: str, payload: dict | None = None):
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)
        body = None if payload is None else json.dumps(payload)
        conn.request(method, path, body=body,
                     headers={"Content-Type": "application/json"}
                     if body is not None else {})
        resp = conn.getresponse()
        self.last_status = resp.status
        self.last_headers = {k.lower(): v for k, v in resp.getheaders()}
        return conn, resp

    def _json_call(self, method: str, path: str,
                   payload: dict | None = None) -> dict:
        conn, resp = self._request(method, path, payload)
        try:
            raw = resp.read()
        finally:
            conn.close()
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return {"raw": raw.decode("utf-8", "replace")}

    def generate(self, prompt, max_new: int, **kw) -> dict:
        """POST /v1/generate, non-streaming; returns the JSON body (the
        ``tokens`` list on 200, the error + ``retry_after_s`` on 4xx/5xx;
        check :attr:`last_status`)."""
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new": int(max_new), **kw}
        return self._json_call("POST", "/v1/generate", payload)

    def stream(self, prompt, max_new: int, **kw) -> Iterator[int]:
        """POST /v1/generate with ``stream: true``; yields each token as
        its SSE event arrives.  On a non-200 the rejection body lands in
        :attr:`last_terminal` and nothing is yielded."""
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new": int(max_new), "stream": True, **kw}
        self.last_terminal = None
        conn, resp = self._request("POST", "/v1/generate", payload)
        try:
            if resp.status != 200:
                raw = resp.read()
                try:
                    self.last_terminal = json.loads(raw)
                except json.JSONDecodeError:
                    self.last_terminal = {"raw": raw.decode("utf-8", "replace")}
                return
            for event, data in _iter_sse(resp):
                if event == "end":
                    self.last_terminal = data
                    return
                yield int(data["token"])
        finally:
            conn.close()

    def healthz(self) -> dict:
        return self._json_call("GET", "/healthz")

    def metrics(self) -> str:
        conn, resp = self._request("GET", "/metrics")
        try:
            return resp.read().decode("utf-8")
        finally:
            conn.close()


def _iter_sse(resp) -> Iterator[tuple[str, dict]]:
    """Parse an SSE byte stream into ``(event, json_data)`` pairs.
    ``event`` is ``"message"`` for bare ``data:`` lines (tokens) and the
    explicit event name otherwise (the terminal ``end``)."""
    event = "message"
    data_lines: list[str] = []
    for raw in resp:
        line = raw.decode("utf-8").rstrip("\n").rstrip("\r")
        if line.startswith("event:"):
            event = line[len("event:"):].strip()
        elif line.startswith("data:"):
            data_lines.append(line[len("data:"):].strip())
        elif line == "" and data_lines:
            yield event, json.loads("\n".join(data_lines))
            event = "message"
            data_lines = []

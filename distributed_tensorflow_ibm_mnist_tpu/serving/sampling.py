"""Per-request sampling for the serving tier (ISSUE 13).

A request carries a :class:`SamplingParams` — ``(temperature, top_p,
top_k, min_p, seed)`` — validated at submit time, and the engine turns
the per-slot values into device-side DATA planes: (slots,) float32
temperature, top-p, and min-p vectors, a (slots,) int32 top-k vector
(ISSUE 14), plus a (slots, 2) uint32 base-key plane, all fed to the
SAME compiled decode/verify programs regardless of the mix (the
one-program-many-behaviors discipline the census gates pin; see
core/generate.py ``_pick_rows`` / ``_sample_window_core`` /
``_verify_sample_core``).

PRNG contract — a request's token stream is a pure function of its seed:

* the base key is the host-side Threefry derivation
  ``[seed >> 32, seed & 0xffffffff]`` (:func:`base_key`), numerically
  identical to ``jax.random.PRNGKey(seed)`` but computed with numpy so
  submit never dispatches a device program;
* the token at generated index ``n`` is picked with
  ``fold_in(base_key, n)`` — the index, not the window phase, owns the
  key, so decode-ahead width, dense/paged layout, engine restarts, and
  router failover replays all consume the identical key schedule (the
  speculative path derives its accept/residual draws from the same
  ``fold_in`` family; see ``_verify_sample_core``).

:func:`first_pick` is the ONE module-level jitted first-token pick every
engine shares for prefill-miss, prefix-cache-hit, and paged-extend
landings: hit and miss run the same program over the same stored logits,
so a sampled request's first token is bit-identical either way — which
is what makes the prefix cache sampling-safe (it stores the
deterministic prefill logits, never a sampled token).
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.core.generate import _pick_rows


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Validated per-request sampling config.

    ``temperature == 0`` is greedy (argmax; ``top_p``/``top_k``/``min_p``
    must be 0 and the seed is inert), ``temperature > 0`` samples the
    tempered distribution, optionally truncated to the ``top_k``
    highest-logit tokens, nucleus-filtered by ``0 < top_p < 1`` (top-k
    applies first, like the offline generator), and/or min-p-filtered by
    ``0 < min_p <= 1`` (tokens below ``min_p * max_prob`` cut, applied
    last; ``min_p = 1`` keeps only the argmax).  ``seed`` fully
    determines the request's token stream at fixed params/prompt —
    submit the same seed twice and the streams are token-identical;
    best-of-n is "same prompt, n seeds" (examples/11_sampling.py).
    """

    temperature: float = 0.0
    top_p: float = 0.0
    top_k: int = 0
    min_p: float = 0.0
    seed: int = 0

    def __post_init__(self):
        t, p, k, s = self.temperature, self.top_p, self.top_k, self.seed
        mp = self.min_p
        if not (isinstance(t, (int, float)) and np.isfinite(t) and t >= 0):
            raise ValueError(
                f"temperature must be a finite float >= 0, got {t!r}")
        if not (isinstance(p, (int, float)) and 0.0 <= float(p) <= 1.0):
            raise ValueError(f"top_p must be in [0, 1], got {p!r}")
        if p and t == 0:
            raise ValueError(
                "top_p filters a SAMPLING distribution; set temperature > 0")
        if (not isinstance(k, (int, np.integer)) or isinstance(k, bool)
                or int(k) < 0):
            raise ValueError(f"top_k must be an int >= 0, got {k!r}")
        if k and t == 0:
            raise ValueError(
                "top_k filters a SAMPLING distribution; set temperature > 0")
        if not (isinstance(mp, (int, float)) and 0.0 <= float(mp) <= 1.0):
            raise ValueError(f"min_p must be in [0, 1], got {mp!r}")
        if mp and t == 0:
            raise ValueError(
                "min_p filters a SAMPLING distribution; set temperature > 0")
        if not isinstance(s, (int, np.integer)) or isinstance(s, bool):
            raise ValueError(f"seed must be an int, got {s!r}")
        if not 0 <= int(s) < (1 << 64):
            raise ValueError(f"seed must fit in uint64, got {s}")

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0

    def key(self) -> np.ndarray:
        """The request's (2,) uint32 Threefry base key."""
        return base_key(self.seed)

    def to_dict(self) -> dict:
        """Strict-JSON form (plain floats/ints) — what the request
        journal persists (serving/journal.py).  Round-trips exactly
        through :meth:`from_dict`: the stream is a pure function of
        these five numbers, which is what makes crash replay
        token-identical."""
        return {"temperature": float(self.temperature),
                "top_p": float(self.top_p), "top_k": int(self.top_k),
                "min_p": float(self.min_p), "seed": int(self.seed)}

    @classmethod
    def from_dict(cls, d: dict) -> "SamplingParams":
        """Rebuild from :meth:`to_dict` output (re-validated)."""
        return cls(**d)


#: The default: greedy decode, seed inert.
GREEDY = SamplingParams()


def base_key(seed: int) -> np.ndarray:
    """``jax.random.PRNGKey(seed)`` computed on the HOST with numpy —
    the same ``[hi32, lo32]`` uint32 pair, derived without dispatching
    (submit-path code must never pay a device program)."""
    seed = int(seed)
    return np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)


@jax.jit
def first_pick(logits, temps, topps, topks, minps, keys, pos):
    """The shared first-token pick program: fold each row's base key at
    its generated index (0 for a fresh request) and pick with the same
    data-driven math the decode window uses.  Module-level jit: every
    engine in the process shares one compilation per shape (top-k and
    min-p ride the ``topks``/``minps`` DATA planes), and prefix-cache
    hit/miss paths are bit-identical by construction.
    Returns ``((B,) int32 token, (B,) float32 logprob)``."""
    step_keys = jax.vmap(jax.random.fold_in)(keys, pos)
    return _pick_rows(logits, temps, topps, topks, minps, step_keys)

"""Radix trie over token blocks: prefix sharing for the paged KV cache.

The blake2b prefix cache (serving/prefix_cache.py) only hits on EXACT
(bucket, prompt) matches and stores a full dense cache row per entry.  With
the cache paged (serving/kv_pool.py), a prefix is just a list of page ids —
so sharing generalizes to a radix trie keyed by ``page_size``-token blocks:
each node owns ONE page (the same id in every layer's pool — kv_pool's
cross-layer page contract) holding the K/V of its block, refcounted by the
live requests whose block tables reference it.

* ``match(tokens)`` walks the deepest path of whole blocks equal to the
  prompt's prefix — a partial hit skips ``matched_tokens`` of prefill work
  (the engine computes only the suffix, via kv_pool's extend program).
* Matched pages are READ-ONLY to the matching request: its block table
  maps the shared blocks to the trie's pages and every later block to
  private pages, so divergence is copy-on-write by remapping — the shared
  page is never written (the paged attention only writes the current
  chunk's positions, all ≥ the match boundary).
* ``insert`` donates a request's freshly computed full blocks: the pages
  move from the request's private allocation into the trie (ref=1, held by
  the donor until retirement).  A concurrent identical insert keeps the
  existing node — the loser's duplicate page stays private and is freed
  normally (content-identical, so either page serves future matches).
* ``evict`` frees LRU unreferenced LEAF nodes when the pool runs dry —
  interior nodes are pinned by their children, so the trie always stays
  prefix-closed.

The exact-match cache is this trie's degenerate single-path case (every
prompt a chain of blocks, hit = full-path match); the dense engine keeps
the blake2b cache, the paged engine uses this.

Determinism: LRU ordering uses a monotonic touch counter, not wall-clock,
so the fault-injection harness (utils/chaos.py) replays identically.

Sampling-safe by construction (ISSUE 13): the trie stores PROMPT blocks
only — whole ``page_size``-token blocks of the request's prompt, a
deterministic prefill product.  No sampled (generated) token ever enters
a shared page, so a sampled request matching a prefix reuses exactly the
K/V a greedy request would have computed, and picks its own tokens from
its own seed downstream.
"""

from __future__ import annotations

import numpy as np


class RadixNode:
    """One ``page_size``-token block of some cached prefix.  ``ref`` counts
    live holders (matching or donating requests); ``page`` is the pool page
    id holding this block's K/V in every layer."""

    __slots__ = ("key", "page", "parent", "children", "ref", "last_use")

    def __init__(self, key: bytes | None, page: int, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: dict[bytes, RadixNode] = {}
        self.ref = 0
        self.last_use = 0


class RadixCache:
    """Host-side radix trie over token blocks; see the module docstring."""

    def __init__(self, page_size: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.page_size = int(page_size)
        self.root = RadixNode(None, -1, None)  # sentinel, owns no page
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0

    def _touch(self, node: RadixNode) -> None:
        self._tick += 1
        node.last_use = self._tick

    def _block_key(self, tokens: np.ndarray, j: int) -> bytes:
        ps = self.page_size
        return np.ascontiguousarray(
            tokens[j * ps:(j + 1) * ps], dtype=np.int32).tobytes()

    @property
    def n_blocks(self) -> int:
        """Resident nodes (= trie-owned pages)."""
        count = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            count += len(node.children)
            stack.extend(node.children.values())
        return count

    def match(self, tokens) -> tuple[list[RadixNode], int]:
        """Deepest whole-block path equal to the prompt's prefix.  Returns
        (path nodes root-first, matched token count).  Touches the path
        (LRU) but does NOT acquire — callers that will reference the pages
        must ``acquire`` the path before any allocation can evict it."""
        tokens = np.asarray(tokens).reshape(-1)
        path: list[RadixNode] = []
        cur = self.root
        for j in range(len(tokens) // self.page_size):
            child = cur.children.get(self._block_key(tokens, j))
            if child is None:
                break
            self._touch(child)
            path.append(child)
            cur = child
        return path, len(path) * self.page_size

    def acquire(self, nodes) -> None:
        for node in nodes:
            node.ref += 1

    def release(self, nodes) -> None:
        for node in nodes:
            if node.ref <= 0:
                raise ValueError("release of an unheld radix node")
            node.ref -= 1

    def insert(self, tokens, have: int, pages_by_block: dict[int, int],
               path: list[RadixNode]) -> tuple[list[RadixNode], list[int]]:
        """Donate blocks ``have .. have+len(pages_by_block)`` of ``tokens``
        (page ids in ``pages_by_block``, keyed by block index) into the
        trie below ``path`` (the acquired match, ``len(path) == have``).

        Returns ``(held, kept)``: ``held`` are the new nodes (each created
        with ref=1 — the donor holds them until retirement, alongside the
        matched path), ``kept`` the page ids NOT donated because an
        identical node already existed — those stay the donor's private
        pages (its block table already points at them; content-identical
        to the winner's, freed at retirement like any private page)."""
        tokens = np.asarray(tokens).reshape(-1)
        cur = path[-1] if path else self.root
        held: list[RadixNode] = []
        kept: list[int] = []
        for j in sorted(pages_by_block):
            key = self._block_key(tokens, j)
            child = cur.children.get(key)
            if child is not None:
                # same-prefix race: existing node wins, donor keeps its page
                self._touch(child)
                kept.append(pages_by_block[j])
            else:
                child = RadixNode(key, int(pages_by_block[j]), cur)
                child.ref = 1
                self._touch(child)
                cur.children[key] = child
                held.append(child)
            cur = child
        return held, kept

    def evict(self, need: int, free_fn) -> int:
        """Free up to ``need`` pages from unreferenced LEAF nodes, LRU
        first (a parent becomes evictable once its last child goes), calling
        ``free_fn(page_id)`` per page.  Returns pages actually freed."""
        freed = 0
        while freed < need:
            victim = None
            stack = [self.root]
            while stack:
                node = stack.pop()
                for child in node.children.values():
                    if not child.children and child.ref == 0:
                        if victim is None or child.last_use < victim.last_use:
                            victim = child
                    else:
                        stack.append(child)
            if victim is None:
                break
            del victim.parent.children[victim.key]
            free_fn(victim.page)
            freed += 1
        return freed

    def record(self, hit: bool, tokens: int = 0) -> None:
        """Stat accounting: one admission's match outcome."""
        if hit:
            self.hits += 1
            self.hit_tokens += int(tokens)
        else:
            self.misses += 1

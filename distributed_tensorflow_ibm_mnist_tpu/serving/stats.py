"""Serving metrics: per-request TTFT/latency, engine tokens/sec, occupancy.

The serving analog of the trainer's metrics-of-record discipline
(utils/metrics.py): every number a capacity plan needs, as one JSON record.

* **TTFT** (time-to-first-token) — submit to the first token being ON THE
  HOST (the prefill's pick), the user-visible responsiveness figure.  Queue
  wait is inside it by construction: a request that sat behind a full
  batch shows it here, which is exactly what head-of-line blocking looks
  like in data.
* **latency** — submit to retirement (EOS / budget / deadline-cancel).
* **tokens/sec** — real generated tokens over the engine's busy window
  (first admission to last retirement): the SUSTAINED figure continuous
  batching improves, not a per-step peak.
* **occupancy** — time-weighted mean fraction of slots holding a live
  request.  Static batching's head-of-line blocking shows up directly as
  occupancy lost to retired-but-still-decoding rows; the refill loop keeps
  it near 1 under load.
* **windows** (ISSUE 5) — per decode-ahead window: dispatch time (jit call
  until control returns, async under the hood) vs readback time (the ONE
  blocking host sync per window), total occupied-slot steps vs waste steps
  (post-EOS/post-budget tokens decoded inside a window and discarded on
  the host — the bounded ≤k−1 overrun decode-ahead trades for k× fewer
  syncs).  ``waste_frac`` is the fraction of occupied-slot decode work
  thrown away; it rises with ``decode_ahead`` and is the number to weigh
  against the sync savings.
* **prefix cache** — hits/misses of the prompt prefix cache
  (serving/prefix_cache.py); a hit skips one whole prefill dispatch.
* **speculative acceptance** (ISSUE 9) — per verify window and slot:
  ``drafted`` tokens proposed by the n-gram drafter, ``accepted`` drafts
  the target model's argmax reproduced, ``corrected`` free
  correction/continuation tokens (one per verified slot).
  ``accept_rate = accepted / drafted`` is the drafter's quality;
  ``useful_tokens_per_window = (window_steps − waste) / n_windows`` is the
  figure speculation actually improves (plain decode-ahead pins it at ≤ k
  sequential steps per dispatch; speculation emits ``accepted + 1`` tokens
  for ONE k-position forward).  Both are None — never NaN — when their
  denominators are zero, so dense/plain records keep a stable schema.

* **sampling** (ISSUE 13) — ``n_sampled_requests`` (requests whose own
  :class:`~..serving.sampling.SamplingParams` decoded with temperature
  > 0), ``mean_temperature`` over those (None when none — never a
  fictitious zero-mean), and a streaming per-token NLL histogram
  (``-logprob`` under the raw-logits convention, every generated token,
  greedy rows included) whose p50/p95/p99 come from a
  utils/telemetry.HistogramSketch — fixed memory at any token count, and
  the sketches merge bucket-wise in the router rollup.
* **SLO / goodput** (ISSUE 11) — a request may declare latency targets
  ``(ttft_slo_s, tpot_slo_s)`` (serving/scheduler.Request); the engine
  judges TTFT at first token and TPOT at retirement.  A *tracked* request
  (≥1 SLO declared) is **met** iff it retired ``done`` with no judged
  constraint failed; failed/cancelled tracked requests are misses (the
  user did not get their tokens in time).  ``goodput_rps`` = SLO-met
  requests per busy-window second — the overload metric ROADMAP item 3
  gates on: throughput counts tokens, goodput counts tokens *somebody
  got in time*.
* **bounded samples** (ISSUE 11) — counters are exact and O(1), but the
  percentile SAMPLE lists (``self.requests``) are a seeded reservoir
  (Algorithm R, ``sample_cap`` records): below the cap every request is
  kept and percentiles are exact; past it each subsequent request
  replaces a uniformly random kept one, so a week-long soak holds a
  uniform sample at fixed memory instead of growing without bound.  For
  streaming (no-stored-samples) percentiles, see
  utils/telemetry.HistogramSketch — tier-1 cross-checks the two agree
  within bucket resolution.

Percentiles are p50/p95/p99 over completed requests (cancelled requests
count in TTFT if they got a first token, and in the cancel counter, not in
latency — a deadline kill is not a service time).
"""

from __future__ import annotations

import contextlib
import hashlib
import random
import threading

import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import Request
from distributed_tensorflow_ibm_mnist_tpu.utils.metrics import MetricWriter
from distributed_tensorflow_ibm_mnist_tpu.utils.telemetry import HistogramSketch


def slo_verdict(req: "Request") -> str | None:
    """None = untracked (no SLO declared); else ``"met"`` / ``"miss"``.

    Met requires terminal status ``done`` AND no judged constraint
    failed.  A tracked request that failed or was cancelled is a miss
    even when no constraint was ever judged — an answer that never
    arrived did not meet its latency target.
    """
    if req.ttft_slo_s is None and req.tpot_slo_s is None:
        return None
    if req.status != "done":
        return "miss"
    if req.slo_ttft_ok is False or req.slo_tpot_ok is False:
        return "miss"
    return "met"


def percentiles(xs, qs=(50, 95, 99)) -> dict[str, float]:
    """{"p50": ..., "p95": ..., "p99": ...} over xs (empty -> None values)."""
    if not len(xs):
        return {f"p{q}": None for q in qs}
    arr = np.asarray(xs, np.float64)
    return {f"p{q}": round(float(np.percentile(arr, q)), 6) for q in qs}


def transcript_digest(tokens) -> str:
    """Content address of one token transcript: blake2b over the int32
    stream.  The token-parity primitive of the crash bench and the
    recovery tests (serving/journal.py): a client transcript stitched
    across a SIGKILL — pre-crash SSE prefix + post-recovery resume —
    must digest identically to the uncrashed reference's, which is a
    stronger statement than equal lengths and cheaper to ship in a
    one-line bench record than the streams themselves."""
    return hashlib.blake2b(np.asarray(tokens, np.int32).tobytes(),
                           digest_size=16).hexdigest()


class ServingStats:
    """Accumulates request records and engine-loop samples.

    The engine calls :meth:`tick` once per host-loop iteration (occupancy
    integration, weighted by the iteration's wall time) and :meth:`add`
    once per retired request; :meth:`summary` folds everything into one
    flat dict and :meth:`emit` writes it through a :class:`MetricWriter`
    (non-finite values are sanitized to null by the writer itself).

    Thread model (the daemonized tier — serving/daemon.py): each stats
    object has ONE writer — the engine that owns it, driven by exactly one
    pump thread — but is READ from other threads (``merge``/``summary``/
    ``vitals`` on the daemon's control and telemetry paths).  Every
    mutator and every snapshot therefore holds ``self._lock`` (an RLock,
    uncontended in the single-threaded case), so a reader can never see a
    half-applied :meth:`add` (request counted, reservoir/SLO counters not
    yet) and :meth:`merge` folds N live records without torn counters.
    """

    def __init__(self, slots: int, decode_ahead: int = 1,
                 sample_cap: int = 2048, role: str = "both"):
        if sample_cap < 1:
            raise ValueError(f"sample_cap must be >= 1, got {sample_cap}")
        self.slots = slots
        self.decode_ahead = decode_ahead
        # which serving role produced this record ("both" = monolithic;
        # "prefill"/"decode" = a disaggregated tier — ISSUE 16).  The
        # router rollup groups per-role so prefill-side figures (chunk
        # stalls, radix skips) never blend into decode-side TPOT.
        self.role = str(role)
        self._lock = threading.RLock()
        # bounded percentile-sample reservoir (Algorithm R; see module
        # docstring).  Counters below are EXACT regardless of the cap;
        # only the percentile samples are subject to reservoir sampling.
        # Seeded so soak reruns keep identical sample populations.
        self.sample_cap = int(sample_cap)
        self.requests: list[Request] = []
        self._rng = random.Random(0)
        self._n_requests = 0
        self._n_done = 0
        self._n_cancelled = 0
        self._n_failed = 0
        self._n_engine_fault = 0
        self._tokens = 0
        # --- SLO / goodput accounting (ISSUE 11) --- all zero when no
        # request declares an SLO, so the schema stays stable
        self._slo_tracked = 0
        self._slo_met = 0
        self._slo_miss = 0
        self._slo_ttft_miss = 0
        self._slo_tpot_miss = 0
        self._occ_time = 0.0   # integral of occupied_slots * dt
        self._busy_time = 0.0  # integral of dt while the engine had work
        self._decode_steps = 0
        self._start_t: float | None = None
        self._end_t: float | None = None
        # --- decode-ahead window accounting (ISSUE 5) ---
        self._windows = 0
        self._dispatch_time = 0.0  # window jit-call time (async dispatch)
        self._readback_time = 0.0  # the blocking (slots, k) host sync
        self._window_steps = 0     # occupied-slot decode steps dispatched
        self._waste_steps = 0      # of those, discarded post-retirement
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_oversized = 0
        # --- speculative acceptance accounting (ISSUE 9) --- all zero on
        # non-speculative engines, so the schema stays stable across modes
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_corrected = 0
        # --- per-request sampling accounting (ISSUE 13) --- all zero on
        # greedy-only traffic, so the schema stays stable.  The NLL sketch
        # holds -logprob per generated token (every request — greedy rows
        # included, their logprobs are the same raw-logits convention), a
        # streaming model-confidence figure; [1e-4, 1e2] nats spans
        # near-certain (1e-4) to vocab-uniform-at-any-real-vocab (1e2)
        self._n_sampled = 0          # requests that decoded with temp > 0
        self._temp_sum = 0.0         # over sampled requests only
        self._n_logprob_tokens = 0
        self._nll = HistogramSketch(lo=1e-4, hi=1e2)
        # --- paged KV pool + radix prefix accounting (ISSUE 7) --- the
        # engine samples pool occupancy each step (pool_sample) and records
        # each admission's radix-match outcome (radix); all zero/None for
        # dense engines, so the schema stays stable across layouts
        self._kv_page_size = 0
        self._kv_pages_total = 0
        self._kv_pages_live = 0
        self._kv_pages_peak = 0
        self._kv_page_bytes = 0
        self._radix_hits = 0
        self._radix_misses = 0
        self._radix_hit_tokens = 0
        # --- chunked-prefill accounting (ISSUE 14) --- all zero/None on
        # whole-prompt engines, so the schema stays stable across regimes
        self._prefill_chunks = 0     # extend[b{C}] dispatches
        self._chunk_stall_s = 0.0    # total wall seconds inside chunk
        #   dispatches (the decode-latency budget chunking bounds)
        self._longest_prompt = 0     # max admitted prompt tokens; 0 = no
        #   admission recorded (summary reports None)
        # --- compile accounting (ISSUE 6) --- the engine's own XLA
        # program family: a CompileTracker snapshot DELTA from engine
        # construction to stats emission (utils/tracing.py)
        self._compile: dict | None = None
        # --- tensor-parallel per-chip footprint (ISSUE 10) --- stamped by
        # the engine (memory()); tp=1 with whole-tree bytes on single-chip
        # engines, so the schema never branches on the mesh
        self._tp = 1
        self._cp = 1  # context-parallel degree (ISSUE 20); 1 off-mesh
        self._kv_bytes_per_chip: int | None = None
        self._weight_bytes_per_chip: int | None = None
        self._quant = "none"  # weight storage scheme ("int8" when the
        #   engine quantizes at upload — ISSUE 12); stamped with memory()

    def tick(self, occupied: int, dt: float, decoded: bool = False) -> None:
        with self._lock:
            self._occ_time += occupied * dt
            self._busy_time += dt
            if decoded:
                self._decode_steps += 1

    def window(self, dispatch_s: float, readback_s: float, steps: int,
               waste: int) -> None:
        """One decode-ahead window: ``steps`` = occupied slots × window
        length dispatched, ``waste`` = the subset discarded on the host
        (tokens decoded past a row's EOS/budget inside the window)."""
        with self._lock:
            self._windows += 1
            self._dispatch_time += dispatch_s
            self._readback_time += readback_s
            self._window_steps += steps
            self._waste_steps += waste

    def prefix(self, hit: bool) -> None:
        """One prefix-cache lookup (hit = prefill skipped entirely)."""
        with self._lock:
            if hit:
                self._prefix_hits += 1
            else:
                self._prefix_misses += 1

    def spec(self, drafted: int, accepted: int, corrected: int = 1) -> None:
        """One slot's outcome in one speculative verify window: ``drafted``
        tokens proposed, ``accepted`` of them reproduced by the target
        model's argmax, plus ``corrected`` free correction/continuation
        tokens (1 per verified slot — the model's own next token after the
        accepted prefix, emitted whether or not anything was accepted)."""
        with self._lock:
            self._spec_drafted += int(drafted)
            self._spec_accepted += int(accepted)
            self._spec_corrected += int(corrected)

    def prefix_oversized(self, count: int) -> None:
        """Absolute count of PrefixCache.put refusals (entry > max_bytes);
        the engine copies the cache's own counter at emission time."""
        self._prefix_oversized = int(count)

    def pool_sample(self, pages_live: int, pages_total: int,
                    page_size: int, page_bytes: int) -> None:
        """One page-pool occupancy sample (the paged engine calls this per
        step): live/total allocatable pages, the page size in tokens, and
        the cross-layer bytes one page occupies (kv_pool.pool_page_bytes)."""
        with self._lock:
            self._kv_pages_live = int(pages_live)
            self._kv_pages_peak = max(self._kv_pages_peak, int(pages_live))
            self._kv_pages_total = int(pages_total)
            self._kv_page_size = int(page_size)
            self._kv_page_bytes = int(page_bytes)

    def radix(self, hit: bool, tokens: int = 0) -> None:
        """One admission's radix-trie match outcome: ``tokens`` = shared
        prefix length whose prefill was skipped (whole pages only)."""
        with self._lock:
            if hit:
                self._radix_hits += 1
                self._radix_hit_tokens += int(tokens)
            else:
                self._radix_misses += 1

    def chunk(self, stall_s: float) -> None:
        """One chunked-prefill dispatch (ISSUE 14): ``stall_s`` = wall
        seconds the dispatch occupied the host loop — the bounded
        per-iteration decode-latency cost the chunked_prefill bench leg
        gates on."""
        with self._lock:
            self._prefill_chunks += 1
            self._chunk_stall_s += float(stall_s)

    def prompt_admitted(self, n_tokens: int) -> None:
        """One admission's prompt length (chunked engines call this at
        allocation) — ``longest_prompt_admitted`` documents the regime's
        headline capability: prompts past every bucket."""
        self._longest_prompt = max(self._longest_prompt, int(n_tokens))

    def memory(self, tp: int, kv_bytes_per_chip: int,
               weight_bytes_per_chip: int, quant: str = "none",
               cp: int = 1) -> None:
        """Stamp the engine's parallel degrees (``tp``, and ``cp`` for
        context-parallel serving — 1 everywhere else), per-chip memory
        footprint (parallel/tensor_parallel.per_chip_bytes over the cache
        and the decode weights), and weight storage scheme (``quant``).
        Re-stamped at every emit point, so a stats object swapped in
        mid-run still reports them."""
        self._tp = int(tp)
        self._cp = int(cp)
        self._kv_bytes_per_chip = int(kv_bytes_per_chip)
        self._weight_bytes_per_chip = int(weight_bytes_per_chip)
        self._quant = str(quant)

    def set_compile(self, delta: dict) -> None:
        """Record the engine's compile accounting — a
        ``CompileTracker.delta`` dict (``n_compiled_programs``,
        ``compile_time_s``, ``by_site``).  The engine calls this with its
        construction→emission snapshot delta, so the figure is THIS
        engine's program family, not the process total."""
        self._compile = delta

    def add(self, req: Request) -> None:
        with self._lock:
            self._add_locked(req)

    def _add_locked(self, req: Request) -> None:
        self._n_requests += 1
        if req.status == "done":
            self._n_done += 1
        elif req.status == "cancelled":
            self._n_cancelled += 1
        elif req.status == "failed":
            self._n_failed += 1
        if req.engine_fault:
            self._n_engine_fault += 1
        self._tokens += len(req.generated)
        # sampling accounting (ISSUE 13): a request is "sampled" when its
        # own SamplingParams asked for temperature > 0 (engine-default
        # sampling is a construction knob, not per-request traffic mix);
        # NLL is recorded for EVERY generated token — greedy rows share
        # the raw-logits logprob convention, so the sketch is one
        # model-confidence distribution across the whole traffic
        if req.sampling is not None and req.sampling.sampled:
            self._n_sampled += 1
            self._temp_sum += float(req.sampling.temperature)
        for lp in req.logprobs:
            self._nll.record(-lp)
        self._n_logprob_tokens += len(req.logprobs)
        verdict = slo_verdict(req)
        if verdict is not None:
            self._slo_tracked += 1
            if verdict == "met":
                self._slo_met += 1
            else:
                self._slo_miss += 1
                # per-constraint attribution; a miss judged on neither
                # constraint (failed/cancelled before any verdict) counts
                # in slo_miss only
                if req.slo_ttft_ok is False:
                    self._slo_ttft_miss += 1
                if req.slo_tpot_ok is False:
                    self._slo_tpot_miss += 1
        if len(self.requests) < self.sample_cap:
            self.requests.append(req)
        else:
            j = self._rng.randrange(self._n_requests)
            if j < self.sample_cap:
                self.requests[j] = req
        if req.admit_t is not None:
            self._start_t = req.admit_t if self._start_t is None else min(
                self._start_t, req.admit_t)
        if req.finish_t is not None:
            self._end_t = req.finish_t if self._end_t is None else max(
                self._end_t, req.finish_t)

    def summary(self) -> dict:
        # counters are exact; ttft/latency percentiles are computed over
        # the bounded reservoir (exact below sample_cap)
        with self._lock:
            return self._summary_locked()

    def _summary_locked(self) -> dict:
        done = [r for r in self.requests if r.status == "done"]
        ttft = [r.first_token_t - r.submit_t for r in self.requests
                if r.first_token_t is not None]
        latency = [r.finish_t - r.submit_t for r in done
                   if r.finish_t is not None]
        window = (
            (self._end_t - self._start_t)
            if self._start_t is not None and self._end_t is not None
            and self._end_t > self._start_t else None
        )
        out = {
            "slots": self.slots,
            "role": self.role,
            "n_requests": self._n_requests,
            "n_done": self._n_done,
            "n_cancelled": self._n_cancelled,
            "n_failed": self._n_failed,
            "tokens_generated": int(self._tokens),
            "tokens_per_sec": (
                round(self._tokens / window, 3) if window else None
            ),
            "sample_cap": self.sample_cap,
            "percentile_samples": len(self.requests),
            # SLO / goodput (ISSUE 11): tracked = requests that declared
            # ≥1 SLO; goodput = SLO-met requests per busy-window second
            "slo_tracked": self._slo_tracked,
            "slo_met": self._slo_met,
            "slo_miss": self._slo_miss,
            "slo_ttft_miss": self._slo_ttft_miss,
            "slo_tpot_miss": self._slo_tpot_miss,
            "slo_met_rate": (
                round(self._slo_met / self._slo_tracked, 4)
                if self._slo_tracked > 0 else None
            ),
            "goodput_rps": (
                round(self._slo_met / window, 3)
                if window and self._slo_tracked > 0 else None
            ),
            "busy_s": round(self._busy_time, 6),
            "decode_steps": self._decode_steps,
            "slot_occupancy": (
                round(self._occ_time / (self._busy_time * self.slots), 4)
                if self._busy_time > 0 else None
            ),
            "decode_ahead": self.decode_ahead,
            "n_windows": self._windows,
            "window_dispatch_s": round(self._dispatch_time, 6),
            "window_readback_s": round(self._readback_time, 6),
            "window_steps": self._window_steps,
            "window_waste_steps": self._waste_steps,
            "window_waste_frac": (
                round(self._waste_steps / self._window_steps, 4)
                if self._window_steps > 0 else None
            ),
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "prefix_hit_rate": (
                round(self._prefix_hits
                      / (self._prefix_hits + self._prefix_misses), 4)
                if (self._prefix_hits + self._prefix_misses) > 0 else None
            ),
            "prefix_oversized": self._prefix_oversized,
            # speculative acceptance (all-zero/None on non-spec engines)
            "drafted_tokens": self._spec_drafted,
            "accepted_tokens": self._spec_accepted,
            "corrected_tokens": self._spec_corrected,
            "accept_rate": (
                round(self._spec_accepted / self._spec_drafted, 4)
                if self._spec_drafted > 0 else None
            ),
            "useful_tokens_per_window": (
                round((self._window_steps - self._waste_steps)
                      / self._windows, 4)
                if self._windows > 0 else None
            ),
            # per-request sampling (ISSUE 13; all-zero/None on greedy-only
            # traffic).  mean_temperature averages SAMPLED requests only —
            # folding greedy zeros in would report a fictitious lukewarm
            # cluster.  NLL percentiles stream from the sketch (no stored
            # per-token samples), None when no token recorded a logprob.
            "n_sampled_requests": self._n_sampled,
            "mean_temperature": (
                round(self._temp_sum / self._n_sampled, 4)
                if self._n_sampled > 0 else None
            ),
            "logprob_tokens": self._n_logprob_tokens,
            "nll_p50": self._nll.percentile(50),
            "nll_p95": self._nll.percentile(95),
            "nll_p99": self._nll.percentile(99),
            # paged KV pool (all-zero/None on dense engines)
            "kv_page_size": self._kv_page_size or None,
            "kv_pages_total": self._kv_pages_total,
            "kv_pages_live": self._kv_pages_live,
            "kv_pages_peak": self._kv_pages_peak,
            "kv_bytes_live": self._kv_pages_live * self._kv_page_bytes,
            "kv_bytes_peak": self._kv_pages_peak * self._kv_page_bytes,
            # tensor/context-parallel per-chip footprint (tp=cp=1 / None
            # until the engine stamps it — null, never NaN)
            "tp": self._tp,
            "cp": self._cp,
            "kv_bytes_per_chip": self._kv_bytes_per_chip,
            "weight_bytes_per_chip": self._weight_bytes_per_chip,
            "quant": self._quant,
            # radix prefix sharing (partial-prefix prefill skips)
            "radix_hits": self._radix_hits,
            "radix_misses": self._radix_misses,
            "radix_hit_tokens": self._radix_hit_tokens,
            "radix_hit_rate": (
                round(self._radix_hits
                      / (self._radix_hits + self._radix_misses), 4)
                if (self._radix_hits + self._radix_misses) > 0 else None
            ),
            # chunked prefill (ISSUE 14; all-zero/None on whole-prompt
            # engines).  chunk_stall_frac = share of busy time spent
            # inside chunk dispatches — the interleaving tax the bench
            # leg bounds.
            "n_prefill_chunks": self._prefill_chunks,
            "chunk_stall_s": round(self._chunk_stall_s, 6),
            "chunk_stall_frac": (
                round(self._chunk_stall_s / self._busy_time, 4)
                if self._busy_time > 0 and self._prefill_chunks > 0
                else None
            ),
            "longest_prompt_admitted": (
                self._longest_prompt if self._longest_prompt > 0 else None
            ),
            # compile accounting (None until set_compile — an engine that
            # never emitted stats has no delta to report)
            "n_compiled_programs": (
                self._compile["n_compiled_programs"]
                if self._compile is not None else None),
            "compile_time_s": (
                self._compile["compile_time_s"]
                if self._compile is not None else None),
            "compile_by_site": (
                self._compile["by_site"]
                if self._compile is not None else None),
        }
        for name, xs in (("ttft_s", ttft), ("latency_s", latency)):
            for k, v in percentiles(xs).items():
                out[f"{name}_{k}"] = v
        return out

    def vitals(self) -> dict:
        """Cheap live subset for the telemetry health sampler
        (utils/telemetry.Telemetry): counters and rates only, no
        percentile work, safe to call every sampling interval."""
        with self._lock:
            return self._vitals_locked()

    def _vitals_locked(self) -> dict:
        p_total = self._prefix_hits + self._prefix_misses
        r_total = self._radix_hits + self._radix_misses
        return {
            "n_requests": self._n_requests,
            "n_done": self._n_done,
            "n_cancelled": self._n_cancelled,
            "n_failed": self._n_failed,
            "tokens_generated": self._tokens,
            "prefix_hit_rate": (round(self._prefix_hits / p_total, 4)
                                if p_total > 0 else None),
            "radix_hit_rate": (round(self._radix_hits / r_total, 4)
                               if r_total > 0 else None),
            "accept_rate": (round(self._spec_accepted / self._spec_drafted, 4)
                            if self._spec_drafted > 0 else None),
            "n_sampled_requests": self._n_sampled,
            "n_prefill_chunks": self._prefill_chunks,
            "kv_pages_live": self._kv_pages_live,
            "kv_pages_total": self._kv_pages_total,
            "slo_tracked": self._slo_tracked,
            "slo_met": self._slo_met,
            "slo_miss": self._slo_miss,
        }

    def emit(self, writer: MetricWriter, kind: str = "serving") -> dict:
        return writer.write(kind, **self.summary())

    @classmethod
    def merge(cls, records: list["ServingStats"]) -> dict:
        """Cluster-level rollup over N engine records (the router's one
        ``router`` metric record — serving/router.py).

        Counters SUM; percentiles are recomputed over the MERGED request
        samples (a percentile of percentiles is not a percentile); every
        ratio is re-derived from merged numerator/denominator and is None
        — never NaN — when the denominator is zero, so the record stays
        strict-JSON.  ``kv_pages_peak`` sums per-engine peaks: an upper
        bound on the cluster's concurrent peak (per-engine peaks need not
        align in time).  ``per_engine`` carries each engine's own summary
        as a sub-record, so the rollup never hides a sick replica.

        Counters come from each record's EXACT counters; percentiles are
        recomputed over the union of the per-engine sample reservoirs
        (exact while every engine stayed below its ``sample_cap``).
        SLO counters sum and ``slo_met_rate``/``goodput_rps`` re-derive
        over the merged totals, so the cluster goodput is met-requests
        per second of the CLUSTER's busy window, not a mean of rates.

        Safe against LIVE records: every record's lock is held for the
        whole fold (the daemonized tier merges while pump threads are
        still retiring requests), so the rollup is a consistent snapshot
        — no counter is read mid-:meth:`add`.
        """
        with contextlib.ExitStack() as stack:
            # canonical acquisition order: two concurrent merges over
            # overlapping record sets can never deadlock (RLock, so a
            # duplicate record in the list re-enters harmlessly)
            for rec in sorted(records, key=id):
                stack.enter_context(rec._lock)
            return cls._merge_locked(records)

    @classmethod
    def _merge_locked(cls, records: list["ServingStats"]) -> dict:
        reqs = [r for rec in records for r in rec.requests]
        done = [r for r in reqs if r.status == "done"]
        ttft = [r.first_token_t - r.submit_t for r in reqs
                if r.first_token_t is not None]
        latency = [r.finish_t - r.submit_t for r in done
                   if r.finish_t is not None]
        n_tokens = sum(rec._tokens for rec in records)
        slo_tracked = sum(rec._slo_tracked for rec in records)
        slo_met = sum(rec._slo_met for rec in records)
        starts = [rec._start_t for rec in records if rec._start_t is not None]
        ends = [rec._end_t for rec in records if rec._end_t is not None]
        window = (max(ends) - min(starts)
                  if starts and ends and max(ends) > min(starts) else None)
        slots = sum(rec.slots for rec in records)
        busy_weighted = sum(rec._busy_time * rec.slots for rec in records)
        occ_time = sum(rec._occ_time for rec in records)
        w_steps = sum(rec._window_steps for rec in records)
        waste = sum(rec._waste_steps for rec in records)
        p_hits = sum(rec._prefix_hits for rec in records)
        p_miss = sum(rec._prefix_misses for rec in records)
        drafted = sum(rec._spec_drafted for rec in records)
        accepted = sum(rec._spec_accepted for rec in records)
        n_windows = sum(rec._windows for rec in records)
        r_hits = sum(rec._radix_hits for rec in records)
        r_miss = sum(rec._radix_misses for rec in records)
        compiled = [rec._compile for rec in records if rec._compile is not None]
        n_chunks = sum(rec._prefill_chunks for rec in records)
        chunk_stall = sum(rec._chunk_stall_s for rec in records)
        busy_total = sum(rec._busy_time for rec in records)
        longest = [rec._longest_prompt for rec in records
                   if rec._longest_prompt > 0]
        n_sampled = sum(rec._n_sampled for rec in records)
        temp_sum = sum(rec._temp_sum for rec in records)
        nll = HistogramSketch.merge([rec._nll for rec in records])
        # replicas hold DISJOINT chip groups (parallel/tensor_parallel.
        # tp_device_groups), so the cluster's per-chip figure is the worst
        # chip anywhere (max), the cluster total sums per_chip * tp * cp
        # per engine, and `tp`/`cp` report the common degree or None when
        # mixed (a heterogeneous-cp fleet is visible, never averaged)
        tps = {rec._tp for rec in records}
        cps = {rec._cp for rec in records}
        quants = {rec._quant for rec in records}
        stamped = [rec for rec in records
                   if rec._kv_bytes_per_chip is not None]
        out = {
            "n_engines": len(records),
            "slots": slots,
            "n_requests": sum(rec._n_requests for rec in records),
            "n_done": sum(rec._n_done for rec in records),
            "n_cancelled": sum(rec._n_cancelled for rec in records),
            "n_failed": sum(rec._n_failed for rec in records),
            "n_engine_fault": sum(rec._n_engine_fault for rec in records),
            "tokens_generated": int(n_tokens),
            "tokens_per_sec": (round(n_tokens / window, 3) if window else None),
            "percentile_samples": len(reqs),
            "slo_tracked": slo_tracked,
            "slo_met": slo_met,
            "slo_miss": sum(rec._slo_miss for rec in records),
            "slo_ttft_miss": sum(rec._slo_ttft_miss for rec in records),
            "slo_tpot_miss": sum(rec._slo_tpot_miss for rec in records),
            "slo_met_rate": (round(slo_met / slo_tracked, 4)
                             if slo_tracked > 0 else None),
            "goodput_rps": (round(slo_met / window, 3)
                            if window and slo_tracked > 0 else None),
            "busy_s": round(sum(rec._busy_time for rec in records), 6),
            "decode_steps": sum(rec._decode_steps for rec in records),
            "slot_occupancy": (round(occ_time / busy_weighted, 4)
                               if busy_weighted > 0 else None),
            "n_windows": n_windows,
            "window_dispatch_s": round(
                sum(rec._dispatch_time for rec in records), 6),
            "window_readback_s": round(
                sum(rec._readback_time for rec in records), 6),
            "window_steps": w_steps,
            "window_waste_steps": waste,
            "window_waste_frac": (round(waste / w_steps, 4)
                                  if w_steps > 0 else None),
            "prefix_hits": p_hits,
            "prefix_misses": p_miss,
            "prefix_hit_rate": (round(p_hits / (p_hits + p_miss), 4)
                                if (p_hits + p_miss) > 0 else None),
            "prefix_oversized": sum(rec._prefix_oversized for rec in records),
            # acceptance counters SUM; accept_rate re-derives over the
            # merged totals (a rate of rates overweights idle engines) and
            # stays None when nothing was drafted cluster-wide
            "drafted_tokens": drafted,
            "accepted_tokens": accepted,
            "corrected_tokens": sum(rec._spec_corrected for rec in records),
            "accept_rate": (round(accepted / drafted, 4)
                            if drafted > 0 else None),
            "useful_tokens_per_window": (
                round((w_steps - waste) / n_windows, 4)
                if n_windows > 0 else None),
            # sampling (ISSUE 13): counters sum, mean_temperature
            # re-derives over the merged sampled-request count (a mean of
            # means overweights idle engines), the NLL sketches merge
            # bucket-wise (HistogramSketch.merge) so cluster percentiles
            # come from one histogram, not a percentile of percentiles
            "n_sampled_requests": n_sampled,
            "mean_temperature": (round(temp_sum / n_sampled, 4)
                                 if n_sampled > 0 else None),
            "logprob_tokens": sum(rec._n_logprob_tokens for rec in records),
            "nll_p50": nll.percentile(50),
            "nll_p95": nll.percentile(95),
            "nll_p99": nll.percentile(99),
            "kv_pages_total": sum(rec._kv_pages_total for rec in records),
            "kv_pages_live": sum(rec._kv_pages_live for rec in records),
            "kv_pages_peak": sum(rec._kv_pages_peak for rec in records),
            "kv_bytes_live": sum(rec._kv_pages_live * rec._kv_page_bytes
                                 for rec in records),
            "kv_bytes_peak": sum(rec._kv_pages_peak * rec._kv_page_bytes
                                 for rec in records),
            "radix_hits": r_hits,
            "radix_misses": r_miss,
            "radix_hit_tokens": sum(rec._radix_hit_tokens for rec in records),
            "radix_hit_rate": (round(r_hits / (r_hits + r_miss), 4)
                               if (r_hits + r_miss) > 0 else None),
            # chunked prefill (ISSUE 14): counters sum, the stall fraction
            # re-derives over the merged busy time, and the longest prompt
            # is a cluster-wide max (None when no engine recorded one)
            "n_prefill_chunks": n_chunks,
            "chunk_stall_s": round(chunk_stall, 6),
            "chunk_stall_frac": (
                round(chunk_stall / busy_total, 4)
                if busy_total > 0 and n_chunks > 0 else None),
            "longest_prompt_admitted": (
                max(longest) if longest else None),
            "tp": tps.pop() if len(tps) == 1 else None,
            "cp": cps.pop() if len(cps) == 1 else None,
            # common scheme or None when replicas disagree (a mid-rollout
            # mixed fleet is visible, never silently averaged)
            "quant": quants.pop() if len(quants) == 1 else None,
            "kv_bytes_per_chip": (
                max(rec._kv_bytes_per_chip for rec in stamped)
                if stamped else None),
            "weight_bytes_per_chip": (
                max(rec._weight_bytes_per_chip for rec in stamped)
                if stamped else None),
            "kv_bytes_cluster": (
                sum(rec._kv_bytes_per_chip * rec._tp * rec._cp
                    for rec in stamped)
                if stamped else None),
            "weight_bytes_cluster": (
                sum(rec._weight_bytes_per_chip * rec._tp * rec._cp
                    for rec in stamped)
                if stamped else None),
            "n_compiled_programs": (
                sum(c["n_compiled_programs"] for c in compiled)
                if compiled else None),
            "compile_time_s": (
                round(sum(c["compile_time_s"] for c in compiled), 6)
                if compiled else None),
            "per_role": cls._role_rollups(records),
            "per_engine": [rec.summary() for rec in records],
        }
        for name, xs in (("ttft_s", ttft), ("latency_s", latency)):
            for k, v in percentiles(xs).items():
                out[f"{name}_{k}"] = v
        return out

    @classmethod
    def _role_rollups(cls, records: list["ServingStats"]) -> dict:
        """Per-role sub-rollups (ISSUE 16): group engine records by the
        serving role that produced them so a disaggregated tier's rollup
        separates prefill-side figures (chunk dispatches, radix skips,
        page pressure) from decode-side service latency.  TTFT/latency
        land where requests RETIRE — the decode side in a disaggregated
        tier — so the decode sub-rollup carries the user-visible
        percentiles plus TPOT (time-per-output-token over the post-first-
        token stretch), while the prefill sub-rollup shows the work that
        never retires a request locally.  A monolithic tier reports one
        ``"both"`` entry; every ratio/percentile is None — never NaN —
        when its denominator is empty (strict-JSON, like everything else
        in the record).  Callers hold every record's lock (``merge``).
        """
        out: dict[str, dict] = {}
        for role in sorted({rec.role for rec in records}):
            recs = [rec for rec in records if rec.role == role]
            reqs = [r for rec in recs for r in rec.requests]
            done = [r for r in reqs if r.status == "done"]
            ttft = [r.first_token_t - r.submit_t for r in reqs
                    if r.first_token_t is not None]
            tpot = [(r.finish_t - r.first_token_t) / (len(r.generated) - 1)
                    for r in done
                    if r.finish_t is not None and r.first_token_t is not None
                    and len(r.generated) > 1]
            sub = {
                "n_engines": len(recs),
                "n_requests": sum(rec._n_requests for rec in recs),
                "n_done": sum(rec._n_done for rec in recs),
                "tokens_generated": sum(rec._tokens for rec in recs),
                "busy_s": round(sum(rec._busy_time for rec in recs), 6),
                "n_prefill_chunks": sum(rec._prefill_chunks
                                        for rec in recs),
                "radix_hits": sum(rec._radix_hits for rec in recs),
                "radix_hit_tokens": sum(rec._radix_hit_tokens
                                        for rec in recs),
                "kv_pages_peak": sum(rec._kv_pages_peak for rec in recs),
            }
            for name, xs in (("ttft_s", ttft), ("tpot_s", tpot)):
                for k, v in percentiles(xs).items():
                    sub[f"{name}_{k}"] = v
            out[role] = sub
        return out

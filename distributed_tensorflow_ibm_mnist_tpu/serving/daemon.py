"""The daemonized serving tier: a long-lived, thread-safe service front.

The rebuilt tier so far is step-pumped — the router only moves when a
benchmark script calls :meth:`Router.step` — while the reference repo's
parameter-server deployment was an always-on SERVICE absorbing
asynchronous traffic (ROADMAP item 3; TF-Replicator, PAPERS.md
1902.00465, is the pattern reference for asynchronous replica
orchestration).  :class:`ServingDaemon` closes that gap: it wraps a
:class:`~..serving.router.Router` in a small set of threads so callers
``submit()`` from anywhere and tokens stream back while they do.

Thread topology (N replicas → N+3 threads)::

    callers ──submit()──▶ admission heap ──dispatcher──▶ router._dispatch
                                                             │ tier lock
    pump[i] ──engine.step()──▶ token callbacks ──▶ delivery queue
                                                             │
    delivery ──▶ per-request stream queues + user callbacks (in order)
    watchdog ──▶ liveness / orphan retry / completions / telemetry

* **Pumps** — one per replica, each driving ONE engine's
  ``step()`` loop, preserving the engine's single-threaded contract
  (engine.py §Thread model).  A pump that sees ``step()`` raise fails
  its replica over under the tier lock (harvest + re-dispatch to
  siblings — exactly :meth:`Router.step`'s isolation, minus the shared
  iteration) and exits; sibling pumps never stall.
* **Dispatcher** — drains the admission heap in policy order
  (serving/policies.py) into :meth:`Router._dispatch` under the tier
  lock.  Router-level ``QueueFull`` is absorbed here (the request waits
  in admission); only the ADMISSION bound surfaces to callers, so
  backpressure stays end-to-end bounded.
* **Delivery** — the single thread that crosses tokens back to callers.
  Pumps enqueue ``(request, token)`` onto one FIFO queue as callbacks
  fire; since one request's tokens are produced by one pump in order,
  and a failover re-dispatches only after the dead attempt's callbacks
  have stopped (harvest holds the tier lock), FIFO delivery preserves
  PER-REQUEST order end to end — and the router's delivered high-water
  mark (router.py) keeps replayed failover prefixes suppressed, so
  streams stay exactly-once.  User callbacks run HERE, not on pumps: a
  raising callback is counted and isolated, never a pump casualty.
* **Watchdog** — the external liveness check ``stall_timeout_s`` cannot
  provide: the engine's watchdog is judged INSIDE ``step()``, so a pump
  wedged mid-step (or parked by ``daemon-pump`` chaos) never trips it.
  The watchdog reads :attr:`InferenceEngine.heartbeat_t` from outside:
  a HEALTHY replica with work whose heartbeat stays frozen past
  ``liveness_timeout_s`` is declared wedged and failed over.  It also
  pumps prefill→decode handoffs on disaggregated tiers (below), retries
  router orphans, scans for completions when no pump is alive to, and
  ticks ``telemetry.maybe_sample()``.

Disaggregation (ISSUE 16): on a role-typed tier the watchdog drains the
prefill replicas' outboxes each tick (``Router._pump_handoffs`` under the
tier lock).  Landing a packet mutates the DESTINATION engine, whose pump
thread may be mid-``step()`` — so each replica's engine carries a daemon
lock: pumps hold their replica's lock around ``step()``, and the handoff
pump holds the destination's around ``admit_prefilled`` (installed via
``Router._admit_guard``).  Outbox appends/pops themselves are CPython
atomic deque ops, so the SOURCE side needs no lock beyond the tier's.

Locking: ONE tier lock serializes every router-level mutation (dispatch,
failover harvest, orphan retry, close) — the router itself stays
lock-free single-threaded code (router.py §docstring).  ``engine.step()``
runs OUTSIDE the tier lock (pumping is the hot path; CPython's atomic
``deque.append``/``popleft`` make the scheduler's queue safe to pop
while the dispatcher appends — scheduler.py §Thread model).  Stats and
telemetry objects carry their own locks (stats.py, telemetry.py).

Chaos: the ``daemon-pump`` site (utils/chaos.py) fires one event per
pump-thread activation — a pump consults it the FIRST time it finds work
to serve.  ``kind="wedge"`` parks the pump with its heartbeat frozen
(exercising the watchdog → failover path); any other kind raises in the
pump loop (an engine-wide fault, failed over like a real one).  Chaos
stays deterministic under threads because every site's event counter is
its own lock-ordered sequence (chaos.py §Concurrency).

Durability (ISSUE 18): ``journal=`` wires a write-ahead request journal
(serving/journal.py).  Submit WALs the full request identity before the
caller is acknowledged, the delivery thread appends the delivered
high-water after each token crosses, and the terminal event appends the
verdict — ``journal.recover()`` rebuilds a fresh tier from those three
record streams after a SIGKILL, replaying every incomplete request with
its prefix suppressed (streams are pure functions of their seed, so the
replay is token-identical).  All journal touches are nil-guarded like
chaos/telemetry: an unjournaled daemon pays nothing.

Lifecycle: ``start()`` spawns the threads; ``drain(timeout)`` stops
admission, waits for in-flight work to finish, then joins everything;
``close()`` after a clean drain leaves ``tracer.open_spans == 0`` and
every KV pool at refcount zero (pinned in tests/test_daemon.py).
Conservation is exact and exposed in :attr:`counters`::

    submitted == done + cancelled + failed + outstanding
    (+ rejected never entered the tier — raised back to the caller)
"""

from __future__ import annotations

import heapq
import queue
import threading
import time
from typing import Callable, Iterator

import numpy as np

from distributed_tensorflow_ibm_mnist_tpu.serving.policies import (
    AdmissionPolicy,
    FIFOPolicy,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.replica import (
    DRAINING,
    FAILED,
    HEALTHY,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.router import (
    NoHealthyReplica,
    Router,
)
from distributed_tensorflow_ibm_mnist_tpu.serving.scheduler import (
    QueueFull,
    request_fingerprint,
)
from distributed_tensorflow_ibm_mnist_tpu.utils.chaos import ChaosFault

_END = "end"
_TOK = "tok"


class DaemonRequest:
    """Thread-safe caller handle for one logical request.

    ``tokens``/``status``/``error`` are safe to read from any thread;
    they settle once :meth:`wait` (or the ``end`` event in
    :meth:`ServingDaemon.stream`) returns.  ``priority`` orders the
    admission heap under :class:`~.policies.PriorityPolicy`.
    """

    def __init__(self, did: int, prompt, max_new: int, *,
                 deadline_s: float | None, submit_t: float,
                 callback: Callable | None, priority: int = 0,
                 ttft_slo_s: float | None = None,
                 tpot_slo_s: float | None = None, sampling=None,
                 idempotency_key: str | None = None, resume_from: int = 0,
                 trace_ctx=None):
        self.id = did
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new = int(max_new)
        self.deadline_s = deadline_s
        self.submit_t = submit_t
        self.callback = callback        # runs on the DELIVERY thread
        self.priority = int(priority)
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.sampling = sampling
        # durability identity (serving/journal.py): the client's retry
        # key, the replay fingerprint the front door checks key reuse
        # against, and the delivered high-water this request resumed
        # past (0 for anything but a crash-recovered replay)
        self.idempotency_key = idempotency_key
        self.fingerprint: str | None = None
        self.resume_from = int(resume_from)
        # True when receipt is confirmed OUTSIDE the delivery callback
        # (the front door: tokens count as received only after the
        # drained socket write, which journals the high-water itself —
        # the delivery loop must not, or the mark would overstate)
        self.external_receipt = False
        # delivered-mark pacing (daemon-native requests): when the last
        # mark was journaled and at what logical length — submit() sets
        # the anchor so the first mark waits out a full interval
        self._hw_mark_t = 0.0
        self._hw_mark_n = 0
        # distributed tracing (utils/tracing.TraceContext): minted or
        # parsed at the front door, persisted in the journal's admitted
        # record, restored by recover() — the SAME trace id follows the
        # request across dispatch, failover, handoff, and crash replay
        self.trace_ctx = trace_ctx
        self._tspan: dict | None = None  # daemon-side span bookkeeping:
        #   {"root": daemon_request span, "admit": open admission-wait
        #   span or None, "tid": the request's daemon track}; None when
        #   untraced — every touch nil-guarded like the chaos hooks
        self.rr = None                  # RouterRequest once dispatched
        self.tokens: list[int] = []     # delivered tokens SINCE resume_from,
        #   in order (logical index of tokens[i] is resume_from + i)
        self.first_token_t: float | None = None
        # terminal state set by the daemon (delivery thread / close)
        self.final_status: str | None = None
        self.final_error: str | None = None
        self._events: queue.Queue = queue.Queue()   # stream() feed
        self._done = threading.Event()
        self._ended = False             # delivery-side end-once latch

    @property
    def status(self) -> str:
        if self.final_status is not None:
            return self.final_status
        return self.rr.status if self.rr is not None else "queued"

    @property
    def error(self) -> str | None:
        if self.final_error is not None:
            return self.final_error
        return self.rr.error if self.rr is not None else None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def overdue_at(self) -> float:
        return (np.inf if self.deadline_s is None
                else self.submit_t + self.deadline_s)

    @property
    def total_tokens(self) -> int:
        """LOGICAL stream length: the suppressed resumed prefix plus the
        tokens this process delivered — what the journal's delivered
        high-water and the SSE ``id:`` counter speak in."""
        return self.resume_from + len(self.tokens)

    def wait(self, timeout: float | None = None) -> bool:
        """Block until terminal (done/cancelled/failed); False on timeout."""
        return self._done.wait(timeout)


class ServingDaemon:
    """Own a :class:`Router` as a long-lived concurrent service.

    The router must be dedicated to this daemon once :meth:`start` runs
    (the daemon owns its pumping; callers go through :meth:`submit`).
    ``max_queue`` bounds the ADMISSION set — waiting + in-flight logical
    requests — and is the only bound callers see as :class:`QueueFull`.
    ``policy`` orders/sheds admission (default :class:`FIFOPolicy`).
    ``liveness_timeout_s`` is the watchdog's wedge deadline: a HEALTHY
    replica with work and a frozen heartbeat for this long fails over —
    set it above worst-case first-token latency (cold compiles!) or
    prewarm first.  ``chaos`` defaults to the router's injector.
    """

    def __init__(self, router: Router, *,
                 policy: AdmissionPolicy | None = None,
                 max_queue: int = 256,
                 liveness_timeout_s: float = 10.0,
                 watchdog_interval_s: float = 0.02,
                 idle_sleep_s: float = 0.0005,
                 telemetry=None, chaos=None, journal=None,
                 journal_hw_interval_s: float = 0.05):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if liveness_timeout_s <= 0:
            raise ValueError(
                f"liveness_timeout_s must be > 0, got {liveness_timeout_s}")
        if journal_hw_interval_s < 0:
            raise ValueError(
                f"journal_hw_interval_s must be >= 0, "
                f"got {journal_hw_interval_s}")
        self.router = router
        self.policy = policy if policy is not None else FIFOPolicy()
        self.max_queue = int(max_queue)
        self.liveness_timeout_s = float(liveness_timeout_s)
        self.watchdog_interval_s = float(watchdog_interval_s)
        self.idle_sleep_s = float(idle_sleep_s)
        self.clock = router.clock
        self._tracer = router._tracer
        self._chaos = chaos if chaos is not None else router._chaos
        self._telemetry = (telemetry if telemetry is not None
                           else router._telemetry)
        if self._telemetry is not None:
            self._telemetry.register_source("daemon", self._telemetry_vitals)
        # serving/journal.RequestJournal | None — the write-ahead request
        # journal (crash durability).  Nil-guarded like _chaos/_telemetry:
        # an unjournaled daemon pays zero instructions per submit/token.
        # Three journaling points: `admitted` WAL in submit() (before the
        # ack — a raising append fails the submit), `delivered` high-water
        # on the delivery thread AFTER each token crosses (the mark never
        # overstates what the client got), `retired` at the terminal
        # event.  The daemon owns the journal's lifecycle: close() syncs
        # and closes it, like it closes the router.
        self._journal = journal
        # delivered-mark pacing: a mark finer than the journal's flush
        # cadence adds ZERO durability — an unflushed mark does not
        # survive the crash either — so per-token marks just tax the
        # delivery thread.  Marks land at most every
        # journal_hw_interval_s per request (matched to the journal's
        # fsync_interval_s default), understating by at most one
        # interval of tokens: replay re-emits that suffix and SSE ids
        # dedup it.  The exact mark lands once, with the terminal.
        # 0 restores per-token marks.
        self.journal_hw_interval_s = float(journal_hw_interval_s)

        # the ONE lock for router-level mutations (module docstring)
        self._tier_lock = threading.RLock()
        # per-replica ENGINE locks (module docstring §Disaggregation):
        # a replica's pump holds its own around step(); the watchdog's
        # handoff pump holds the destination's around admit_prefilled.
        # Keyed by index — stable across respawns.
        self._engine_locks = {rep.index: threading.Lock()
                              for rep in router.replicas}
        router._admit_guard = lambda rep: self._engine_locks[rep.index]
        # admission: policy-ordered heap + its own condition variable
        self._adm_cv = threading.Condition()
        self._admission: list[tuple[tuple, DaemonRequest]] = []
        self._inflight: list[DaemonRequest] = []   # dispatched, not ended
        self._delivery_q: queue.Queue = queue.Queue()
        self._ids = 0
        self._counts_lock = threading.Lock()
        self.counters = {"submitted": 0, "rejected": 0,
                         "rejected_with_hint": 0, "done": 0,
                         "cancelled": 0, "failed": 0,
                         "delivered_tokens": 0, "callback_errors": 0,
                         "pump_faults": 0, "pump_wedges": 0,
                         "journal_errors": 0}
        self._work_since: dict[int, float] = {}    # watchdog anchors
        self._threads: list[threading.Thread] = []
        self._started = False
        self._draining = False
        self._stop = threading.Event()
        self._closed = False

    def _count(self, name: str, n: int = 1) -> None:
        with self._counts_lock:
            self.counters[name] += n

    def _reject(self, exc: QueueFull, queued: int) -> None:
        """Stamp the policy's backoff hint onto a rejection about to be
        raised and keep the books: ``rejected`` counts every rejection,
        ``rejected_with_hint`` the subset that carried a machine-readable
        estimate (the 429/503 Retry-After source).  Called under the
        admission lock — the depth the hint is computed at is exactly the
        depth the verdict was made at."""
        if getattr(exc, "retry_after_s", None) is None:
            try:
                exc.retry_after_s = self.policy.retry_after_s(queued)
            except Exception:
                exc.retry_after_s = None   # a sick policy never blocks a 429
        self._count("rejected")
        if exc.retry_after_s is not None:
            self._count("rejected_with_hint")

    # ------------------------------------------------------------------
    # caller API

    def submit(self, prompt, max_new: int, *, deadline_s: float | None = None,
               callback: Callable | None = None, priority: int = 0,
               ttft_slo_s: float | None = None,
               tpot_slo_s: float | None = None,
               sampling=None, idempotency_key: str | None = None,
               resume_from: int = 0, trace_ctx=None,
               trace_parent: int | None = None) -> DaemonRequest:
        """Thread-safe admission.  Raises :class:`QueueFull` at the
        admission bound, :class:`~.policies.SLOUnmeetable` when the
        policy sheds, ``RuntimeError`` after drain/close.  Every raised
        rejection carries ``retry_after_s`` — the policy's wait-predictor
        backoff hint (None when it has no basis), the machine-readable
        half of a 429/503 ``Retry-After`` header (ISSUE 17).  ``callback``
        (``cb(dr, tok)``) runs on the delivery thread, in stream order.

        ``idempotency_key`` rides into the journal so a recovered tier
        can rebind a client's retry; ``resume_from`` (crash recovery —
        serving/journal.py) suppresses the first ``resume_from`` tokens
        of the regenerated stream.  When a journal is wired, the
        ``admitted`` record lands BEFORE this method returns: a raising
        journal (:class:`~.journal.JournalWriteError`) means the request
        was never admitted — no ack without the WAL behind it.

        ``trace_ctx`` (utils/tracing.TraceContext) makes the request a
        member of a distributed trace: the daemon opens its own span
        lane, threads the context through the router to every engine
        attempt, and persists the traceparent in the journal's admitted
        record so a post-crash replay CONTINUES the same trace.
        ``trace_parent`` is the caller's span id in the shared tier
        tracer (the front door's http span) — the daemon span parents
        under it; when absent the daemon span records the context's
        ``parent_ctx`` hex edge instead, which is how a recovered
        process's spans join the original trace in a merged export."""
        if self._closed or self._draining:
            raise RuntimeError(
                "daemon is " + ("closed" if self._closed else "draining")
                + " — no new requests")
        if resume_from < 0:
            raise ValueError(f"resume_from must be >= 0, got {resume_from}")
        with self._adm_cv:
            # bound + policy verdict decided atomically with the insert,
            # so concurrent submitters cannot oversubscribe the bound
            queued = len(self._admission) + len(self._inflight)
            if queued >= self.max_queue:
                exc = QueueFull(
                    f"daemon admission queue at bound ({self.max_queue}) "
                    "— retry later or shed load")
                self._reject(exc, queued)
                raise exc
            try:
                dr_id = self._ids
                dr = DaemonRequest(dr_id, prompt, max_new,
                                   deadline_s=deadline_s,
                                   submit_t=self.clock(),
                                   callback=callback, priority=priority,
                                   ttft_slo_s=ttft_slo_s,
                                   tpot_slo_s=tpot_slo_s, sampling=sampling,
                                   idempotency_key=idempotency_key,
                                   resume_from=resume_from,
                                   trace_ctx=trace_ctx)
                self.policy.admit(dr, queued)
            except QueueFull as exc:
                self._reject(exc, queued)
                raise
            if self._journal is not None:
                # write-ahead: on disk before the caller hears "yes".  A
                # raising append propagates — the request was never
                # admitted, so nothing is lost and nothing is counted.
                dr.fingerprint = request_fingerprint(
                    dr.prompt, dr.max_new, dr.sampling)
                try:
                    self._journal.admitted(dr)
                except Exception:
                    self._count("journal_errors")
                    raise
                dr._hw_mark_t = self.clock()
            self._ids += 1
            if self._tracer is not None and trace_ctx is not None:
                # the request's daemon lane: root span for the whole
                # daemon-side lifetime, admit child for the admission
                # wait.  parent = the front door's span when the tracer
                # is shared; otherwise the W3C hex edge (parent_ctx)
                # joins this lane to the upstream span in a merged export
                ttid = self._tracer.track(f"dreq {dr.id}")
                kw = dict(trace=trace_ctx.trace_id,
                          sampled=trace_ctx.sampled, request=dr.id,
                          resume_from=resume_from)
                if trace_parent is None:
                    kw["parent_ctx"] = trace_ctx.span_id
                root = self._tracer.begin("daemon_request", cat="daemon",
                                          parent=trace_parent, tid=ttid,
                                          **kw)
                admit = self._tracer.begin("admit", cat="daemon",
                                           parent=root, tid=ttid)
                dr._tspan = {"root": root, "admit": admit, "tid": ttid}
            heapq.heappush(self._admission, (self.policy.key(dr), dr))
            self._count("submitted")
            self._adm_cv.notify()
        return dr

    def stream(self, dr: DaemonRequest,
               timeout: float | None = None) -> Iterator[int]:
        """Yield ``dr``'s tokens as they are delivered; returns at the
        terminal event.  ``timeout`` bounds the wait per event (raises
        ``queue.Empty`` — a liveness guard for tests)."""
        while True:
            kind, payload = dr._events.get(timeout=timeout)
            if kind == _TOK:
                yield payload
            else:
                return

    def cancel(self, dr: DaemonRequest,
               reason: str = "cancelled by caller") -> bool:
        """Cancel one request wherever it currently is (ISSUE 17 — the
        front door's client-disconnect path).  Returns False when ``dr``
        is already terminal, True when cancellation was initiated.

        Still waiting in admission: removed from the heap and ended
        ``cancelled`` immediately (it holds nothing).  Already in the
        tier: :meth:`Router.cancel` forces its deadline clocks into the
        past under the tier lock, so the next pump sweep retires it down
        the lapsed-deadline path — slot freed, KV pages freed, tracer
        span closed — and :meth:`_scan_completions` delivers the
        terminal event.  Conservation stays exact: the request counts
        ``cancelled``, never dropped."""
        if dr.done:
            return False
        # force the daemon-level clock first: whatever in-between state
        # the dispatcher has the request in (popped but not dispatched,
        # requeued after transient backpressure), its next overdue check
        # cancels it — there is no unguarded window
        dr.deadline_s = -1e18
        removed = False
        with self._adm_cv:
            for i, (_key, queued_dr) in enumerate(self._admission):
                if queued_dr is dr:
                    del self._admission[i]
                    heapq.heapify(self._admission)
                    removed = True
                    break
        if removed:
            self._end_request(dr, "cancelled", reason)
            return True
        with self._tier_lock:
            if dr.rr is not None and not dr.rr.done:
                self.router.cancel(dr.rr, reason=reason)
        return True

    @property
    def outstanding(self) -> int:
        with self._adm_cv:
            return len(self._admission) + len(self._inflight)

    def conservation(self) -> dict:
        """The exact-accounting check: every submitted request is
        terminal or still in the tier, and nothing is double-counted."""
        with self._counts_lock:
            c = dict(self.counters)
        c["outstanding"] = self.outstanding
        c["conserved"] = (c["submitted"] == c["done"] + c["cancelled"]
                          + c["failed"] + c["outstanding"])
        return c

    def summary(self) -> dict:
        """The service-level rollup: the router's cluster ``ServingStats``
        merge + router counters, with the daemon's front-door books
        (submitted/rejected/``rejected_with_hint``/conservation) folded in
        under ``"daemon"`` — rejections never reach engine stats (they
        never entered the tier), so this is where they surface."""
        out = self.router.summary()
        out["daemon"] = self.conservation()
        if self._journal is not None:
            out["journal"] = self._journal.stats()
        return out

    # ------------------------------------------------------------------
    # elastic capacity (ISSUE 17): the autoscaler's seam.  All three are
    # thread-safe; scale-ups become dispatchable the moment they return.

    def add_replica(self, role: str = "both"):
        """Scale-up: append one fresh replica (warm when the factory
        wires a persistent compile cache), give it an engine lock, and
        start its pump thread.  Returns the new
        :class:`~.replica.Replica`."""
        if self._closed:
            raise RuntimeError("daemon is closed")
        with self._tier_lock:
            rep = self.router.add_replica(role=role)
            self._engine_locks.setdefault(rep.index, threading.Lock())
        self._ensure_pump(rep)
        return rep

    def restart_replica(self, index: int) -> float:
        """Scale-up, warm path: respawn a retired (or failed) replica in
        place through :meth:`Router.restart` — the compile cache makes the
        bring-up a cache read, which is what bounds the scale-up TTFT
        penalty — and start a fresh pump for it.  Returns the measured
        bring-up seconds (the autoscaler's TTFT-penalty bound)."""
        if self._closed:
            raise RuntimeError("daemon is closed")
        with self._tier_lock:
            spawn_s = self.router.restart(index)
            rep = self.router.replicas[index]
            self._engine_locks.setdefault(rep.index, threading.Lock())
        self._ensure_pump(rep)
        return spawn_s

    def retire_replica(self, index: int) -> bool:
        """Scale-down, zero-drop: begin the drain (no new dispatches; the
        pump keeps serving what is in flight).  The watchdog closes the
        replica once idle (:meth:`Router.finish_retires`) and its pump
        exits.  False when the router refuses (replica not HEALTHY, or
        it is the last prefill/decode-capable capacity)."""
        with self._tier_lock:
            return self.router.begin_retire(index)

    def _ensure_pump(self, rep) -> None:
        """Start a pump thread for ``rep`` unless a live one exists.
        Before :meth:`start` this is a no-op — start() pumps every
        replica then in ``router.replicas``, scale-ups included."""
        if not self._started or self._stop.is_set():
            return
        name = f"dtm-pump-{rep.index}"
        if any(t.name == name and t.is_alive() for t in self._threads):
            return
        t = threading.Thread(target=self._pump, args=(rep,),
                             name=name, daemon=True)
        self._threads.append(t)
        t.start()

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> "ServingDaemon":
        if self._closed:
            raise RuntimeError("daemon is closed")
        if self._started:
            return self
        self._started = True
        for rep in self.router.replicas:
            t = threading.Thread(target=self._pump, args=(rep,),
                                 name=f"dtm-pump-{rep.index}", daemon=True)
            self._threads.append(t)
        self._threads.append(threading.Thread(
            target=self._dispatch_loop, name="dtm-dispatch", daemon=True))
        self._threads.append(threading.Thread(
            target=self._watchdog_loop, name="dtm-watchdog", daemon=True))
        self._delivery_thread = threading.Thread(
            target=self._delivery_loop, name="dtm-delivery", daemon=True)
        for t in self._threads:
            t.start()
        self._delivery_thread.start()
        return self

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admission, serve everything in flight, join the threads.
        Returns True when the tier drained clean within ``timeout``
        (False = work remained; :meth:`close` will cancel it)."""
        self._draining = True
        deadline = None if timeout is None else self.clock() + timeout
        clean = True
        while self.outstanding > 0:
            if deadline is not None and self.clock() > deadline:
                clean = False
                break
            if not self._live_pumps() and not self.router.healthy():
                clean = self.outstanding == 0   # dead tier: nothing will move
                break
            time.sleep(self.watchdog_interval_s)
        self._shutdown_threads()
        return clean and self.outstanding == 0

    def close(self) -> None:
        """Stop everything, cancel whatever :meth:`drain` left, close the
        router.  Idempotent; safe without a prior drain."""
        if self._closed:
            return
        self._draining = True
        self._shutdown_threads()
        self._closed = True
        with self._adm_cv:
            leftovers = [dr for _, dr in self._admission] + list(self._inflight)
            self._admission.clear()
            self._inflight.clear()
        for dr in leftovers:
            if not dr._done.is_set():
                dr.final_status = "cancelled"
                dr.final_error = "daemon closed with request outstanding"
                self._count("cancelled")
                if self._journal is not None:
                    # leftovers bypass the delivery queue (it is already
                    # joined) — journal their terminal verdict here so a
                    # clean close leaves zero incomplete entries
                    try:
                        self._journal.retired(dr.id, "cancelled",
                                              dr.final_error)
                    except Exception:
                        self._count("journal_errors")
                self._tr_close_dr(dr, "cancelled")
                dr._events.put((_END, "cancelled"))
                dr._done.set()
        with self._tier_lock:
            self.router.close()
        if self._journal is not None:
            try:
                self._journal.close()   # final flush + fsync
            except Exception:
                self._count("journal_errors")
        if self._telemetry is not None:
            self._telemetry.unregister_source("daemon")

    def __enter__(self) -> "ServingDaemon":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _shutdown_threads(self) -> None:
        if not self._started or self._stop.is_set():
            self._stop.set()
            return
        self._stop.set()
        with self._adm_cv:
            self._adm_cv.notify_all()
        for t in self._threads:
            t.join(timeout=5.0)
        # pumps are joined: no further token enqueues — the sentinel
        # lands after every token already delivered, so the delivery
        # thread drains the queue completely before exiting
        self._delivery_q.put(None)
        self._delivery_thread.join(timeout=5.0)
        self._scan_completions()   # finalize anything the pumps raced

    # ------------------------------------------------------------------
    # pump threads

    def _pump(self, rep) -> None:
        consulted = False
        while not self._stop.is_set():
            if rep.state == FAILED or not rep.alive:
                return
            if not rep.engine.has_work:
                if self._draining and self.outstanding == 0:
                    return
                time.sleep(self.idle_sleep_s)
                continue
            if not consulted and self._chaos is not None:
                # one daemon-pump event per pump activation, consulted
                # the FIRST time this pump finds work (mid-wave by
                # construction; chaos.py docstring)
                consulted = True
                event, spec = self._chaos.fire_event("daemon-pump")
                if spec is not None:
                    if spec.kind == "wedge":
                        self._count("pump_wedges")
                        self._park_wedged(rep)
                        return
                    self._fail_from_pump(
                        rep, ChaosFault("daemon-pump", spec.kind, event))
                    return
            try:
                with self._engine_locks[rep.index]:
                    # re-check under the engine lock: finish_retires()
                    # closes a drained replica's engine under this same
                    # lock, so a pump that raced the idle check must see
                    # the terminal state here, never step a closed engine
                    if rep.state == FAILED or not rep.alive:
                        return
                    rep.engine.step()
            except Exception as e:
                self._fail_from_pump(rep, e)
                return
            self._scan_completions()

    def _park_wedged(self, rep) -> None:
        """Chaos ``kind="wedge"``: stop stepping but stay alive, heartbeat
        frozen — exactly what a pump stuck in a hung device call looks
        like from outside.  The watchdog must notice and fail the replica
        over; the parked thread exits once it does (or on shutdown)."""
        if self._tracer is not None:
            self._tracer.instant("pump_wedged", cat="daemon", tid=rep.tid,
                                 replica=rep.index)
        while not self._stop.is_set() and rep.state != FAILED:
            time.sleep(self.idle_sleep_s)

    def _fail_from_pump(self, rep, exc: BaseException) -> None:
        self._count("pump_faults")
        with self._tier_lock:
            if rep.state != FAILED:
                try:
                    self.router._fail_replica(rep, exc)
                except Exception:
                    pass   # replica already marked FAILED (first statement)
        self._scan_completions()

    def _live_pumps(self) -> int:
        return sum(t.is_alive() for t in self._threads
                   if t.name.startswith("dtm-pump-"))

    # ------------------------------------------------------------------
    # dispatcher thread

    def _dispatch_loop(self) -> None:
        while True:
            with self._adm_cv:
                while not self._admission and not self._stop.is_set():
                    self._adm_cv.wait(timeout=0.05)
                if self._stop.is_set() and not self._admission:
                    return
                key, dr = heapq.heappop(self._admission)
            if self._stop.is_set() and self._closed:
                return
            requeue = False
            with self._tier_lock:
                if self.clock() > dr.overdue_at:
                    self._end_request(dr, "cancelled",
                                      "deadline lapsed in admission queue")
                    continue
                remaining = (None if dr.deadline_s is None
                             else dr.overdue_at - self.clock())
                try:
                    rr = self.router.submit(
                        dr.prompt, dr.max_new, deadline_s=remaining,
                        callback=self._delivery_cb(dr),
                        ttft_slo_s=dr.ttft_slo_s, tpot_slo_s=dr.tpot_slo_s,
                        sampling=dr.sampling, resume_from=dr.resume_from,
                        trace_ctx=dr.trace_ctx,
                        trace_parent=(dr._tspan["root"]
                                      if dr._tspan is not None else None))
                except QueueFull:
                    requeue = True   # transient: wait in admission
                except NoHealthyReplica:
                    if not self.router.healthy():
                        self._end_request(dr, "failed",
                                          "no healthy replica remained")
                        continue
                    requeue = True
                except RuntimeError as e:   # router closed under us
                    self._end_request(dr, "failed", str(e))
                    continue
                else:
                    dr.rr = rr
                    if self._tracer is not None and dr._tspan is not None \
                            and dr._tspan.get("admit") is not None:
                        self._tracer.end(dr._tspan["admit"])
                        dr._tspan["admit"] = None
                    with self._adm_cv:
                        self._inflight.append(dr)
            if requeue:
                with self._adm_cv:
                    heapq.heappush(self._admission, (key, dr))
                time.sleep(self.idle_sleep_s)   # let pumps free slots

    # ------------------------------------------------------------------
    # delivery thread

    def _delivery_cb(self, dr: DaemonRequest) -> Callable:
        def _cb(_rr, tok):
            # pump thread → FIFO queue; the router's high-water wrapper
            # already suppressed replayed failover prefixes before us
            self._delivery_q.put((_TOK, dr, int(tok)))
        return _cb

    def _delivery_loop(self) -> None:
        while True:
            item = self._delivery_q.get()
            if item is None:
                return
            kind, dr, payload = item
            if kind == _TOK:
                if dr._ended:
                    continue   # post-terminal stragglers are dropped
                if dr.first_token_t is None:
                    dr.first_token_t = self.clock()
                    try:
                        self.policy.note_first_token(
                            dr.first_token_t - dr.submit_t)
                    except Exception:
                        pass
                dr.tokens.append(payload)
                self._count("delivered_tokens")
                dr._events.put((_TOK, payload))
                if dr.callback is not None:
                    try:
                        dr.callback(dr, payload)
                    except Exception:
                        # a sick user callback must not kill delivery
                        self._count("callback_errors")
                if (self._journal is not None and not dr.external_receipt
                        and self.clock() - dr._hw_mark_t
                        >= self.journal_hw_interval_s):
                    # high-water AFTER the token crossed: the mark may
                    # UNDERstate what the client holds (crash in the
                    # seam, or the up-to-one-interval of tokens since
                    # the last paced mark → a few replayed tokens,
                    # deduped client-side by their SSE ids) but never
                    # overstates — replay can re-emit, it can never
                    # leave a gap.  A sick journal is counted, never a
                    # delivery casualty.  For front-door requests
                    # (external_receipt) the callback only ENQUEUES to
                    # the event loop — marking here would overstate, so
                    # the front door journals after each drained socket
                    # write instead (frontend.py).
                    dr._hw_mark_t = self.clock()
                    dr._hw_mark_n = dr.total_tokens
                    try:
                        self._journal.delivered(dr.id, dr.total_tokens)
                    except Exception:
                        self._count("journal_errors")
            else:
                if not dr._ended:
                    dr._ended = True
                    if self._journal is not None:
                        try:
                            if (not dr.external_receipt
                                    and dr.total_tokens > 0
                                    and dr.total_tokens != dr._hw_mark_n):
                                # the exact mark the pacing skipped — a
                                # cleanly-retired request always
                                # journals delivered == total
                                self._journal.delivered(
                                    dr.id, dr.total_tokens)
                            self._journal.retired(dr.id, payload, dr.error)
                        except Exception:
                            self._count("journal_errors")
                    self._tr_close_dr(dr, payload)
                    dr._events.put((_END, payload))
                    dr._done.set()

    def _tr_close_dr(self, dr: DaemonRequest, status: str) -> None:
        """Close the request's daemon spans at its terminal event —
        stamping the tail-keep signals (status / error / slo_miss /
        redispatch count) the export-time sampler reads."""
        if self._tracer is None or dr._tspan is None:
            return
        t, dr._tspan = dr._tspan, None
        if t.get("admit") is not None:
            self._tracer.end(t["admit"])
        kw: dict = {"status": status}
        error = dr.final_error if dr.final_error is not None else (
            dr.rr.error if dr.rr is not None else None)
        if error is not None:
            kw["error"] = error
        rr = dr.rr
        req = rr.req if rr is not None else None
        if req is not None and (req.slo_ttft_ok is False
                                or req.slo_tpot_ok is False):
            kw["slo_miss"] = True
        if rr is not None and rr.redispatches:
            kw["redispatches"] = rr.redispatches
        self._tracer.end(t["root"], **kw)

    def _end_request(self, dr: DaemonRequest, status: str,
                     error: str | None) -> None:
        """Terminal verdict for a request the ROUTER never finished (or
        never saw).  Counted once; the end event rides the delivery queue
        so it lands after any tokens already enqueued."""
        if dr.final_status is None and (dr.rr is None or not dr.rr.done):
            dr.final_status = status
            dr.final_error = error
        self._count(status if status in ("done", "cancelled", "failed")
                    else "failed")
        self._delivery_q.put((_END, dr, status))

    def _scan_completions(self) -> None:
        """Move router-terminal requests out of ``_inflight`` and enqueue
        their end events.  Runs on pumps and the watchdog; the claim is
        made under the admission lock so each request ends exactly once."""
        ended: list[DaemonRequest] = []
        with self._adm_cv:
            still: list[DaemonRequest] = []
            for dr in self._inflight:
                rr = dr.rr
                if rr is not None and rr.done:
                    ended.append(dr)
                else:
                    still.append(dr)
            self._inflight[:] = still
        for dr in ended:
            status = dr.status
            self._count(status if status in ("done", "cancelled", "failed")
                        else "failed")
            self._delivery_q.put((_END, dr, status))

    # ------------------------------------------------------------------
    # watchdog thread

    def _watchdog_loop(self) -> None:
        while not self._stop.is_set():
            self._scan_completions()
            with self._tier_lock:
                try:
                    self.router._pump_handoffs()
                except Exception:
                    pass   # a sick handoff pump must not kill the watchdog
                if self.router._retiring:
                    try:
                        self.router.finish_retires()
                    except Exception:
                        pass
                if self.router._orphans:
                    try:
                        self.router._retry_orphans()
                    except Exception:
                        pass
            self._check_liveness()
            if self._telemetry is not None:
                try:
                    self._telemetry.maybe_sample()
                except Exception:
                    pass
            self._stop.wait(self.watchdog_interval_s)

    def _check_liveness(self) -> None:
        """The external wedge detector (module docstring): a HEALTHY
        replica with work whose heartbeat has not moved for
        ``liveness_timeout_s`` — judged from OUTSIDE ``step()`` — is
        failed over even though its pump never returns."""
        now = self.clock()
        for rep in self.router.replicas:
            # retiring drains are watched too: a replica that wedges with
            # work mid-retire would stall the scale-down forever — failing
            # it over instead harvests its in-flight work (still zero-drop)
            watched = (rep.state == HEALTHY
                       or (rep.state == DRAINING
                           and rep.index in self.router._retiring))
            if not watched or not rep.alive:
                self._work_since.pop(rep.index, None)
                continue
            if not rep.engine.has_work:
                self._work_since.pop(rep.index, None)
                continue
            anchor = self._work_since.setdefault(rep.index, now)
            hb = rep.engine.heartbeat_t
            last = anchor if hb is None else max(hb, anchor)
            if now - last <= self.liveness_timeout_s:
                continue
            if self._tracer is not None:
                self._tracer.instant(
                    "pump_wedge_detected", cat="daemon", tid=rep.tid,
                    replica=rep.index,
                    frozen_s=round(now - last, 6))
            self._work_since.pop(rep.index, None)
            with self._tier_lock:
                if rep.state in (HEALTHY, DRAINING):
                    try:
                        self.router._fail_replica(rep, RuntimeError(
                            f"pump wedged: no progress for "
                            f"{now - last:.3f}s with work in flight"))
                    except Exception:
                        pass
            self._scan_completions()

    # ------------------------------------------------------------------
    # telemetry

    def _telemetry_vitals(self) -> dict:
        with self._counts_lock:
            c = dict(self.counters)
        with self._adm_cv:
            admission = len(self._admission)
            inflight = len(self._inflight)
        return {
            "policy": self.policy.name,
            "admission_depth": admission,
            "inflight": inflight,
            "live_pumps": self._live_pumps(),
            "draining": self._draining,
            **c,
        }

"""Failure detection + elastic recovery: preemption and divergence restart.

SURVEY.md §5 row 3: the reference's recovery story was K8s pod restart +
the chief's checkpoint.  TPU jobs are gang-scheduled, so the rebuild's
story is the same shape, made explicit and testable:

* :class:`PreemptionHandler` — catches SIGTERM/SIGINT (the TPU-VM
  maintenance-event signal path) and flips a flag the training loop polls
  between epochs; the Trainer then checkpoints and exits cleanly instead of
  dying mid-epoch.
* :func:`run_with_recovery` — supervision loop: build a Trainer, run it; on
  divergence (:class:`~...debug.TrainingDiverged`) or crash, rebuild and
  resume from the latest checkpoint, bounded by ``max_restarts``.  Note:
  replays are deterministic (same seed, same data order), so this recovers
  transient faults (a flaky hop, a bad host) — a divergence that is a pure
  function of the config (bad LR) will recur and exhaust ``max_restarts``;
  change the config, don't just restart.
"""

from __future__ import annotations

import signal
import threading
from typing import Any, Callable

from distributed_tensorflow_ibm_mnist_tpu.utils.debug import TrainingDiverged


class PreemptionHandler:
    """Flag-on-signal; install around the training loop.

    >>> with PreemptionHandler() as h:
    ...     trainer.fit(preemption=h)   # loop polls h.triggered
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._prev: dict[int, Any] = {}
        self._event = threading.Event()

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def trigger(self) -> None:
        """Manual trigger (tests, external schedulers)."""
        self._event.set()

    def _handle(self, signum, frame):
        self._event.set()

    def __enter__(self) -> "PreemptionHandler":
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


def run_with_recovery(
    make_trainer: Callable[[], Any],
    max_restarts: int = 2,
    on_restart: Callable[[int, BaseException], None] | None = None,
    preemption: PreemptionHandler | None = None,
) -> dict[str, Any]:
    """Run ``make_trainer().fit()`` with restart-from-checkpoint supervision.

    ``make_trainer`` must return a fresh Trainer whose config has a
    ``checkpoint_dir`` (the recovery anchor) — each retry constructs a new
    trainer with ``resume=True`` semantics forced, so it restarts from the
    last durable step rather than from scratch.  ``preemption`` (a
    :class:`PreemptionHandler`) is forwarded to every ``fit`` so SIGTERM
    still means checkpoint-and-exit under supervision.  Returns the final
    summary with a ``restarts`` count added.
    """
    attempt = 0
    while True:
        trainer = make_trainer()
        if attempt > 0:
            cfg = trainer.config
            if not cfg.checkpoint_dir:
                raise ValueError("run_with_recovery needs checkpoint_dir to resume")
            trainer.config = cfg.replace(resume=True)
        try:
            summary = trainer.fit(preemption=preemption)
            summary["restarts"] = attempt
            return summary
        except (TrainingDiverged, FloatingPointError) as e:
            attempt += 1
            if attempt > max_restarts:
                raise
            if on_restart is not None:
                on_restart(attempt, e)

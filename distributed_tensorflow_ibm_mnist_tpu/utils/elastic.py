"""Failure detection + elastic recovery: preemption and divergence restart.

SURVEY.md §5 row 3: the reference's recovery story was K8s pod restart +
the chief's checkpoint.  TPU jobs are gang-scheduled, so the rebuild's
story is the same shape, made explicit and testable:

* :class:`PreemptionHandler` — catches SIGTERM/SIGINT (the TPU-VM
  maintenance-event signal path) and flips a flag the training loop polls
  (between epochs, and every ``preempt_poll_every`` steps on the stream
  path); the Trainer then checkpoints and exits cleanly instead of dying
  mid-epoch.  Off the main thread (``signal.signal`` is main-thread-only)
  it degrades to manual-trigger-only with a warning instead of crashing.
* :func:`run_with_recovery` — supervision loop: build a Trainer, run it; on
  a RETRYABLE failure (divergence, FP errors, I/O faults — the set is
  configurable), rebuild and resume from the latest INTACT checkpoint,
  with exponential backoff (deterministic jitter), a restart budget that
  counts only restarts inside a sliding window (``restart_window_s`` —
  faults spread over weeks must not kill a month-long run), and a
  ``restart`` record through the trainer's MetricWriter so restarts are
  visible in the metrics log, not just in stderr.

Replays are deterministic: the resumed trainer derives each epoch's data
order from the ABSOLUTE epoch index (restored step // steps_per_epoch),
so a recovered run retraces exactly the trajectory the fault-free run
takes — the chaos soak (scripts/chaos_soak.py) asserts the final state is
bit-identical.  A failure that is a pure function of the config (bad LR)
will recur and exhaust the budget; change the config, don't just restart.
"""

from __future__ import annotations

import hashlib
import signal
import struct
import threading
import time
import warnings
from collections import deque
from typing import Any, Callable

from distributed_tensorflow_ibm_mnist_tpu.utils.debug import TrainingDiverged

# Retryable by default: divergence (restore + replay recovers transient
# numeric faults), FP traps, and I/O faults (OSError covers checkpoint
# read/write hiccups, data-loader errors, FileNotFoundError from a
# checkpoint dir whose every step was condemned).  ChaosFault and
# programming errors are deliberately NOT here.
DEFAULT_RETRYABLE: tuple[type[BaseException], ...] = (
    TrainingDiverged,
    FloatingPointError,
    OSError,
)


class PreemptionHandler:
    """Flag-on-signal; install around the training loop.

    >>> with PreemptionHandler() as h:
    ...     trainer.fit(preemption=h)   # loop polls h.triggered

    ``signal.signal`` only works on the main thread of the main
    interpreter; entered anywhere else (worker threads, some notebook/
    server harnesses) the handler degrades to MANUAL trigger only — a
    warning is emitted, :meth:`trigger` and :attr:`triggered` keep
    working, and no signal handlers are (un)installed.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = signals
        self._prev: dict[int, Any] = {}
        self._event = threading.Event()
        self.installed = False  # did signal handlers actually install?

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def trigger(self) -> None:
        """Manual trigger (tests, external schedulers, degraded mode)."""
        self._event.set()

    def _handle(self, signum, frame):
        self._event.set()

    def __enter__(self) -> "PreemptionHandler":
        try:
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._handle)
            self.installed = True
        except ValueError:
            # not the main thread: roll back whatever did install, degrade
            for s, prev in self._prev.items():
                signal.signal(s, prev)
            self._prev.clear()
            self.installed = False
            warnings.warn(
                "PreemptionHandler entered off the main thread: signal "
                "handlers cannot install (signal.signal is main-thread-"
                "only); degraded to manual trigger() only",
                RuntimeWarning,
                stacklevel=2,
            )
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self.installed = False


def _jitter(seed: int, attempt: int) -> float:
    """Deterministic jitter factor in [0.5, 1.0) — a pure function of
    (seed, attempt), so chaos replays back off identically."""
    h = hashlib.blake2b(struct.pack("<qq", seed, attempt), digest_size=8).digest()
    return 0.5 + 0.5 * (int.from_bytes(h, "little") / 2.0**64)


def run_with_recovery(
    make_trainer: Callable[[], Any],
    max_restarts: int = 2,
    on_restart: Callable[[int, BaseException], None] | None = None,
    preemption: PreemptionHandler | None = None,
    retryable: tuple[type[BaseException], ...] = DEFAULT_RETRYABLE,
    backoff_base_s: float = 0.25,
    backoff_max_s: float = 30.0,
    restart_window_s: float | None = None,
    jitter_seed: int = 0,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    tracer=None,
) -> dict[str, Any]:
    """Run ``make_trainer().fit()`` with restart-from-checkpoint supervision.

    ``make_trainer`` must return a fresh Trainer whose config has a
    ``checkpoint_dir`` (the recovery anchor) — each retry constructs a new
    trainer with ``resume=True`` forced, restores the latest INTACT
    checkpoint (torn/corrupt steps are walked past —
    ``CheckpointManager.restore_latest_intact``), and runs only the
    REMAINING epochs with the original data schedule, so the recovered
    trajectory is the fault-free trajectory.  ``preemption`` is forwarded
    to every ``fit`` so SIGTERM still means checkpoint-and-exit under
    supervision.

    Restart policy: an exception in ``retryable`` triggers a restart,
    after ``min(backoff_max_s, backoff_base_s * 2**(k-1)) * jitter``
    seconds (k = restarts counted INSIDE ``restart_window_s``; jitter is
    deterministic per (``jitter_seed``, attempt)).  Only restarts within
    the window count against ``max_restarts`` — with a window set, N
    faults spread over a month don't kill the run; without one
    (``None``), the budget is lifetime, as before.  Every restart writes a
    ``restart`` record (attempt, exception type, resume step, backoff)
    through the new trainer's MetricWriter.  Returns the final summary
    with a ``restarts`` count added.

    ``tracer`` (utils/tracing.Tracer | None, nil-guarded like every other
    hook): each restart lands as a ``restart`` instant (attempt, exception,
    resume step, backoff) on the timeline, correlated with the trainer's
    ``checkpoint_restore`` span — TOGETHER they are the recovery story a
    ``restart`` JSONL record alone can't tell (what the walk skipped, how
    long the restore took, where the replay resumed).
    """
    attempt = 0
    pending_restart: dict[str, Any] | None = None
    window: deque[float] = deque()
    while True:
        trainer = make_trainer()
        if tracer is not None and getattr(trainer, "_tracer", None) is None:
            # supervised trainers inherit the supervisor's tracer, so the
            # restore/epoch spans land on the same timeline as the restart
            # instants (a fresh trainer per attempt would otherwise trace
            # nowhere)
            trainer._tracer = tracer
        if attempt > 0:
            cfg = trainer.config
            if not cfg.checkpoint_dir:
                raise ValueError("run_with_recovery needs checkpoint_dir to resume")
            trainer.config = cfg.replace(resume=True)
            resume_step = 0
            if trainer._ckpt is not None and trainer._ckpt.latest_step() is not None:
                try:
                    resume_step = trainer.restore_checkpoint()
                except FileNotFoundError:
                    resume_step = 0  # every step condemned: restart fresh
            done_epochs = resume_step // trainer.steps_per_epoch
            if done_epochs:
                # continue-to-total: cfg.epochs is the TOTAL the caller asked
                # for; the resumed trainer runs only what is left (clamped to
                # 1 for the pathological fault-after-final-save case), and
                # fit()'s absolute-epoch data schedule picks up where the
                # restored step left off
                trainer.config = trainer.config.replace(
                    epochs=max(1, cfg.epochs - done_epochs)
                )
            if pending_restart is not None:
                trainer.writer.write(
                    "restart", step=resume_step,
                    attempt=attempt,
                    exception=pending_restart["exception"],
                    resume_step=resume_step,
                    backoff_s=pending_restart["backoff_s"],
                )
                if tracer is not None:
                    tracer.instant(
                        "restart", cat="elastic", attempt=attempt,
                        exception=pending_restart["exception"],
                        resume_step=resume_step,
                        backoff_s=pending_restart["backoff_s"])
                pending_restart = None
        try:
            summary = trainer.fit(preemption=preemption)
            summary["restarts"] = attempt
            return summary
        except tuple(retryable) as e:
            now = clock()
            if restart_window_s is not None:
                while window and now - window[0] > restart_window_s:
                    window.popleft()
            window.append(now)
            if len(window) > max_restarts:
                raise
            attempt += 1
            backoff = min(
                backoff_max_s, backoff_base_s * 2.0 ** (len(window) - 1)
            ) * _jitter(jitter_seed, attempt)
            pending_restart = {
                "exception": type(e).__name__,
                "backoff_s": round(backoff, 4),
            }
            if on_restart is not None:
                on_restart(attempt, e)
            if backoff > 0:
                sleep(backoff)
